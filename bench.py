"""Benchmark harness — measured numbers on the real chip.

Runs the BASELINE.md config-ladder shapes that fit one chip:

  * config 1/2 analogue: covering index build over a TPC-H-like
    ``lineitem`` (int64 key + date + payload), then an indexed point
    filter (FilterIndexRule serve path) vs the unindexed scan;
  * config 3 analogue: ``orders ⋈ lineitem`` via JoinIndexRule
    (co-bucketed, shuffle-free) vs the unindexed sort-merge join.

The Spark-CPU column of BASELINE.md cannot be produced here (the
reference is a JVM/Spark library; no Spark runtime in this image), so
``vs_baseline`` is the measured speedup of the indexed path over the
unindexed path *on the same chip* — the reference's own headline claim
(query acceleration from index-based plan rewrites) measured natively.

Prints exactly ONE JSON line on stdout; progress goes to stderr.

Env knobs: HS_BENCH_ROWS (lineitem rows, default 4M), HS_BENCH_REPS
(timing reps, default 5), HS_BENCH_BUCKETS (default 8).
HS_BENCH_STREAM_LADDER (out-of-core join rung rows, default
64M,256M; append 1000000000 for the opt-in 1B rung),
HS_BENCH_STREAM_MAX_BYTES (wave budget override),
HS_BENCH_STREAM_BASELINE_MAX (largest rung that also times the
materializing stream-off baseline, default 64M).
HS_RESIDENCY_WITNESS=<path> arms the runtime residency witness
(testing/residency_witness.py) for the whole run: per-site peak bytes +
RSS high-water land in the artifact AND in the headline JSON's
"residency" block, and ``hslint --witness <path>`` gates the run
against the ALLOC_SITES bound model (docs/static-analysis.md).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# NOTE: no JAX_PLATFORMS override — this must run on the real chip when
# one is attached (tests force cpu; the bench must not).


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def rss_hwm() -> int:
    """Process resident-set high-water mark in bytes (monotone over the
    process lifetime — a per-rung reading is the peak *so far*, so
    growth between rungs localizes which rung paid it)."""
    from hyperspace_tpu.testing.residency_witness import rss_high_water_bytes

    return rss_high_water_bytes()


def timeit(fn, reps: int):
    """{p50, iqr, n} over ``reps`` trials — the bench defends its own
    numbers: an anomalous trial (CPU contention, page-cache eviction)
    shows up as a wide IQR instead of silently skewing a bare median."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    q1, med, q3 = np.percentile(ts, [25, 50, 75])
    return {"p50": float(med), "iqr": float(q3 - q1), "n": reps}


def gen_data(tmp: str, n_items: int, n_orders: int, n_files: int = 8):
    rng = np.random.default_rng(7)
    items_dir = os.path.join(tmp, "lineitem")
    orders_dir = os.path.join(tmp, "orders")
    os.makedirs(items_dir)
    os.makedirs(orders_dir)
    # lineitem: key skewed across orders, date + qty + price payload
    l_orderkey = rng.integers(0, n_orders, n_items, dtype=np.int64)
    base_date = np.datetime64("1994-01-01")
    l_shipdate = base_date + rng.integers(0, 2400, n_items).astype("timedelta64[D]")
    l_quantity = rng.integers(1, 51, n_items, dtype=np.int64)
    l_extendedprice = rng.normal(30000, 8000, n_items)
    # Rows are laid out in ship-date order before slicing into files, the
    # natural layout of an append-mostly fact table (each file ≈ a date
    # window). This gives the data-skipping bench real per-file min/max
    # ranges to prune; l_orderkey stays uniform within every file, so the
    # key-based filter/join benches are unaffected.
    ship_order = np.argsort(l_shipdate, kind="stable")
    items = pa.table(
        {
            "l_orderkey": l_orderkey[ship_order],
            "l_shipdate": pa.array(
                l_shipdate[ship_order].astype("datetime64[D]")
            ),
            "l_quantity": l_quantity[ship_order],
            "l_extendedprice": l_extendedprice[ship_order],
        }
    )
    o_orderkey = np.arange(n_orders, dtype=np.int64)
    orders = pa.table(
        {
            "o_orderkey": o_orderkey,
            "o_custkey": rng.integers(0, max(n_orders // 10, 1), n_orders),
            "o_totalprice": rng.normal(150000, 30000, n_orders),
        }
    )
    for i in range(n_files):
        lo, hi = i * n_items // n_files, (i + 1) * n_items // n_files
        pq.write_table(items.slice(lo, hi - lo), os.path.join(items_dir, f"part{i}.parquet"))
        lo, hi = i * n_orders // n_files, (i + 1) * n_orders // n_files
        pq.write_table(orders.slice(lo, hi - lo), os.path.join(orders_dir, f"part{i}.parquet"))
    return items_dir, orders_dir


def main() -> None:
    n_items = int(os.environ.get("HS_BENCH_ROWS", 4_000_000))
    n_orders = max(n_items // 8, 1)
    reps = int(os.environ.get("HS_BENCH_REPS", 5))
    num_buckets = int(os.environ.get("HS_BENCH_BUCKETS", 8))

    # HS_BENCH_FORCE_CPU_DEVICES=n: simulate an n-device CPU mesh (the
    # smoke uses 8 so the mesh ladder rows exercise the sharded tail on
    # every CI pass). Must be set before the jax backend initializes; no
    # effect unless requested — a real chip keeps its real devices.
    force_dev = os.environ.get("HS_BENCH_FORCE_CPU_DEVICES")
    if force_dev:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={int(force_dev)}"
            ).strip()

    import jax

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.hyperspace import Hyperspace
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig
    from hyperspace_tpu.session import HyperspaceSession

    platform = jax.devices()[0].platform
    log(f"bench: devices={jax.devices()} rows={n_items:,} buckets={num_buckets}")

    tmp = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        items_dir, orders_dir = gen_data(tmp, n_items, n_orders)
        session = HyperspaceSession()
        session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(tmp, "indexes"))
        session.conf.set(C.INDEX_NUM_BUCKETS, num_buckets)
        hs = Hyperspace(session)
        items = session.read.parquet(items_dir)
        orders = session.read.parquet(orders_dir)

        # One-time per-MACHINE setup, not per-process: the native kernel
        # compile caches a .so next to its source (like a C extension
        # built at install time). Keep it out of the cold-build timer,
        # which measures fresh-process build cost.
        from hyperspace_tpu import native

        native.load()

        # HS_RESIDENCY_WITNESS=<path>: wrap every ALLOC_SITES-registered
        # allocation site for the whole run and dump per-site peak bytes
        # + RSS high-water into the artifact at the end; bench_smoke then
        # gates `hslint --witness` on it (zero model gaps, zero
        # bound-class violations). Armed before any workload so the
        # witness sees the cold path too.
        residency_art = os.environ.get("HS_RESIDENCY_WITNESS")
        if residency_art:
            from hyperspace_tpu.testing import residency_witness

            residency_witness.install()

        # --- index build (cold = includes XLA compile; warm = steady state)
        cfg_l = CoveringIndexConfig(
            "l_idx", ["l_orderkey"], ["l_shipdate", "l_quantity", "l_extendedprice"]
        )
        t0 = time.perf_counter()
        hs.create_index(items, cfg_l)
        build_cold = time.perf_counter() - t0
        hs.delete_index("l_idx")
        hs.vacuum_index("l_idx")
        session.index_manager.clear_cache()
        t0 = time.perf_counter()
        hs.create_index(items, cfg_l)
        build_warm = time.perf_counter() - t0
        from hyperspace_tpu.indexes.covering_build import last_build_breakdown

        breakdown = {k: round(v, 3) for k, v in last_build_breakdown.items()}
        staged = sum(breakdown.values())
        breakdown["other"] = round(max(build_warm - staged, 0.0), 3)
        log(
            f"build lineitem index: cold {build_cold:.2f}s, warm {build_warm:.2f}s "
            f"({n_items / build_warm:,.0f} rows/s warm); stages: {breakdown}"
        )
        cfg_o = CoveringIndexConfig("o_idx", ["o_orderkey"], ["o_custkey", "o_totalprice"])
        hs.create_index(orders, cfg_o)

        # --- point filter (FilterIndexRule serve path, bucket-pruned)
        session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        key = int(n_orders // 3)

        def q_filter(df):
            return df.filter(df["l_orderkey"] == key).select(
                "l_orderkey", "l_shipdate", "l_quantity"
            )

        session.enable_hyperspace()
        plan = q_filter(items).explain()
        if "Hyperspace(Type: CI" not in plan:
            log(f"WARNING: filter not index-served:\n{plan}")
        indexed_rows = q_filter(items).collect().num_rows  # warmup + sanity
        filter_idx = timeit(lambda: q_filter(items).collect(), reps)
        session.disable_hyperspace()
        base_rows = q_filter(items).collect().num_rows
        assert base_rows == indexed_rows, (base_rows, indexed_rows)
        filter_raw = timeit(lambda: q_filter(items).collect(), reps)
        log(
            f"point filter p50: indexed {filter_idx['p50'] * 1e3:.1f}ms vs "
            f"unindexed {filter_raw['p50'] * 1e3:.1f}ms "
            f"({filter_raw['p50'] / filter_idx['p50']:.2f}x)"
        )

        # --- fused serve-pipeline compiler (filter→aggregate;
        # docs/serve-compiler.md): interleaved A/B of the fused native
        # pass vs the interpreted chain
        # (hyperspace.serve.fusedpipeline.enabled on/off within one
        # process, so page-cache/allocator drift hits both legs). The
        # dispatch threshold is pinned low FOR THIS SECTION only: the
        # A/B measures fused-vs-interpreted, not the calibrated
        # crossover (which would route tiny smoke runs to the
        # interpreted chain on both legs and measure nothing).
        from hyperspace_tpu import functions as hsf
        from hyperspace_tpu.execution import pipeline_compiler as _pc

        _fused_min_saved = _pc._NATIVE_FUSED_PIPELINE_MIN_ROWS
        _pc._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1 << 10
        agg_lo = n_orders // 4
        agg_hi = agg_lo + max(n_orders // 8, 1)

        def q_fagg(df):
            return df.filter(
                (df["l_orderkey"] >= agg_lo) & (df["l_orderkey"] < agg_hi)
            ).agg(
                hsf.count().alias("n"),
                hsf.sum("l_extendedprice").alias("rev"),
                hsf.min("l_quantity").alias("qmin"),
                hsf.max("l_quantity").alias("qmax"),
            )

        def q_gagg(df):
            return (
                df.filter(
                    (df["l_orderkey"] >= agg_lo) & (df["l_orderkey"] < agg_hi)
                )
                .group_by("l_quantity")
                .agg(
                    hsf.count().alias("n"),
                    hsf.sum("l_extendedprice").alias("rev"),
                )
            )

        def _ab_stats(ts):
            q1, med, q3 = np.percentile(ts, [25, 50, 75])
            return {"p50": float(med), "iqr": float(q3 - q1), "n": len(ts)}

        def ab_fused(q):
            # reset the telemetry BEFORE the warm run: a silent fused
            # fallback must read as fused_ran=False, not inherit the
            # previous query's stats (the smoke gate depends on this)
            _pc.last_fused_stats = {}
            q(items).collect()  # warm (and capture the fused telemetry)
            stats = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in _pc.last_fused_stats.items()
            }
            t_on, t_off = [], []
            rows_on = rows_off = None
            for _ in range(reps):
                t0 = time.perf_counter()
                rows_on = q(items).collect().num_rows
                t_on.append(time.perf_counter() - t0)
                session.conf.set(C.SERVE_FUSEDPIPELINE_ENABLED, False)
                t0 = time.perf_counter()
                rows_off = q(items).collect().num_rows
                t_off.append(time.perf_counter() - t0)
                session.conf.unset(C.SERVE_FUSEDPIPELINE_ENABLED)
            assert rows_on == rows_off, (rows_on, rows_off)
            return _ab_stats(t_on), _ab_stats(t_off), stats

        session.enable_hyperspace()
        plan = q_fagg(items).explain()
        if "Hyperspace(Type: CI" not in plan:
            log(f"WARNING: filter-aggregate not index-served:\n{plan}")
        fagg_on, fagg_off, fagg_stats = ab_fused(q_fagg)
        gagg_on, gagg_off, gagg_stats = ab_fused(q_gagg)
        _pc._NATIVE_FUSED_PIPELINE_MIN_ROWS = _fused_min_saved
        session.disable_hyperspace()
        log(
            "filter→aggregate p50: fused "
            f"{fagg_on['p50'] * 1e3:.1f}ms vs interpreted "
            f"{fagg_off['p50'] * 1e3:.1f}ms "
            f"({fagg_off['p50'] / fagg_on['p50']:.2f}x); "
            f"scanned {fagg_stats.get('rows_scanned', 0):,} rows, "
            f"passed {fagg_stats.get('rows_passed', 0):,}, fused "
            f"materialized {fagg_stats.get('rows_materialized', 0):,} "
            "(interpreted materializes every passing row per column)"
        )
        log(
            "grouped-aggregate p50: fused "
            f"{gagg_on['p50'] * 1e3:.1f}ms vs interpreted "
            f"{gagg_off['p50'] * 1e3:.1f}ms "
            f"({gagg_off['p50'] / gagg_on['p50']:.2f}x); "
            f"{gagg_stats.get('groups', 0)} groups over "
            f"{gagg_stats.get('rows_passed', 0):,} passing rows"
        )

        # --- aggregate index plane (docs/agg-serve.md): a fully-covered
        # grouped point aggregate answered from the _aggstate sidecar
        # with ZERO parquet row groups read, A/B'd interleaved against
        # the fused pass (hyperspace.index.agg.enabled off forces the
        # PR 7 path on the SAME plan); then the sampling plane's
        # approximate COUNT/SUM vs exact. The dedicated single-column
        # z-order index keeps row groups range-sorted on the filter key
        # so whole-row-group coverage is real, not a bucket accident.
        from hyperspace_tpu.indexes.zorder import (
            ZOrderCoveringIndexConfig as _ZCfg,
        )

        hs.create_index(
            items,
            _ZCfg("agg_idx", ["l_orderkey"], ["l_quantity", "l_extendedprice"]),
        )

        def q_meta(df):
            return (
                df.filter(df["l_orderkey"] >= 0)
                .group_by("l_quantity")
                .agg(
                    hsf.count().alias("n"),
                    hsf.min("l_orderkey").alias("kmin"),
                    hsf.max("l_orderkey").alias("kmax"),
                    hsf.sum("l_orderkey").alias("ksum"),
                )
            )

        _pc._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1 << 10
        session.enable_hyperspace()
        _pc.last_aggplane_stats = {}
        meta_rows = q_meta(items).collect().num_rows
        meta_stats = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in _pc.last_aggplane_stats.items()
        }
        t_meta, t_fused_ab = [], []
        rows_a = rows_b = None
        for _ in range(reps):
            t0 = time.perf_counter()
            rows_a = q_meta(items).collect().num_rows
            t_meta.append(time.perf_counter() - t0)
            session.conf.set(C.INDEX_AGG_ENABLED, False)
            t0 = time.perf_counter()
            rows_b = q_meta(items).collect().num_rows
            t_fused_ab.append(time.perf_counter() - t0)
            session.conf.unset(C.INDEX_AGG_ENABLED)
        assert rows_a == rows_b == meta_rows, (rows_a, rows_b, meta_rows)
        meta_ab = (_ab_stats(t_meta), _ab_stats(t_fused_ab))
        log(
            "agg-metadata p50: sidecar "
            f"{meta_ab[0]['p50'] * 1e3:.2f}ms vs fused "
            f"{meta_ab[1]['p50'] * 1e3:.2f}ms "
            f"({meta_ab[1]['p50'] / meta_ab[0]['p50']:.1f}x); "
            f"{meta_stats.get('row_groups_metadata', 0)}/"
            f"{meta_stats.get('row_groups_total', 0)} row groups from "
            f"metadata, {meta_stats.get('rows_scanned', 0)} rows read"
        )

        # approximate plane: bounded-error COUNT/SUM from the stratified
        # sample (explicit opt-in; exact collect() is never substituted)
        from hyperspace_tpu.execution import approx_exec as _apx

        session.conf.set(C.SERVE_APPROX_ENABLED, True)
        q_apx = items.filter(
            (items["l_orderkey"] >= agg_lo) & (items["l_orderkey"] < agg_hi)
        ).agg(hsf.count().alias("n"), hsf.sum("l_quantity").alias("sq"))
        est = q_apx.collect_approx(max_rel_error=1.0)
        t_apx = timeit(
            lambda: q_apx.collect_approx(max_rel_error=1.0), reps
        )
        t_exact = timeit(lambda: q_apx.collect(), reps)
        truth = q_apx.collect()
        e = est.to_pydict()
        tn = truth.column("n").to_pylist()[0]
        ts_ = truth.column("sq").to_pylist()[0]
        apx_stats = dict(_apx.last_approx_stats)
        n_in_ci = bool(e["n_lo"][0] <= tn <= e["n_hi"][0])
        s_in_ci = bool(e["sq_lo"][0] <= ts_ <= e["sq_hi"][0])
        n_err = abs(e["n"][0] - tn) / max(tn, 1)
        log(
            f"agg-approx p50: estimate {t_apx['p50'] * 1e3:.2f}ms vs exact "
            f"{t_exact['p50'] * 1e3:.2f}ms; COUNT rel err {n_err:.4f} "
            f"(bound held: n={n_in_ci}, sum={s_in_ci}; "
            f"{apx_stats.get('sample_rows', 0):,} sampled of "
            f"{apx_stats.get('population_rows', 0):,} rows)"
        )
        session.conf.unset(C.SERVE_APPROX_ENABLED)
        _pc._NATIVE_FUSED_PIPELINE_MIN_ROWS = _fused_min_saved
        session.disable_hyperspace()
        hs.delete_index("agg_idx")
        hs.vacuum_index("agg_idx")
        session.index_manager.clear_cache()

        # --- indexed join (JoinIndexRule, co-bucketed, shuffle-free)
        def q_join(o, i):
            return o.join(i, on=o["o_orderkey"] == i["l_orderkey"]).select(
                "o_orderkey", "o_custkey", "l_quantity"
            )

        session.enable_hyperspace()
        plan = q_join(orders, items).explain()
        if plan.count("Hyperspace(Type: CI") != 2:
            log(f"WARNING: join not index-served on both sides:\n{plan}")
        j_rows = q_join(orders, items).collect().num_rows
        join_idx = timeit(lambda: q_join(orders, items).collect(), reps)
        # per-stage serve breakdown of the LAST uncached run (busy time;
        # stages overlap under the pipelined serve, so they can sum past
        # the p50 wall — the overlapped excess is the pipeline win)
        from hyperspace_tpu.execution import join_exec

        join_stages = {
            k: round(v * 1e3, 2)
            for k, v in join_exec.last_serve_breakdown.items()
        }
        log(f"join serve stages (last uncached run, busy ms): {join_stages}")
        session.disable_hyperspace()
        jb_rows = q_join(orders, items).collect().num_rows
        assert j_rows == jb_rows, (j_rows, jb_rows)
        join_raw = timeit(lambda: q_join(orders, items).collect(), reps)
        log(
            f"join p50: indexed {join_idx['p50'] * 1e3:.1f}ms vs "
            f"unindexed {join_raw['p50'] * 1e3:.1f}ms "
            f"({join_raw['p50'] / join_idx['p50']:.2f}x)"
        )

        # --- serve-server mode: the same queries with the serve cache on
        # (hyperspace.serve.cache.enabled): decoded index data stays in
        # RAM between queries, so a warm serve pays only match/mask work.
        # Results stay differential-checked against the uncached serve.
        session.enable_hyperspace()
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        assert q_filter(items).collect().num_rows == indexed_rows  # warm
        filter_cached = timeit(lambda: q_filter(items).collect(), reps)
        assert q_join(orders, items).collect().num_rows == j_rows  # warm
        join_cached = timeit(lambda: q_join(orders, items).collect(), reps)
        cache = session.serve_cache
        log(
            f"serve-server (cached): filter {filter_cached['p50'] * 1e3:.2f}ms "
            f"({filter_raw['p50'] / filter_cached['p50']:.1f}x), "
            f"join {join_cached['p50'] * 1e3:.1f}ms "
            f"({join_raw['p50'] / join_cached['p50']:.2f}x); "
            f"{cache.resident_bytes / 1e6:.0f}MB resident"
        )
        # --- concurrent serve frontend (serve/frontend.py): the
        # contention ladder — the SAME indexed point workload at 1/8/64
        # clients through the admission-controlled frontend (snapshot
        # pinning, single-flight, shedding). p50/p99 are client-observed;
        # QPS counts completed queries over the rung's wall clock. Keys
        # cycle a 256-key working set so the serve cache is exercised
        # (warm hits) without single-flight collapsing the whole rung
        # into one execution.
        from hyperspace_tpu.serve import ServeFrontend
        from hyperspace_tpu.testing import faults as _flt

        rng_k = np.random.default_rng(23)
        ladder_keys = [
            int(k) for k in rng_k.integers(0, n_orders, 256)
        ]

        def q_point_k(k):
            return items.filter(items["l_orderkey"] == k).select(
                "l_orderkey", "l_quantity"
            )

        def serve_rung(clients, queries_per_client=8):
            session.clear_serve_cache()
            fe = ServeFrontend(session)
            lats, errors = [], []
            lat_lock = threading.Lock()

            def client(ci):
                try:
                    for j in range(queries_per_client):
                        k = ladder_keys[
                            (ci * queries_per_client + j) % len(ladder_keys)
                        ]
                        t0 = time.perf_counter()
                        fe.serve(q_point_k(k))
                        dt = time.perf_counter() - t0
                        with lat_lock:
                            lats.append(dt)
                except Exception as exc:
                    errors.append(exc)

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=client, args=(ci,))
                for ci in range(clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            cache = session.serve_cache
            stats = fe.stats()
            fe.close()
            assert not errors, errors[:3]
            assert cache.high_water_bytes <= cache.max_bytes
            lats.sort()
            return {
                "clients": clients,
                "queries": len(lats),
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
                "p99_ms": round(
                    lats[min(len(lats) - 1, len(lats) * 99 // 100)] * 1e3, 2
                ),
                "qps": round(len(lats) / wall, 1),
                "cache_high_water_bytes": cache.high_water_bytes,
                "cache_max_bytes": cache.max_bytes,
                "deduped": stats["deduped"],
                "shed": stats["shed"],
                "retries": stats["retries"],
            }

        serve_concurrency = []
        for clients in (1, 8, 64):
            row = serve_rung(clients)
            serve_concurrency.append(row)
            log(
                f"serve frontend {clients:>2} clients: p50 {row['p50_ms']}ms "
                f"p99 {row['p99_ms']}ms {row['qps']} qps "
                f"(deduped {row['deduped']}, cache high-water "
                f"{row['cache_high_water_bytes'] / 1e6:.0f}MB)"
            )

        # --- obs plane A/B (hyperspace_tpu/obs/, docs/observability.md):
        # the SAME 8-client rung with tracing+querylog ON vs OFF,
        # interleaved on/off/on/off so drift hits both legs equally.
        # The on legs additionally prove the structural contract
        # bench_smoke.sh gates on: every EXECUTION yields exactly one
        # root span, and the querylog gains exactly one schema-valid
        # row per execution (deduped submits share the winner's trace).
        from hyperspace_tpu.obs import querylog as _oql
        from hyperspace_tpu.obs import trace as _otr

        obs_dir = _oql.obs_root(session.conf)
        obs_legs = {"on": [], "off": []}
        obs_roots = obs_rows_written = obs_executions = 0
        session.conf.set(C.OBS_TRACE_RETAIN, 4096)
        for leg in ("on", "off", "on", "off"):
            session.conf.set(C.OBS_ENABLED, leg == "on")
            _otr.reset()
            rows_before = len(_oql.read_records(obs_dir))
            row = serve_rung(8)
            obs_legs[leg].append(row)
            if leg == "on":
                executions = row["queries"] - row["deduped"]
                roots = _otr.finished("serve.query")
                all_rows = _oql.read_records(obs_dir)
                rows_now = len(all_rows)
                assert len(roots) == executions, (len(roots), executions)
                for r in roots:
                    assert r.attrs.get("status") == "ok", r.attrs
                assert rows_now - rows_before == executions, (
                    rows_now, rows_before, executions,
                )
                root_ids = {r.trace_id for r in roots}
                new_rows = [
                    rec
                    for rec in all_rows
                    if rec.get("trace_id") in root_ids
                ]
                assert len(new_rows) == executions
                for rec in new_rows:
                    err = _oql.validate_record(rec)
                    assert err is None, (err, rec)
                obs_roots += len(roots)
                obs_rows_written += rows_now - rows_before
                obs_executions += executions
        session.conf.set(C.OBS_ENABLED, False)
        _otr.set_enabled(False)
        _otr.reset()
        obs_p50_on = float(
            np.median([r["p50_ms"] for r in obs_legs["on"]])
        )
        obs_p50_off = float(
            np.median([r["p50_ms"] for r in obs_legs["off"]])
        )
        obs_overhead = obs_p50_on / max(obs_p50_off, 1e-9) - 1.0
        serve_obs = {
            "p50_on_ms": round(obs_p50_on, 2),
            "p50_off_ms": round(obs_p50_off, 2),
            "overhead_ratio": round(obs_overhead, 4),
            "roots": obs_roots,
            "querylog_rows": obs_rows_written,
            "executions": obs_executions,
        }
        if n_items >= 4_000_000:
            # the acceptance bar holds at the real rung; tiny smoke
            # rows are noise-dominated and only gate the structure
            assert obs_overhead <= 0.05, serve_obs
        log(
            f"obs A/B: p50 on {serve_obs['p50_on_ms']}ms / off "
            f"{serve_obs['p50_off_ms']}ms ({obs_overhead * 100:+.1f}%), "
            f"{obs_roots} roots == {obs_executions} executions, "
            f"{obs_rows_written} querylog rows"
        )

        # --- advisor closed-loop rung (hyperspace_tpu/advisor/,
        # docs/advisor.md): a canned skewed workload over a dedicated
        # lake — record it in query-log format, replay for a baseline,
        # run profile → what-if recommend → budgeted apply, replay the
        # SAME workload again, then a second advise() pass. The gates
        # bench_smoke.sh asserts: the top create recommendation indexes
        # the workload's filter key (the bench-fastest index for a point
        # lookup), it applies under budget, the post-apply pass emits
        # ZERO create recommendations (convergence), and replay QPS
        # stays within tolerance of the baseline (the index must never
        # fall off a cliff, even where brute scans win on tiny rows).
        from hyperspace_tpu.advisor import advise as _advise
        from hyperspace_tpu.advisor import (
            apply_recommendations as _advisor_apply,
        )
        from hyperspace_tpu.testing import replay as _replay

        adv_lake = os.path.join(tmp, "advisor_lake")
        os.makedirs(adv_lake)
        adv_rows = min(n_items, 2_000_000)
        adv_files = 8
        rng = np.random.default_rng(29)
        per = max(1, adv_rows // adv_files)
        for i in range(adv_files):
            pq.write_table(
                pa.table(
                    {
                        "key": rng.integers(0, 1000, per),
                        "ts": np.arange(i * per, (i + 1) * per, dtype=np.int64),
                        "payload": rng.integers(0, 1 << 30, per),
                    }
                ),
                os.path.join(adv_lake, f"part-{i:03d}.parquet"),
            )
        adv_records = _replay.skewed_keys(
            [adv_lake],
            "key",
            list(range(0, 1000, 37)),
            24,
            project=["key", "payload"],
        )
        adv_obs_dir = os.path.join(tmp, "advisor_obs")
        _replay.record_workload(adv_records, adv_obs_dir)
        adv_base = _replay.replay_records(session, adv_records)
        assert adv_base.completed == len(adv_records), adv_base.to_dict()
        adv_report = _advise(session, directory=adv_obs_dir)
        adv_creates = [
            r for r in adv_report.recommendations if r.kind == "create"
        ]
        assert adv_creates, "skewed workload must motivate an index"
        assert adv_creates[0].indexed_columns[0] == "key", adv_creates[0]
        adv_summary = _advisor_apply(session, adv_creates, force=True)
        assert adv_summary["applied"] >= 1, adv_summary
        adv_after = _replay.replay_records(session, adv_records)
        assert adv_after.completed == len(adv_records), adv_after.to_dict()
        adv_second = _advise(session, directory=adv_obs_dir)
        adv_creates_after = [
            r for r in adv_second.recommendations if r.kind == "create"
        ]
        assert not adv_creates_after, [r.to_dict() for r in adv_creates_after]
        adv_qps_ratio = adv_after.qps / max(adv_base.qps, 1e-9)
        assert 0.2 <= adv_qps_ratio <= 5.0, (
            adv_base.to_dict(), adv_after.to_dict(),
        )
        advisor_rung = {
            "records": len(adv_records),
            "baseline_p50_ms": round(adv_base.p50_s * 1e3, 2),
            "after_p50_ms": round(adv_after.p50_s * 1e3, 2),
            "baseline_qps": round(adv_base.qps, 1),
            "after_qps": round(adv_after.qps, 1),
            "qps_ratio": round(adv_qps_ratio, 3),
            "recommended": [r.index_name for r in adv_creates],
            "top_indexed_columns": list(adv_creates[0].indexed_columns),
            "applied": adv_summary["applied"],
            "creates_after_apply": len(adv_creates_after),
        }
        log(
            f"advisor loop: {len(adv_creates)} rec(s) "
            f"({adv_creates[0].index_name} on "
            f"{','.join(adv_creates[0].indexed_columns)}), applied "
            f"{adv_summary['applied']}, p50 {advisor_rung['baseline_p50_ms']}"
            f"ms -> {advisor_rung['after_p50_ms']}ms, qps ratio "
            f"{advisor_rung['qps_ratio']}, converged="
            f"{not adv_creates_after}"
        )

        # --- fault-injection rung (testing/faults.py): one serve per
        # injection point x {transient, persistent}, each differential
        # against the fault-free result — the bench-level witness that
        # every point fires and the retry/degrade paths answer
        # bit-identically (bench_smoke.sh asserts the fired counts)
        # two query shapes per leg: the point filter exercises the read/
        # log/cache seams; the filter→aggregate exercises the fused
        # native pass, whose dispatch (native.load) is where the
        # kernel_dispatch point lives — a tiny point query can sit below
        # every native threshold and never touch the loader. The
        # aggregate sums an INT column only: the parquet_read-persistent
        # leg degrades to the source-order plan, and float sums are not
        # associative across the index-vs-source row orders (the same
        # boundary docs/serve-compiler.md documents) — int sums are
        # exact under any order, keeping the differential bitwise.
        def q_fault_agg(df):
            return df.filter(
                (df["l_orderkey"] >= agg_lo) & (df["l_orderkey"] < agg_hi)
            ).agg(
                hsf.count().alias("n"),
                hsf.sum("l_quantity").alias("sq"),
            )

        fault_qs = [q_point_k(ladder_keys[0]), q_fault_agg(items)]
        fault_bases = [session.execute(q.logical_plan) for q in fault_qs]
        _flt.reset()
        fe = ServeFrontend(session)
        for point, spec in (
            ("parquet_read", "transient:1"),
            ("parquet_read", "persistent;match=v__="),
            ("kernel_dispatch", "transient:1"),
            ("kernel_dispatch", "persistent"),
            ("log_read", "transient:1"),
            ("log_read", "persistent"),
            ("cache_insert", "transient:1"),
            ("cache_insert", "persistent"),
        ):
            session.clear_serve_cache()
            session.index_manager.clear_cache()
            _flt.set_fault(point, spec)
            for q, base_t in zip(fault_qs, fault_bases):
                out = fe.serve(q)
                assert out.equals(base_t), (point, spec)
            _flt.clear()
        # the fastbus_send seam lives on the fleet fast plane (serve/
        # fastbus.py), not the single-process serve path: fire it at the
        # transport directly — an armed fault surfaces as the typed
        # OSError every caller catches to fall back to the durable
        # planes (the fleet ladder's chaos rung witnesses that fallback
        # end to end)
        from hyperspace_tpu.serve import fastbus as _fastbus
        from hyperspace_tpu.testing.faults import InjectedFault as _IF

        _flt.set_fault("fastbus_send", "transient:1")
        try:
            _fastbus.push(os.path.join(tmp, "no-such.sock"), {"type": "event"})
            raise AssertionError("armed fastbus_send did not fire")
        except _IF:
            pass
        _flt.clear()
        fault_stats = fe.stats()
        fe.close()
        fault_fired = _flt.stats()
        _flt.reset()
        missing = [p for p in _flt.POINTS if fault_fired.get(p, 0) < 1]
        assert not missing, f"fault points never fired: {missing}"
        log(
            f"fault matrix: fired {fault_fired}; frontend retries "
            f"{fault_stats['retries']}, degraded {fault_stats['degraded']}, "
            f"degraded pins {fault_stats['degraded_pins']}, failed "
            f"{fault_stats['failed']}"
        )
        assert fault_stats["failed"] == 0

        # --- chaos rung (testing/chaos.py, docs/recovery.md): a seeded
        # lifecycle schedule crashed at each (step x point) cell in
        # turn, recovered, retried, and differentially served — the
        # bench-level witness that a crashed writer never strands an
        # index, never changes an answer, and never leaks an orphan
        # (bench_smoke.sh gates on the three zeros below)
        from hyperspace_tpu.testing import chaos as _chaos

        chaos_summary = _chaos.run_crash_matrix(
            os.path.join(tmp, "chaos"),
            seed=11,
            n_steps=10,
            max_cells=int(os.environ.get("HS_BENCH_CHAOS_CELLS", 8)),
        )
        assert chaos_summary["crashes_fired"] >= 1, chaos_summary
        assert chaos_summary["stranded_after_recovery"] == 0, chaos_summary
        assert chaos_summary["orphans_after_gc"] == 0, chaos_summary
        assert chaos_summary["serve_mismatches"] == 0, chaos_summary
        log(
            f"chaos: {chaos_summary['cells']} cells, "
            f"{chaos_summary['crashes_fired']} crashes fired, "
            f"{chaos_summary['rolled_back']} rollbacks, "
            f"{chaos_summary['serves_verified']} serves verified, "
            f"0 stranded / 0 orphans / 0 mismatches"
        )

        # --- multi-process fleet ladder (serve/fleet.py, docs/fleet-
        # serve.md): N REAL frontend processes over one lake, identical
        # schedules from a barrier start — the horizontal twin of the
        # 1/8/64-client ladder above. Each rung reports aggregate QPS,
        # cross-process dedup (claim/spool wins OR fast-plane handoffs/
        # result-cache hits — the dedup that saved 256/512 queries at
        # one process must not regress to 0 at eight), the fast-plane
        # witnesses (pushed fanout events received, spool-free result
        # handoffs, push-vs-poll wait milliseconds), and the zeros
        # bench_smoke.sh gates on: wrong answers, leaked pin files,
        # leaked member/socket files. The final rung is the chaos rung:
        # kill -9 one frontend mid-serve, survivors degrade fast ->
        # durable bit-identically, the dead frontend's durable pins and
        # fast-plane member file reaped at lease expiry.
        from hyperspace_tpu.testing import fleet_harness as _fleet

        fleet_procs = [
            int(x)
            for x in os.environ.get(
                "HS_BENCH_FLEET", "2,4,8,16,32"
            ).split(",")
            if x.strip()
        ]
        fleet_iters = int(os.environ.get("HS_BENCH_FLEET_ITERS", 8))
        fleet_rows = int(os.environ.get("HS_BENCH_FLEET_ROWS", 50_000))
        fleet_root = os.path.join(tmp, "fleet")
        fleet_lake = _fleet.build_lake(fleet_root, rows=fleet_rows)
        fleet_ladder = []
        for np_ in fleet_procs:
            row = _fleet.run_fleet(
                os.path.join(fleet_root, f"rung{np_}"),
                n_procs=np_,
                iters=fleet_iters,
                reuse_lake=fleet_lake,
                fastpath_phase=True,
            )
            assert row["wrong_answers"] == 0, row
            assert row["leaked_pin_files"] == 0, row
            assert row["leaked_fast_members"] == 0, row
            assert row["fast_frontends"] == np_, row
            # dedup may land on any plane: claim/spool wins, owner-routed
            # handoffs, or fast result-cache hits
            assert (
                row["cross_process_dedup"]
                + row["fast_handoffs"]
                + row["fast_result_hits"]
                > 0
            ), row
            # the deterministic fast-path witnesses (two-phase harness):
            # every live worker received the parent refresh as a PUSH,
            # and served at least one spool-free owner-routed probe
            assert row["fast_push_received"] >= 1, row
            assert row["fast_handoffs"] >= 1, row
            fleet_ladder.append(row)
            fast_avg = row["fast_wait_ms_total"] / max(1, row["fast_waits"])
            poll_avg = row["poll_wait_ms_total"] / max(1, row["poll_waits"])
            log(
                f"fleet {np_} procs: {row['qps']} qps aggregate, p50 "
                f"{row['p50_ms']}ms p99 {row['p99_ms']}ms, dedup "
                f"{row['cross_process_dedup']}+{row['fast_handoffs']}fast"
                f"/{row['queries']}, push recv {row['fast_push_received']}, "
                f"waits fast {row['fast_waits']}x{fast_avg:.2f}ms vs poll "
                f"{row['poll_waits']}x{poll_avg:.2f}ms, 0 wrong / 0 leaked"
            )
        # ladder shape gates: QPS monotone through the rungs (within
        # run-to-run jitter), and the 2-process rung beating the
        # single-process 64-client rung — the whole point of replacing
        # elections + fsync'd spool round-trips with owner routing
        for prev, cur in zip(fleet_ladder, fleet_ladder[1:]):
            assert cur["qps"] >= prev["qps"] * 0.85, (
                "fleet ladder QPS not monotone",
                prev["processes"],
                prev["qps"],
                cur["processes"],
                cur["qps"],
            )
        serve64 = next(
            (r for r in serve_concurrency if r["clients"] == 64), None
        )
        fleet2 = next(
            (r for r in fleet_ladder if r["processes"] == 2), None
        )
        fleet_vs_single = None
        if serve64 is not None and fleet2 is not None:
            fleet_vs_single = {
                "single_process_64c_qps": serve64["qps"],
                "fleet_2proc_qps": fleet2["qps"],
                "beats_single": bool(fleet2["qps"] > serve64["qps"]),
            }
            log(
                f"fleet 2-proc {fleet2['qps']} qps vs single-process "
                f"64-client {serve64['qps']} qps -> "
                f"{'BEATS' if fleet_vs_single['beats_single'] else 'TRAILS'}"
            )
            if os.environ.get("HS_BENCH_FLEET_STRICT"):
                # the acceptance bar holds at the real rung; tiny smoke
                # rows measure process-spawn overhead, not the plane
                assert fleet_vs_single["beats_single"], fleet_vs_single
        fleet_chaos = _fleet.run_fleet(
            os.path.join(fleet_root, "chaos"),
            n_procs=max(fleet_procs) if fleet_procs else 2,
            iters=fleet_iters,
            kill_one=True,
            reuse_lake=fleet_lake,
            fastpath_phase=True,
        )
        assert fleet_chaos["wrong_answers"] == 0, fleet_chaos
        assert fleet_chaos["leaked_pin_files"] == 0, fleet_chaos
        assert fleet_chaos["leaked_fast_members"] == 0, fleet_chaos
        # fast -> durable degradation witnessed: survivors probed the
        # dead owner's digests, paid one failed connect each, and fell
        # back to the claim/spool plane bit-identically
        assert fleet_chaos["fast_fallbacks"] >= 1, fleet_chaos
        log(
            f"fleet chaos (kill -9 one of {fleet_chaos['processes']}): "
            f"{fleet_chaos['workers_reporting']} survivors, 0 wrong "
            f"answers, 0 leaked pins/members, dedup "
            f"{fleet_chaos['cross_process_dedup']}, fast->durable "
            f"fallbacks {fleet_chaos['fast_fallbacks']}, p99 "
            f"{fleet_chaos['p99_ms']}ms"
        )

        session.conf.set(C.SERVE_CACHE_ENABLED, False)
        session.clear_serve_cache()  # later stages measure uncached paths;
        # keeping 200+MB resident would only add allocator/page pressure
        session.disable_hyperspace()

        # --- Hybrid Scan join (BASELINE config 4 analogue): append ~3%
        # source rows AFTER indexing; the index must still serve, with the
        # delta union-compensated and re-bucketed at execution time
        n_extra = max(n_items // 32, 1)
        extra = pa.table(
            {
                "l_orderkey": np.random.default_rng(9).integers(
                    0, n_orders, n_extra
                ),
                "l_shipdate": pa.array(
                    np.full(n_extra, np.datetime64("1998-01-01"))
                ),
                "l_quantity": np.full(n_extra, 7, dtype=np.int64),
                "l_extendedprice": np.full(n_extra, 1.0),
            }
        )
        pq.write_table(extra, os.path.join(items_dir, "appended.parquet"))
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.index_manager.clear_cache()
        items2 = session.read.parquet(items_dir)
        session.enable_hyperspace()
        plan = q_join(orders, items2).explain()
        hybrid_served = plan.count("Hyperspace(Type: CI") == 2
        if not hybrid_served:
            log(f"WARNING: hybrid join not index-served:\n{plan}")
        h_rows = q_join(orders, items2).collect().num_rows
        hybrid_idx = timeit(lambda: q_join(orders, items2).collect(), reps)
        hybrid_stages = {
            k: round(v * 1e3, 2)
            for k, v in join_exec.last_serve_breakdown.items()
        }
        log(
            "hybrid serve stages (last uncached run, busy ms): "
            f"{hybrid_stages}"
        )
        # serve-server mode over the SAME hybrid state: the joinside cache
        # keys on (index files + appended files) fingerprints, so repeated
        # queries on a stable appended state skip the per-query union
        # compensation entirely
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        assert q_join(orders, items2).collect().num_rows == h_rows
        hybrid_cached = timeit(lambda: q_join(orders, items2).collect(), reps)

        # cached-DELTA row: evicting everything but the fingerprint-keyed
        # ("delta", …) entry before each trial isolates the steady state
        # of a serve process fielding varied projections over a
        # slowly-appending table — the index side re-prepares, but the
        # appended compensation (read + re-bucket) is already done and
        # the query pays only the per-bucket merge
        hcache = session.serve_cache

        def run_cached_delta():
            for kind in ("joinside", "bucketed", "scan"):
                hcache.evict_kind(kind)
            q_join(orders, items2).collect()

        run_cached_delta()  # warm the delta entry itself
        hybrid_cached_delta = timeit(run_cached_delta, reps)
        log(
            "hybrid cached-delta (only the prepared delta warm) p50: "
            f"{hybrid_cached_delta['p50'] * 1e3:.1f}ms"
        )
        session.conf.set(C.SERVE_CACHE_ENABLED, False)
        session.clear_serve_cache()
        session.disable_hyperspace()
        assert q_join(orders, items2).collect().num_rows == h_rows
        hybrid_raw = timeit(lambda: q_join(orders, items2).collect(), reps)
        log(
            f"hybrid-scan join p50: indexed {hybrid_idx['p50'] * 1e3:.1f}ms vs "
            f"unindexed {hybrid_raw['p50'] * 1e3:.1f}ms "
            f"({hybrid_raw['p50'] / hybrid_idx['p50']:.2f}x); "
            f"serve-server {hybrid_cached['p50'] * 1e3:.1f}ms "
            f"({hybrid_raw['p50'] / hybrid_cached['p50']:.2f}x)"
        )
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, False)

        # --- Delta incremental refresh (BASELINE config 5): index a Delta
        # table with lineage, commit appends, time the incremental refresh
        delta_dir = os.path.join(tmp, "delta_tbl")
        dlog = os.path.join(delta_dir, "_delta_log")
        os.makedirs(dlog)
        rngd = np.random.default_rng(13)
        n_delta = max(n_items // 4, 1)

        def delta_file(name, rows):
            t = pa.table(
                {
                    "k": rngd.integers(0, n_orders, rows),
                    "q": rngd.integers(1, 51, rows),
                }
            )
            fp = os.path.join(delta_dir, name)
            pq.write_table(t, fp)
            st = os.stat(fp)
            return {
                "path": name,
                "size": st.st_size,
                "modificationTime": int(st.st_mtime * 1000),
                "dataChange": True,
            }

        schema_str = json.dumps(
            {
                "type": "struct",
                "fields": [
                    {"name": "k", "type": "long", "nullable": True, "metadata": {}},
                    {"name": "q", "type": "long", "nullable": True, "metadata": {}},
                ],
            }
        )
        with open(os.path.join(dlog, f"{0:020d}.json"), "w") as f:
            f.write(json.dumps({"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}) + "\n")
            f.write(
                json.dumps(
                    {
                        "metaData": {
                            "id": "bench",
                            "schemaString": schema_str,
                            "partitionColumns": [],
                            "format": {"provider": "parquet"},
                        }
                    }
                )
                + "\n"
            )
            f.write(json.dumps({"add": delta_file("part-0.parquet", n_delta)}) + "\n")


        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        ddf = session.read.delta(delta_dir)
        hs.create_index(ddf, CoveringIndexConfig("delta_idx", ["k"], ["q"]))
        n_append = max(n_delta // 8, 1)
        with open(os.path.join(dlog, f"{1:020d}.json"), "w") as f:
            f.write(
                json.dumps({"add": delta_file("part-1.parquet", n_append)}) + "\n"
            )
        session.index_manager.clear_cache()
        t0 = time.perf_counter()
        hs.refresh_index("delta_idx", C.REFRESH_MODE_INCREMENTAL)
        delta_refresh = time.perf_counter() - t0
        log(
            f"delta incremental refresh of {n_append:,} appended rows: "
            f"{delta_refresh:.2f}s ({n_append / delta_refresh:,.0f} rows/s)"
        )

        # --- z-order range query (the index kind had no perf row through
        # round 5 — VERDICT weak #5). Two-dimensional range predicate; the
        # z-layout clusters both dims so row-group min/max stats prune to
        # a narrow band of each bucket file.
        from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig
        from hyperspace_tpu.indexes.sketches import MinMaxSketch
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        session.conf.set(C.INDEX_LINEAGE_ENABLED, False)  # delta section left it on
        session.index_manager.clear_cache()
        items3 = session.read.parquet(items_dir)
        hs.create_index(
            items3,
            ZOrderCoveringIndexConfig(
                "z_idx", ["l_shipdate", "l_quantity"], ["l_orderkey"]
            ),
        )
        zlo = np.datetime64("1995-06-01")
        zhi = np.datetime64("1995-06-30")

        def q_zrange(df):
            return df.filter(
                (df["l_shipdate"] >= zlo)
                & (df["l_shipdate"] <= zhi)
                & (df["l_quantity"] <= 5)
            ).select("l_shipdate", "l_quantity", "l_orderkey")

        session.enable_hyperspace()
        plan = q_zrange(items3).explain()
        if "Hyperspace(Type: ZOCI" not in plan:
            log(f"WARNING: z-order range not index-served:\n{plan}")
        z_rows = q_zrange(items3).collect().num_rows
        # INTERLEAVED A/B (round-7 protocol): rangeprune on vs off
        # alternate within one process, so page-cache/allocator drift
        # hits both legs equally. The "off" leg is the pre-range-plane
        # serve path (full index read + interpreter mask), the "on" leg
        # is zone-map file/row-group pruning + the fused residual mask.
        t_on, t_off = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            q_zrange(items3).collect()
            t_on.append(time.perf_counter() - t0)
            session.conf.set(C.SERVE_RANGEPRUNE_ENABLED, False)
            t0 = time.perf_counter()
            q_zrange(items3).collect()
            t_off.append(time.perf_counter() - t0)
            session.conf.unset(C.SERVE_RANGEPRUNE_ENABLED)

        def _stats(ts):
            q1, med, q3 = np.percentile(ts, [25, 50, 75])
            return {"p50": float(med), "iqr": float(q3 - q1), "n": len(ts)}

        zrange_idx = _stats(t_on)
        zrange_off = _stats(t_off)
        # pruning telemetry of the last rangeprune-on run: refresh it
        # (the off leg overwrote nothing — pruning was disabled — but be
        # explicit and re-run one pruned serve before reading)
        from hyperspace_tpu.indexes import zonemaps as _zonemaps

        q_zrange(items3).collect()
        zprune = dict(_zonemaps.last_prune_stats)
        zmaps_seen = (
            zprune.get("zonemap_files_sidecar", 0)
            + zprune.get("zonemap_files_footer", 0)
        )
        zprune["zonemap_hit_rate"] = round(
            zprune.get("zonemap_files_sidecar", 0) / zmaps_seen, 3
        ) if zmaps_seen else 0.0
        session.disable_hyperspace()
        assert q_zrange(items3).collect().num_rows == z_rows
        zrange_raw = timeit(lambda: q_zrange(items3).collect(), reps)
        log(
            f"z-order range p50: indexed {zrange_idx['p50'] * 1e3:.1f}ms "
            f"(rangeprune off {zrange_off['p50'] * 1e3:.1f}ms) vs "
            f"unindexed {zrange_raw['p50'] * 1e3:.1f}ms "
            f"({zrange_raw['p50'] / zrange_idx['p50']:.2f}x, {z_rows:,} rows); "
            f"prune: {zprune}"
        )
        # the z-index also covers l_shipdate and would win the scoring
        # race below; the data-skipping row must measure DS serving
        hs.delete_index("z_idx")
        hs.vacuum_index("z_idx")

        # --- data-skipping file pruning (min/max sketch; also had no
        # perf row). Files are laid out in ship-date order, so a narrow
        # date range prunes most source files from the scan itself.
        session.index_manager.clear_cache()
        items4 = session.read.parquet(items_dir)
        hs.create_index(
            items4, DataSkippingIndexConfig("ds_idx", MinMaxSketch("l_shipdate"))
        )
        session.enable_hyperspace()
        plan = q_zrange(items4).explain()
        if "Hyperspace(Type: DS" not in plan:
            log(f"WARNING: data-skipping not serving:\n{plan}")
        ds_leaves = session.optimize(
            q_zrange(items4).logical_plan
        ).collect_leaves()
        ds_files = len(ds_leaves[0].relation.files)
        ds_total = len(items4.logical_plan.collect_leaves()[0].relation.files)
        ds_rows = q_zrange(items4).collect().num_rows
        assert ds_rows == z_rows, (ds_rows, z_rows)
        ds_idx_t = timeit(lambda: q_zrange(items4).collect(), reps)
        session.disable_hyperspace()
        ds_raw_t = timeit(lambda: q_zrange(items4).collect(), reps)
        log(
            f"data-skipping prune p50: indexed {ds_idx_t['p50'] * 1e3:.1f}ms "
            f"({ds_files}/{ds_total} files scanned) vs unindexed "
            f"{ds_raw_t['p50'] * 1e3:.1f}ms "
            f"({ds_raw_t['p50'] / ds_idx_t['p50']:.2f}x)"
        )
        hs.delete_index("ds_idx")
        hs.vacuum_index("ds_idx")

        # --- build-throughput ladder: the scale story the BASELINE table
        # tracks (4M/16M/64M). Each rung is an independent dataset +
        # fresh index build; per-stage seconds name the bottleneck. The
        # partition-first sort keeps per-bucket working sets resident, so
        # the 64M rung no longer collapses on permutation gathers.
        ladder_env = os.environ.get(
            "HS_BENCH_LADDER", "4000000,16000000,64000000"
        )
        ladder = []
        for rung_rows in [int(x) for x in ladder_env.split(",") if x.strip()]:
            rung_dir = os.path.join(tmp, f"ladder_{rung_rows}")
            try:
                ldir, _odir = gen_data(
                    rung_dir, rung_rows, max(rung_rows // 8, 1)
                )
                lsession = HyperspaceSession()
                lsession.conf.set(
                    C.INDEX_SYSTEM_PATH, os.path.join(rung_dir, "indexes")
                )
                lsession.conf.set(C.INDEX_NUM_BUCKETS, num_buckets)
                lhs = Hyperspace(lsession)
                ldf = lsession.read.parquet(ldir)
                cfg = CoveringIndexConfig(
                    "ladder_idx",
                    ["l_orderkey"],
                    ["l_shipdate", "l_quantity", "l_extendedprice"],
                )
                lhs.create_index(ldf, cfg)  # warm caches/compiles
                lhs.delete_index("ladder_idx")
                lhs.vacuum_index("ladder_idx")
                lsession.index_manager.clear_cache()
                t0 = time.perf_counter()
                lhs.create_index(ldf, cfg)
                rung_warm = time.perf_counter() - t0
                rung_stages = {
                    k: round(v, 3) for k, v in last_build_breakdown.items()
                }
                ladder.append(
                    {
                        "rows": rung_rows,
                        "build_warm_s": round(rung_warm, 3),
                        "build_rows_per_sec": round(rung_rows / rung_warm),
                        "build_stage_seconds": rung_stages,
                        "rss_high_water_bytes": rss_hwm(),
                    }
                )
                log(
                    f"ladder {rung_rows:,} rows: {rung_warm:.2f}s warm "
                    f"({rung_rows / rung_warm:,.0f} rows/s); stages: "
                    f"{rung_stages}"
                )
            except MemoryError:
                log(f"ladder {rung_rows:,} rows: skipped (MemoryError)")
            finally:
                shutil.rmtree(rung_dir, ignore_errors=True)

        # --- mesh build/serve ladder: the scale-out story (ROADMAP item
        # 2). Per (rows, devices) rung: a warm covering build — on >1
        # devices the shard_map all-to-all shuffle plus the sharded
        # sort+write tail (hyperspace.build.shardedTail.enabled) — and
        # the co-bucketed indexed join served with per-shard prepare +
        # merge. Stage seconds are busy time (sort/write sum across
        # shard tails; the excess over tail_wall is the sharding win);
        # shuffle telemetry records exchange cap + per-peer skew.
        from hyperspace_tpu.indexes.covering_build import (
            last_build_telemetry,
        )

        mesh_sizes_env = os.environ.get("HS_BENCH_MESH", "1,2,8")
        mesh_rows_env = os.environ.get(
            "HS_BENCH_MESH_ROWS", "4000000,64000000"
        )
        avail = len(jax.devices())
        mesh_sizes = [
            d
            for d in (
                int(x) for x in mesh_sizes_env.split(",") if x.strip()
            )
            if 1 <= d <= avail
        ]
        mesh_ladder = []
        for rung_rows in [
            int(x) for x in mesh_rows_env.split(",") if x.strip()
        ]:
            rung_dir = os.path.join(tmp, f"mesh_{rung_rows}")
            try:
                mldir, modir = gen_data(
                    rung_dir, rung_rows, max(rung_rows // 8, 1)
                )
                for D in mesh_sizes:
                    msession = HyperspaceSession(devices=jax.devices()[:D])
                    msession.conf.set(
                        C.INDEX_SYSTEM_PATH,
                        os.path.join(rung_dir, f"indexes_d{D}"),
                    )
                    msession.conf.set(C.INDEX_NUM_BUCKETS, num_buckets)
                    mhs = Hyperspace(msession)
                    mdf = msession.read.parquet(mldir)
                    mcfg = CoveringIndexConfig(
                        "mesh_l_idx",
                        ["l_orderkey"],
                        ["l_shipdate", "l_quantity", "l_extendedprice"],
                    )
                    mhs.create_index(mdf, mcfg)  # warm caches/compiles
                    mhs.delete_index("mesh_l_idx")
                    mhs.vacuum_index("mesh_l_idx")
                    msession.index_manager.clear_cache()
                    t0 = time.perf_counter()
                    mhs.create_index(mdf, mcfg)
                    m_warm = time.perf_counter() - t0
                    m_stages = {
                        k: round(v, 3)
                        for k, v in last_build_breakdown.items()
                    }
                    m_shuffle = {
                        k: v for k, v in last_build_telemetry.items()
                    }
                    modf = msession.read.parquet(modir)
                    mhs.create_index(
                        modf,
                        CoveringIndexConfig(
                            "mesh_o_idx", ["o_orderkey"], ["o_custkey"]
                        ),
                    )
                    msession.enable_hyperspace()

                    def q_mjoin(o=modf, i=mdf):
                        return o.join(
                            i, on=o["o_orderkey"] == i["l_orderkey"]
                        ).select("o_orderkey", "o_custkey", "l_quantity")

                    mplan = q_mjoin().explain()
                    if mplan.count("Hyperspace(Type: CI") != 2:
                        log(
                            f"WARNING: mesh join (D={D}) not index-served:"
                            f"\n{mplan}"
                        )
                    q_mjoin().collect()  # warmup
                    m_join = timeit(lambda: q_mjoin().collect(), reps)
                    m_join_stages = {
                        k: round(v * 1e3, 2)
                        for k, v in join_exec.last_serve_breakdown.items()
                    }
                    mesh_ladder.append(
                        {
                            "rows": rung_rows,
                            "devices": D,
                            "build_warm_s": round(m_warm, 3),
                            "build_rows_per_sec": round(rung_rows / m_warm),
                            "build_stage_seconds": m_stages,
                            "shuffle": m_shuffle,
                            "join_indexed_p50_ms": round(
                                m_join["p50"] * 1e3, 2
                            ),
                            "join_indexed_iqr_ms": round(
                                m_join["iqr"] * 1e3, 2
                            ),
                            "join_serve_stage_ms": m_join_stages,
                            "rss_high_water_bytes": rss_hwm(),
                        }
                    )
                    log(
                        f"mesh ladder {rung_rows:,} rows x {D} devices: "
                        f"build {m_warm:.2f}s "
                        f"({rung_rows / m_warm:,.0f} rows/s), join "
                        f"{m_join['p50'] * 1e3:.1f}ms; stages: {m_stages}"
                        f"; shuffle: {m_shuffle}"
                    )
            except MemoryError:
                log(f"mesh ladder {rung_rows:,} rows: skipped (MemoryError)")
            finally:
                shutil.rmtree(rung_dir, ignore_errors=True)

        # --- out-of-core streaming ladder (docs/out-of-core.md): the
        # join served in budget-packed waves with the spill tier and
        # mmap reads on. The 256M rung is the tentpole claim: it must
        # COMPLETE with peak residency O(wave), where the materializing
        # path holds both decoded sides at once. The stream-off baseline
        # runs only up to HS_BENCH_STREAM_BASELINE_MAX rows (default
        # 64M) — above that the materializing peak is exactly what the
        # flag exists to avoid. 1B rows is opt-in:
        # HS_BENCH_STREAM_LADDER=64000000,256000000,1000000000.
        from hyperspace_tpu.execution import executor as ex_mod

        stream_env = os.environ.get(
            "HS_BENCH_STREAM_LADDER", "64000000,256000000"
        )
        baseline_max = int(
            os.environ.get("HS_BENCH_STREAM_BASELINE_MAX", 64_000_000)
        )
        stream_ladder = []
        for rung_rows in [
            int(x) for x in stream_env.split(",") if x.strip()
        ]:
            rung_dir = os.path.join(tmp, f"stream_{rung_rows}")
            try:
                sldir, sodir = gen_data(
                    rung_dir,
                    rung_rows,
                    max(rung_rows // 8, 1),
                    n_files=max(8, rung_rows // 8_000_000),
                )
                # buckets scale with rows (~4M rows/bucket) so a wave
                # can pack several buckets under stream.maxBytes — a
                # bucket bigger than the whole budget degrades to
                # one-bucket waves and the peak grows to O(bucket)
                s_buckets = max(num_buckets, rung_rows // 4_000_000)
                ssession = HyperspaceSession()
                ssession.conf.set(
                    C.INDEX_SYSTEM_PATH, os.path.join(rung_dir, "indexes")
                )
                ssession.conf.set(C.INDEX_NUM_BUCKETS, s_buckets)
                shs = Hyperspace(ssession)
                sldf = ssession.read.parquet(sldir)
                sodf = ssession.read.parquet(sodir)
                shs.create_index(
                    sldf,
                    CoveringIndexConfig(
                        "stream_l_idx", ["l_orderkey"], ["l_quantity"]
                    ),
                )
                shs.create_index(
                    sodf,
                    CoveringIndexConfig(
                        "stream_o_idx", ["o_orderkey"], ["o_custkey"]
                    ),
                )
                ssession.enable_hyperspace()

                def q_sjoin(o=sodf, i=sldf):
                    return o.join(
                        i, on=o["o_orderkey"] == i["l_orderkey"]
                    ).select("o_orderkey", "o_custkey", "l_quantity")

                splan = q_sjoin().explain()
                if splan.count("Hyperspace(Type: CI") != 2:
                    log(
                        f"WARNING: stream rung join not index-served:"
                        f"\n{splan}"
                    )
                base_row = None
                if rung_rows <= baseline_max:
                    t0 = time.perf_counter()
                    base_rows = q_sjoin().collect().num_rows
                    base_wall = time.perf_counter() - t0
                    base_row = {
                        "wall_s": round(base_wall, 3),
                        "rows_out": base_rows,
                        "serve_stage_ms": {
                            k: round(v * 1e3, 2)
                            for k, v in (
                                join_exec.last_serve_breakdown.items()
                            )
                        },
                        "rss_high_water_bytes": rss_hwm(),
                    }
                # spill round-trip at rung scale (docs/out-of-core.md):
                # measure one side's decoded filter state, then size the
                # cache to hold exactly that — serving the other side
                # demotes it to the spill tier and the re-serve restores
                # it as a zero-copy mmap view
                ssession.conf.set(C.SERVE_CACHE_ENABLED, True)
                ssession.conf.set(C.SERVE_SPILL_MAX_BYTES, 2 << 30)
                ssession.conf.set(C.IO_MMAP_ENABLED, True)
                k_l = int(max(rung_rows // 8, 1) // 3)

                def q_sfilter_l(i=sldf, k=k_l):
                    return i.filter(i["l_orderkey"] == k).select(
                        "l_orderkey", "l_quantity"
                    )

                def q_sfilter_o(o=sodf, k=k_l):
                    return o.filter(o["o_orderkey"] == k).select(
                        "o_orderkey", "o_custkey"
                    )

                l_rows = q_sfilter_l().collect().num_rows
                resident = ssession.serve_cache.stats()["resident_bytes"]
                if resident > 0:
                    # rebuilds the cache at the tight budget
                    ssession.conf.set(
                        C.SERVE_CACHE_MAX_BYTES, resident + 64
                    )
                    assert q_sfilter_l().collect().num_rows == l_rows
                    q_sfilter_o().collect()  # displaces l -> demote
                    assert q_sfilter_l().collect().num_rows == l_rows
                ssession.conf.set(C.SERVE_STREAM_ENABLED, True)
                # HS_BENCH_STREAM_MAX_BYTES: shrink the wave budget so
                # tiny smoke rows still pack >1 wave (0 = conf default)
                wave_budget = int(
                    os.environ.get("HS_BENCH_STREAM_MAX_BYTES", 0)
                )
                if wave_budget > 0:
                    ssession.conf.set(
                        C.SERVE_STREAM_MAX_BYTES, wave_budget
                    )
                ex_mod.stream_stats_reset()
                t0 = time.perf_counter()
                s_rows = q_sjoin().collect().num_rows
                s_wall = time.perf_counter() - t0
                s_stats = dict(ex_mod.last_stream_stats)
                cache_stats = ssession.serve_cache.stats()
                row = {
                    "rows": rung_rows,
                    "num_buckets": s_buckets,
                    "stream_wall_s": round(s_wall, 3),
                    "rows_out": s_rows,
                    "stream_waves": s_stats.get("stream_waves", 0),
                    "stream_buckets": s_stats.get("stream_buckets", 0),
                    "stream_stage_ms": {
                        k: round(v * 1e3, 2)
                        for k, v in join_exec.last_serve_breakdown.items()
                    },
                    "spill_demotes": cache_stats["spill_demotes"],
                    "spill_restores": cache_stats["spill_restores"],
                    "spill_bytes": cache_stats["spill_bytes"],
                    "rss_high_water_bytes": rss_hwm(),
                }
                if base_row is not None:
                    # cheap at-scale identity proxy; the byte-level
                    # differential is tests/test_stream_serve.py
                    assert s_rows == base_row["rows_out"], (
                        s_rows,
                        base_row["rows_out"],
                    )
                    row["materializing_baseline"] = base_row
                    row["stream_speedup"] = round(
                        base_row["wall_s"] / s_wall, 3
                    )
                stream_ladder.append(row)
                log(
                    f"stream ladder {rung_rows:,} rows: "
                    f"{s_wall:.2f}s in {row['stream_waves']} waves "
                    f"({row['stream_buckets']} buckets), "
                    f"spill {row['spill_demotes']}/{row['spill_restores']} "
                    f"demote/restore, rss hwm "
                    f"{row['rss_high_water_bytes'] / 1e9:.2f}GB"
                )
            except MemoryError:
                log(
                    f"stream ladder {rung_rows:,} rows: skipped "
                    f"(MemoryError)"
                )
            finally:
                shutil.rmtree(rung_dir, ignore_errors=True)

        # headline: geometric mean of the three UNCACHED serve-path
        # speedups — stable under one path's unindexed baseline improving,
        # and directly comparable with rounds 1-4. The serve-server
        # (cached) numbers are reported separately, clearly labeled.
        def ms(d):
            return round(d["p50"] * 1e3, 2)

        def iqr_ms(d):
            return round(d["iqr"] * 1e3, 2)

        speedups = [
            filter_raw["p50"] / filter_idx["p50"],
            join_raw["p50"] / join_idx["p50"],
            hybrid_raw["p50"] / hybrid_idx["p50"],
        ]
        geomean = float(np.prod(speedups) ** (1.0 / len(speedups)))

        # resident-set telemetry: always the process RSS high-water;
        # per-site peak bytes too when the residency witness is armed
        # (the artifact is also written here, for hslint --witness)
        residency: dict = {"rss_high_water_bytes": rss_hwm()}
        if residency_art:
            from hyperspace_tpu.testing import residency_witness

            wdoc = residency_witness.dump(residency_art)
            residency["witness_artifact"] = residency_art
            residency["witnessed_sites"] = len(wdoc["sites"])
            residency["witness_peak_bytes_by_site"] = {
                site: rec["peak_bytes"]
                for site, rec in sorted(wdoc["sites"].items())
            }
        print(
            json.dumps(
                {
                    "metric": "indexed_query_speedup_geomean",
                    "value": round(geomean, 3),
                    "unit": "x (geomean of filter/join/hybrid p50 speedups vs unindexed, same chip; uncached serve)",
                    "vs_baseline": round(geomean, 3),
                    "platform": platform,
                    "rows": n_items,
                    "num_buckets": num_buckets,
                    "trials_per_stage": reps,
                    "build_rows_per_sec": round(n_items / build_warm),
                    "build_cold_s": round(build_cold, 3),
                    "build_warm_s": round(build_warm, 3),
                    "build_stage_seconds": breakdown,
                    "filter_indexed_p50_ms": ms(filter_idx),
                    "filter_indexed_iqr_ms": iqr_ms(filter_idx),
                    "filter_unindexed_p50_ms": ms(filter_raw),
                    "filter_unindexed_iqr_ms": iqr_ms(filter_raw),
                    "filter_speedup": round(
                        filter_raw["p50"] / filter_idx["p50"], 3
                    ),
                    "filter_cached_p50_ms": ms(filter_cached),
                    "filter_cached_iqr_ms": iqr_ms(filter_cached),
                    "filter_cached_speedup": round(
                        filter_raw["p50"] / filter_cached["p50"], 3
                    ),
                    "filter_agg": {
                        "fused_p50_ms": ms(fagg_on),
                        "fused_iqr_ms": iqr_ms(fagg_on),
                        "interp_p50_ms": ms(fagg_off),
                        "interp_iqr_ms": iqr_ms(fagg_off),
                        "fused_speedup": round(
                            fagg_off["p50"] / fagg_on["p50"], 3
                        ),
                        "fused_ran": fagg_stats.get("mode") == "agg",
                        "stats": fagg_stats,
                    },
                    "grouped_agg": {
                        "fused_p50_ms": ms(gagg_on),
                        "fused_iqr_ms": iqr_ms(gagg_on),
                        "interp_p50_ms": ms(gagg_off),
                        "interp_iqr_ms": iqr_ms(gagg_off),
                        "fused_speedup": round(
                            gagg_off["p50"] / gagg_on["p50"], 3
                        ),
                        "fused_ran": gagg_stats.get("mode") == "agg",
                        "stats": gagg_stats,
                    },
                    "agg_metadata": {
                        "metadata_p50_ms": ms(meta_ab[0]),
                        "metadata_iqr_ms": iqr_ms(meta_ab[0]),
                        "fused_p50_ms": ms(meta_ab[1]),
                        "fused_iqr_ms": iqr_ms(meta_ab[1]),
                        "metadata_speedup": round(
                            meta_ab[1]["p50"] / meta_ab[0]["p50"], 3
                        ),
                        "metadata_ran": meta_stats.get("mode")
                        == "agg_metadata",
                        "stats": meta_stats,
                    },
                    "agg_approx": {
                        "approx_p50_ms": ms(t_apx),
                        "approx_iqr_ms": iqr_ms(t_apx),
                        "exact_p50_ms": ms(t_exact),
                        "exact_iqr_ms": iqr_ms(t_exact),
                        "count_rel_err": round(n_err, 6),
                        "count_bound_held": n_in_ci,
                        "sum_bound_held": s_in_ci,
                        "stats": {
                            k: v
                            for k, v in apx_stats.items()
                            if k != "wall_s"
                        },
                    },
                    "join_indexed_p50_ms": ms(join_idx),
                    "join_indexed_iqr_ms": iqr_ms(join_idx),
                    "join_unindexed_p50_ms": ms(join_raw),
                    "join_unindexed_iqr_ms": iqr_ms(join_raw),
                    "join_speedup": round(join_raw["p50"] / join_idx["p50"], 3),
                    "join_cached_p50_ms": ms(join_cached),
                    "join_cached_iqr_ms": iqr_ms(join_cached),
                    "join_cached_speedup": round(
                        join_raw["p50"] / join_cached["p50"], 3
                    ),
                    "serve_concurrency": serve_concurrency,
                    "serve_obs": serve_obs,
                    "advisor": advisor_rung,
                    "fleet_ladder": fleet_ladder,
                    "fleet_vs_single": fleet_vs_single,
                    "fleet_chaos": fleet_chaos,
                    "fleet_vs_64client_qps": round(
                        fleet_ladder[-1]["qps"]
                        / max(
                            next(
                                (
                                    r["qps"]
                                    for r in serve_concurrency
                                    if r["clients"] == 64
                                ),
                                1.0,
                            ),
                            1e-9,
                        ),
                        3,
                    )
                    if fleet_ladder
                    else None,
                    "chaos": chaos_summary,
                    "fault_injection": {
                        "fired": fault_fired,
                        "frontend_retries": fault_stats["retries"],
                        "frontend_degraded": fault_stats["degraded"],
                        "frontend_degraded_pins": fault_stats[
                            "degraded_pins"
                        ],
                        "frontend_failed": fault_stats["failed"],
                    },
                    "join_rows_out": j_rows,
                    "join_serve_stage_ms": join_stages,
                    "hybrid_join_indexed_p50_ms": ms(hybrid_idx),
                    "hybrid_join_indexed_iqr_ms": iqr_ms(hybrid_idx),
                    "hybrid_join_unindexed_p50_ms": ms(hybrid_raw),
                    "hybrid_join_unindexed_iqr_ms": iqr_ms(hybrid_raw),
                    "hybrid_join_speedup": round(
                        hybrid_raw["p50"] / hybrid_idx["p50"], 3
                    ),
                    "hybrid_join_cached_p50_ms": ms(hybrid_cached),
                    "hybrid_join_cached_iqr_ms": iqr_ms(hybrid_cached),
                    "hybrid_join_cached_speedup": round(
                        hybrid_raw["p50"] / hybrid_cached["p50"], 3
                    ),
                    "hybrid_join_cached_delta_p50_ms": ms(hybrid_cached_delta),
                    "hybrid_join_cached_delta_iqr_ms": iqr_ms(
                        hybrid_cached_delta
                    ),
                    "hybrid_serve_stage_ms": hybrid_stages,
                    "hybrid_index_served": hybrid_served,
                    "delta_incr_refresh_s": round(delta_refresh, 3),
                    "delta_refresh_rows_per_sec": round(n_append / delta_refresh),
                    "zorder_range_indexed_p50_ms": ms(zrange_idx),
                    "zorder_range_indexed_iqr_ms": iqr_ms(zrange_idx),
                    "zorder_range_unindexed_p50_ms": ms(zrange_raw),
                    "zorder_range_unindexed_iqr_ms": iqr_ms(zrange_raw),
                    "zorder_range_speedup": round(
                        zrange_raw["p50"] / zrange_idx["p50"], 3
                    ),
                    "zorder_range_pruneoff_p50_ms": ms(zrange_off),
                    "zorder_range_pruneoff_iqr_ms": iqr_ms(zrange_off),
                    "zorder_prune": zprune,
                    "zorder_range_rows_out": z_rows,
                    "ds_prune_indexed_p50_ms": ms(ds_idx_t),
                    "ds_prune_indexed_iqr_ms": iqr_ms(ds_idx_t),
                    "ds_prune_unindexed_p50_ms": ms(ds_raw_t),
                    "ds_prune_unindexed_iqr_ms": iqr_ms(ds_raw_t),
                    "ds_prune_speedup": round(
                        ds_raw_t["p50"] / ds_idx_t["p50"], 3
                    ),
                    "ds_prune_files_scanned": ds_files,
                    "ds_prune_files_total": ds_total,
                    "build_ladder": ladder,
                    "mesh_ladder": mesh_ladder,
                    "stream_ladder": stream_ladder,
                    "residency": residency,
                }
            )
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
