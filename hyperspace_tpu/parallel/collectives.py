"""COLLECTIVE_SITES — the registry of cross-process collective call sites.

The SHARED_STATE doctrine applied to the multi-host plane: every
collective / cross-process barrier call site in the package declares its
*symmetry contract* HERE, so "does every process issue the same
collective program?" is a mechanical question (``hslint`` HS8xx,
``analysis/spmd.py``), not a code-review hope. PR 11's review had to
hand-fix a whole class of collective-symmetry bugs — zero-row processes
skipping the ``all_to_all``, waves planned over per-process file lists,
barriers reachable from only some processes — and Exoshuffle (PAPERS.md)
shows shuffle planes live or die by exactly this property. The runtime
collective witness (``testing/collective_witness.py``) wraps the sites
named here during the multi-host dryrun and cross-checks each process's
*recorded* collective sequence against the others (``hslint
--witness``).

Entry shape::

    "<dotted path of the module-level callable>": (
        "<collective op it issues (all_to_all, ppermute, ...)>",
        "<contract>",
        "<one-line justification — why the contract holds>",
    )

Site paths must name MODULE-LEVEL callables (the witness wraps them by
module-attribute replacement; in-module callers resolve the name through
module globals at call time, so the wrapper is seen everywhere).
Contracts:

``symmetric-all``
    Every process issues the call at the same position in its collective
    sequence with the same payload signature (shapes/dtypes/static
    args). The strictest contract — the SPMD requirement for
    ``shard_map`` collectives, whose compiled programs hang or corrupt
    when any participant diverges.
``per-host-lane``
    Every process issues the call at the same sequence position, but the
    payload is that process's own lane data (per-host row subsets,
    local count matrices), so signatures may differ across processes.
``coordinator-gated``
    Only the coordinator (process 0) issues the call — the metadata
    plane's single-writer seams. The witness treats an occurrence on any
    other process as a hard contract violation, and HS801 accepts
    ``is_coordinator`` branches that gate exactly these sites.

Keep this module stdlib-only and import-cheap: the collective witness
imports it inside dryrun worker processes before jax is initialized, and
the analyzer only ever parses it.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: the known symmetry contracts (HS802 rejects anything else)
CONTRACTS = ("symmetric-all", "per-host-lane", "coordinator-gated")

COLLECTIVE_SITES: Dict[str, Tuple[str, str, str]] = {
    # -- bootstrap ------------------------------------------------------------
    "hyperspace_tpu.parallel.mesh.initialize_distributed": (
        "distributed.initialize",
        "per-host-lane",
        "every process joins the one jax job at the same protocol step "
        "but carries its OWN process_id (the per-host payload); topology "
        "parameters agree, and idempotent re-entry is a no-op everywhere",
    ),
    # -- exchange-strategy device programs (parallel/shuffle.py) -------------
    "hyperspace_tpu.parallel.shuffle._flat_program": (
        "all_to_all",
        "symmetric-all",
        "single-controller shard_map program: cap and payload structure "
        "are computed from global inputs, so every trace sees identical "
        "shapes (never reached on a multi-process job — resolve_strategy "
        "coerces to twostage)",
    ),
    "hyperspace_tpu.parallel.shuffle._compact_program": (
        "all_to_all",
        "symmetric-all",
        "single-controller shard_map program over host-packed exact-extent "
        "buffers; slot caps derive from the global count matrix (never "
        "reached on a multi-process job)",
    ),
    "hyperspace_tpu.parallel.shuffle._twostage_program": (
        "ppermute",
        "symmetric-all",
        "H-1 ppermute rounds over the dcn axis with STATIC per-round caps "
        "taken from the allgathered count matrix — every process compiles "
        "and issues the identical round sequence",
    ),
    "hyperspace_tpu.parallel.shuffle._twostage_exchange_mp": (
        "process_allgather",
        "per-host-lane",
        "each process contributes its own [H, L] send-count matrix; the "
        "allgather runs at the same position on every process and its "
        "result makes every later shape decision global",
    ),
    # -- build metadata plane (indexes/covering_build.py) --------------------
    "hyperspace_tpu.indexes.covering_build._global_written": (
        "sync_global_devices",
        "per-host-lane",
        "every process reaches the post-write barrier with its own "
        "written-file subset and returns the identical global union "
        "listing; reachable from every write_bucketed exit path, zero-row "
        "stripes included",
    ),
    # -- action protocol (actions/base.py) -----------------------------------
    "hyperspace_tpu.actions.base._action_rendezvous": (
        "process_allgather",
        "per-host-lane",
        "the action protocol's abort-aware rendezvous: every process "
        "allgathers its own step verdict at the same protocol step, so "
        "a one-sided failure aborts the job everywhere instead of "
        "leaving peers blocked, and no worker enters the data plane "
        "before the coordinator's begin entry exists",
    ),
    "hyperspace_tpu.actions.base._publish_log": (
        "log_write",
        "coordinator-gated",
        "operation-log OCC writes are single-writer by design: only the "
        "coordinator publishes begin/commit entries; workers already hold "
        "the global file list via _global_written",
    ),
    "hyperspace_tpu.actions.base._publish_latest_stable": (
        "log_write",
        "coordinator-gated",
        "latestStable pointer publish rides the same single-writer "
        "metadata seam as the log entries themselves",
    ),
}
