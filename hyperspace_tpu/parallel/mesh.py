"""Session device mesh.

The reference's execution substrate is a Spark cluster (driver +
executors); ours is a 1-D ``jax.sharding.Mesh`` over all addressable
devices — the "executors" are mesh shards, the host Python process is the
driver. Multi-host scaling is the same code: ``jax.devices()`` spans hosts
under ``jax.distributed``, collectives ride ICI within a slice and DCN
across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

SHARD_AXIS = "shard"


def default_mesh(devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return jax.sharding.Mesh(np.array(devs), (SHARD_AXIS,))


class MeshRuntime:
    """Lazily-built mesh owned by a session (one per HyperspaceSession)."""

    def __init__(self, devices: Optional[Sequence] = None):
        self._devices = devices
        self._mesh: Optional[jax.sharding.Mesh] = None

    @property
    def mesh(self) -> jax.sharding.Mesh:
        if self._mesh is None:
            self._mesh = default_mesh(self._devices)
        return self._mesh

    @property
    def num_shards(self) -> int:
        return self.mesh.devices.size
