"""Session device mesh + multi-host bootstrap.

The reference's execution substrate is a Spark cluster (driver +
executors); ours is a 1-D ``jax.sharding.Mesh`` over all addressable
devices — the "executors" are mesh shards, the host Python process is the
driver. Multi-host scaling is the same code: after
:func:`initialize_distributed`, ``jax.devices()`` spans every host
(process-major order, so consecutive mesh positions are ICI neighbors
within a host's chips) and the same ``shard_map`` collectives ride ICI
within a slice and DCN across hosts. The DCN-aware layout and the
collective plan for a v5e-64 are documented in ``docs/MULTIHOST.md``;
``scripts/dryrun_multihost.py`` exercises this bootstrap as 2 real
processes x 4 CPU devices.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np

SHARD_AXIS = "shard"
# hierarchical mesh axes: DCN (cross-host) outer, ICI (intra-host) inner
DCN_AXIS = "dcn"
ICI_AXIS = "ici"

_DISTRIBUTED_INITIALIZED = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    cpu_local_devices: Optional[int] = None,
) -> None:
    """Join a multi-host job (idempotent). Call BEFORE creating a
    HyperspaceSession on every process.

    On TPU pods the three job parameters come from the runtime
    environment and may be omitted (``jax.distributed.initialize()``
    auto-detects). On CPU — the simulation used by tests and the
    multi-host dryrun — the coordination service needs them explicitly,
    plus the gloo cross-process collectives backend and a forced local
    device count (``cpu_local_devices``).

    Registered in ``COLLECTIVE_SITES`` (``parallel/collectives.py``):
    the bootstrap is itself part of the collective program the HS8xx
    sanitizer and the runtime collective witness check.
    """
    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED:
        return
    explicit = (coordinator_address, num_processes, process_id)
    if any(v is not None for v in explicit) and any(
        v is None for v in explicit
    ):
        raise ValueError(
            "initialize_distributed needs coordinator_address, "
            "num_processes AND process_id together (explicit job), or "
            f"none of them (auto-detected TPU pod); got {explicit}"
        )
    if cpu_local_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", int(cpu_local_devices))
        except AttributeError:
            # older jax: the option predates jax_num_cpu_devices — fall
            # back to the XLA flag, honored as long as no backend has
            # been initialized yet (this function's contract: call
            # before any session / device use)
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + " --xla_force_host_platform_device_count="
                    + str(int(cpu_local_devices))
                ).strip()
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
    _DISTRIBUTED_INITIALIZED = True


def bucket_owner_groups(
    bucket_ids: Sequence[int], num_shards: int, min_tasks: int = 1
):
    """Index groups of ``bucket_ids`` by owner shard — THE bucket
    ownership layout (``bucket % num_shards``, the same routing the
    build shuffle uses), shared by the sharded build/serve tails so the
    mapping lives in one place. Returns a list of position lists, one
    per occupied shard, ascending shard id.

    ``min_tasks`` splits large groups WITHIN a shard (chunks never cross
    an ownership boundary) until at least that many task units exist —
    a 2-shard mesh must not cap a thread fan-out below the caller's
    worker budget when there are buckets to spare. Callers always
    collect results per bucket position, so any grouping yields
    identical output; only scheduling changes."""
    groups: dict = {}
    for i, b in enumerate(bucket_ids):
        groups.setdefault(int(b) % num_shards, []).append(i)
    ordered = [groups[s] for s in sorted(groups)]
    if min_tasks <= len(ordered):
        return ordered
    chunks_per = -(-min_tasks // len(ordered))  # ceil
    out = []
    for g in ordered:
        size = -(-len(g) // chunks_per)
        out.extend(g[i : i + size] for i in range(0, len(g), size))
    return out


def default_mesh(devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """The flat data-plane mesh: ONE shard axis over every addressable
    device. ``jax.devices()`` is process-major, so the axis is
    ICI-contiguous per host and XLA routes the shuffle's ``all_to_all``
    over ICI within a host and DCN across hosts."""
    devs = list(devices) if devices is not None else jax.devices()
    return jax.sharding.Mesh(np.array(devs), (SHARD_AXIS,))


def hierarchical_mesh() -> jax.sharding.Mesh:
    """The (dcn, ici) 2-D mesh over all hosts: outer axis = process,
    inner axis = that process's local devices. The layout for
    DCN-minimizing two-stage collectives (docs/MULTIHOST.md): reduce or
    exchange over ``ici`` first (fast, within-host), then once over
    ``dcn``."""
    procs = jax.process_count()
    local = jax.local_device_count()
    devs = np.array(jax.devices()).reshape(procs, local)
    return jax.sharding.Mesh(devs, (DCN_AXIS, ICI_AXIS))


class MeshRuntime:
    """Lazily-built mesh owned by a session (one per HyperspaceSession)."""

    def __init__(self, devices: Optional[Sequence] = None):
        self._devices = devices
        self._mesh: Optional[jax.sharding.Mesh] = None

    @property
    def mesh(self) -> jax.sharding.Mesh:
        if self._mesh is None:
            self._mesh = default_mesh(self._devices)
        return self._mesh

    @property
    def num_shards(self) -> int:
        return self.mesh.devices.size

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def is_coordinator(self) -> bool:
        """Process 0 owns the metadata plane (action protocol, log OCC
        writes) on a multi-host job — the driver role of the reference's
        Spark driver (SURVEY §2.11 driver/executor row)."""
        return jax.process_index() == 0
