"""Distributed layer: device mesh + collectives.

TPU-native replacement for the reference's reliance on Spark's shuffle /
broadcast machinery (SURVEY §2.11, §5 "Distributed communication backend"):
``shard_map`` + XLA collectives (``all_to_all`` for bucketing shuffles,
``all_gather`` for broadcast/stats, ``psum`` for aggregates) over a
``jax.sharding.Mesh`` whose axis rides ICI within a slice and DCN across
hosts.
"""

from hyperspace_tpu.parallel.mesh import MeshRuntime, default_mesh
from hyperspace_tpu.parallel.shuffle import bucket_shuffle

__all__ = ["MeshRuntime", "default_mesh", "bucket_shuffle"]
