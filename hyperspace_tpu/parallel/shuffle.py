"""Bucketing shuffle: ``shard_map`` + ``lax.all_to_all`` over the mesh.

TPU-native replacement for the Spark hash-partition shuffle at the heart of
the covering-index build (reference:
``index/covering/CoveringIndex.scala:58-61`` ``repartition(numBuckets,
indexedCols)`` and the Hybrid-Scan on-the-fly shuffle,
``covering/CoveringIndexRuleUtils.scala:357-417``).

Each device hashes its local rows to buckets (``ops/hash.py``), routes rows
to the device that owns the bucket (``bucket % D``), and exchanges them in
ONE ``all_to_all`` over the ICI ring. Since XLA programs need static
shapes, each device sends a ``[D, cap]`` buffer plus a validity mask, where
``cap`` is the power-of-two-padded MAX per-(shard, peer) count computed on
the host before dispatch — exchange memory tracks real traffic (~n_local
for a balanced hash) instead of the worst-case ``D x n_local``; the host
compacts valid rows after the exchange.
(For >HBM datasets the same exchange runs once per wave over chunked host
batches — the reference leans on Spark's disk-backed shuffle for this;
our wave loop is ``indexes/covering_build._write_bucketed_streaming``,
driven by ``hyperspace.index.build.memoryBudgetBytes``.)
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

_log = logging.getLogger("hyperspace_tpu.shuffle")

# Telemetry of the most recent ``bucket_shuffle`` (host-observed, set by
# ``_exchange_cap``): exchange capacity and the per-(shard, peer)
# send-count skew. The exchange pads every (shard, peer) slot to the MAX
# count, so one hot bucket inflates exchange memory by ~skew× silently —
# the build copies this into its telemetry and the bench publishes it.
last_shuffle_stats: Dict[str, float] = {}

from hyperspace_tpu.ops.hash import hash_columns
from hyperspace_tpu.parallel.mesh import SHARD_AXIS

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_buckets", "num_payload", "seed", "cap")
)
def _shuffle_program(
    mesh, key_reps, valid, payloads, num_buckets, num_payload, seed, cap
):
    """The compiled multi-chip shuffle. Shapes: key_reps [k, N], valid [N],
    payloads tuple of [N]-arrays; N divisible by D = mesh size.

    ``cap`` is the per-(shard, peer) send capacity, computed on the host
    from the actual destination counts and padded to a power of two. The
    exchange buffer is [D, cap] per shard — sized to the real traffic —
    instead of the worst-case [D, n_local] (which inflates memory D× and
    was flagged as the first thing to OOM on a large mesh)."""
    del num_payload  # encoded in payloads pytree structure
    D = mesh.devices.size

    def local(reps, vld, cols):
        n = reps.shape[1]
        bucket = (hash_columns(reps, seed) % jnp.uint32(num_buckets)).astype(
            jnp.int32
        )
        # invalid (padding) rows route to sentinel destination D: they
        # never occupy exchange slots, so cap tracks VALID traffic only
        # (host counts valid rows only; see _exchange_cap)
        dest = jnp.where(vld, bucket % D, jnp.int32(D))
        order = jnp.argsort(dest, stable=True)
        dest_s = dest[order]
        counts = jnp.bincount(dest_s, length=D + 1)
        offsets = jnp.concatenate(
            [jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        rank = jnp.arange(n) - offsets[dest_s]

        def scatter(col, fill=0):
            buf = jnp.full((D, cap), fill, dtype=col.dtype)
            # valid rows have dest_s < D and rank < cap (host-sized);
            # sentinel-dest rows index row D and are dropped by .at[]'s
            # out-of-bounds semantics. bucket_shuffle re-checks the
            # compacted row count, so an undersized cap fails loudly.
            return buf.at[dest_s, rank].set(col[order])

        exchange = lambda x: lax.all_to_all(x, SHARD_AXIS, 0, 0, tiled=True)
        recv_bucket = exchange(scatter(bucket))
        recv_valid = exchange(scatter(vld.astype(jnp.bool_), fill=False))
        recv_cols = tuple(exchange(scatter(c)) for c in cols)
        # Flatten the per-peer dimension; sort locally by (valid desc,
        # bucket, keys) so each bucket is one contiguous run and invalid
        # slots sink to the tail.
        flat_bucket = recv_bucket.reshape(-1)
        flat_valid = recv_valid.reshape(-1)
        flat_cols = tuple(c.reshape(-1) for c in recv_cols)
        sort_bucket = jnp.where(flat_valid, flat_bucket, jnp.int32(num_buckets))
        perm = jnp.argsort(sort_bucket, stable=True)
        return (
            flat_bucket[perm],
            flat_valid[perm],
            tuple(c[perm] for c in flat_cols),
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
    )(key_reps, valid, payloads)


def bucket_shuffle(
    mesh,
    key_reps: np.ndarray,
    payloads: Sequence[np.ndarray],
    num_buckets: int,
    seed: int = 42,
    with_shard_offsets: bool = False,
):
    """Host entry: shuffle rows into bucket-contiguous order across the mesh.

    Returns ``(bucket_ids, payload_cols)`` with all rows grouped by bucket
    (global order: all rows of buckets owned by shard 0, then shard 1, …;
    within a shard, ascending bucket id). The caller does the final
    within-bucket key sort (``ops/sort.py``) before writing.

    ``with_shard_offsets=True`` additionally returns the ``[D+1]`` row
    offsets of each shard's compacted slice — rows
    ``offsets[s]:offsets[s+1]`` are exactly the buckets shard ``s`` owns
    (``bucket % D == s``), the handle the sharded build/serve tail needs
    to keep bucket ownership device-local past the exchange.
    """
    from hyperspace_tpu.ops import pad_len

    D = mesh.devices.size
    n = key_reps.shape[1]
    # power-of-two row count (ops/__init__ shape policy), then round up to
    # a multiple of D so shard_map divides evenly
    target = pad_len(n)
    target += (-target) % D
    pad = target - n
    if pad:
        key_reps = np.pad(key_reps, ((0, 0), (0, pad)))
        payloads = [np.pad(p, (0, pad)) for p in payloads]
    valid = np.ones(n + pad, dtype=bool)
    if pad:
        valid[n:] = False
    cap = _exchange_cap(key_reps, valid, num_buckets, D, seed)
    bucket, vmask, cols = _shuffle_program(
        mesh,
        jnp.asarray(key_reps),
        jnp.asarray(valid),
        tuple(jnp.asarray(p) for p in payloads),
        num_buckets,
        len(payloads),
        seed,
        cap,
    )
    bucket = np.asarray(bucket)
    vmask = np.asarray(vmask)
    keep = np.nonzero(vmask)[0]
    if len(keep) != n:
        raise RuntimeError(
            f"bucket shuffle lost rows: sent {n}, received {len(keep)} "
            f"(cap={cap}) — host/device hash divergence?"
        )
    out = bucket[keep], [np.asarray(c)[keep] for c in cols]
    if not with_shard_offsets:
        return out
    # shard s's post-exchange slice is rows [s*D*cap, (s+1)*D*cap) of the
    # flat output; its compacted extent is the valid count per slice
    per_shard = vmask.reshape(D, D * cap).sum(axis=1)
    offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(per_shard, dtype=np.int64)]
    )
    return out[0], out[1], offsets


def _exchange_cap(
    key_reps: np.ndarray,
    valid: np.ndarray,
    num_buckets: int,
    D: int,
    seed: int,
    chunk: int = 1 << 18,
) -> int:
    """Per-(shard, peer) exchange capacity: the power-of-two-padded MAX
    count of VALID rows any shard sends to any peer. Host-only (chunked
    numpy murmur3, bit-identical to the device hash — never dispatches
    the unsharded array to one device) and pad rows are excluded (the
    program routes them to a sentinel destination)."""
    from hyperspace_tpu.ops import pad_len
    from hyperspace_tpu.ops.hash import bucket_ids_host

    from hyperspace_tpu.constants import (
        BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS,
        BUILD_SHUFFLE_SKEW_WARN_RATIO,
    )

    total = key_reps.shape[1]
    n_local = total // D
    counts = np.zeros((D, D), dtype=np.int64)
    for start in range(0, total, chunk):
        end = min(start + chunk, total)
        dest = bucket_ids_host(key_reps[:, start:end], num_buckets, seed) % D
        shard = np.arange(start, end) // n_local
        v = valid[start:end]
        np.add.at(counts, (shard[v], dest[v]), 1)
    max_count = max(int(counts.max()), 1)
    cap = min(pad_len(max_count), n_local)  # never larger than a shard
    # skew telemetry: the [D, cap] exchange buffers pad every slot to the
    # hottest (shard, peer) count, so memory = skew × the balanced cost
    mean_count = float(counts.mean())
    skew = max_count / mean_count if mean_count > 0 else 1.0
    # publish as ONE atomic rebind, never clear()+update(): a concurrent
    # build copying the snapshot (covering_build telemetry) must see a
    # whole dict, old or new — never the empty window between the two
    # mutations (SHARED_STATE policy: rebind-only)
    global last_shuffle_stats
    last_shuffle_stats = {
        "devices": float(D),
        "cap": float(cap),
        "max_peer_count": float(max_count),
        "mean_peer_count": round(mean_count, 1),
        "skew_ratio": round(skew, 2),
    }
    if (
        skew > BUILD_SHUFFLE_SKEW_WARN_RATIO
        and max_count >= BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS
    ):
        _log.warning(
            "bucket shuffle skew: hottest (shard, peer) slot carries "
            "%.1fx the mean row count (max=%d, mean=%.0f, D=%d) — the "
            "padded exchange buffers inflate accordingly; consider more "
            "buckets or less skewed key columns",
            skew,
            max_count,
            mean_count,
            D,
        )
    return cap
