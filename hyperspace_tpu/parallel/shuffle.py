"""Exchange-strategy plane: pluggable bucketing shuffles over the mesh.

TPU-native replacement for the Spark hash-partition shuffle at the heart
of the covering-index build (reference: ``index/covering/CoveringIndex.
scala:58-61`` ``repartition(numBuckets, indexedCols)``), rebuilt as a
*library of exchange strategies* behind one interface — the Exoshuffle
doctrine (PAPERS.md): shuffle belongs to the application as composable
strategies, not one engine-baked implementation. The strategy is chosen
per build by ``hyperspace.build.exchange.strategy`` (default ``auto``:
per-machine/topology resolution, see :func:`resolve_strategy`):

``flat``
    The original single ``lax.all_to_all`` over the flat shard axis:
    every device scatters rows into a padded ``[D, cap]`` buffer (cap =
    power-of-two-padded max per-(shard, peer) count) and sorts the
    received rows by bucket on device. The baseline every other strategy
    is differential-tested against, and the default on a single-host
    accelerator mesh.
``compact``
    Host-packed variable-length exchange: the host bucket ids computed
    for capacity planning drive an exact-extent pack on the host (slot
    per (source, peer) pair, cap = exact max count — no power-of-two
    blowup), the device program is ONE ``all_to_all`` per payload with
    no on-device hashing, scatter or argsort, and the host unpacks via
    the closed-form receive position of every row. Moves only the
    payload bytes (no bucket/validity planes).
``host``
    No device round-trip at all: rows are reordered in host RAM with the
    canonical post-exchange permutation (threaded native/numpy gathers).
    The CPU-simulation default — an emulated ICI exchange on a CPU mesh
    pays real pack/argsort/copy costs to move rows between host buffers
    that live in the same RAM (39s of the 51s 64M/mesh8 build,
    MULTICHIP_r06) — and the per-host leg of a multi-host decomposition.
``twostage``
    The DCN/ICI decomposition from docs/MULTIHOST.md: the intra-host leg
    runs host-side (each host re-groups its rows in RAM by destination
    lane), and the cross-host leg is one ``ppermute`` round per peer
    host over the ``dcn`` mesh axis with **per-peer slot caps** sized
    from the per-(shard, peer) count matrix — the skew telemetry from
    the ``[D, cap]`` era becomes the slot-sizing input instead of only a
    warning (one hot destination host inflates only the rounds that
    target it, not every slot).

Every strategy produces BIT-IDENTICAL output to ``flat``: the flat
program's post-exchange order is exactly the valid rows stable-sorted by
``(bucket % D, bucket)`` with ties in original row order (received rows
concatenate source-major per peer, sources hold local row order, and the
final per-shard sort is a stable sort by bucket), so
:func:`canonical_order` reproduces it host-side from the bucket ids
alone. ``tests/test_exchange_strategies.py`` makes that argument
mechanical across mesh sizes, payload types and skews.

(For >HBM datasets the same exchange runs once per wave over chunked
host batches — the wave loop is ``indexes/covering_build.
_write_bucketed_streaming``, driven by
``hyperspace.index.build.memoryBudgetBytes``.)

Every device program here that issues a collective (``_flat_program``,
``_compact_program``, ``_twostage_program``, and the
``process_allgather`` in ``_twostage_exchange_mp``) is registered in
``COLLECTIVE_SITES`` (``parallel/collectives.py``) with its symmetry
contract — add a collective without registering it and hslint HS802
goes red; the multi-host dryrun's collective witness then has to
exercise it (HS703/HS804).
"""

from __future__ import annotations

import functools
import logging
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

_log = logging.getLogger("hyperspace_tpu.shuffle")

# Telemetry of the most recent ``bucket_shuffle`` (host-observed):
# strategy name, pack/exchange/unpack stage seconds, exchange capacity
# and the per-(shard, peer) send-count skew. The padded-buffer
# strategies size slots from the MAX count, so one hot bucket inflates
# exchange memory by ~skew× silently — the build copies this into its
# telemetry (accumulating per-wave skew as max/mean) and the bench
# publishes it.
last_shuffle_stats: Dict[str, float] = {}

# Once-per-build latch for the shuffle-skew warning: the streaming build
# runs one exchange per wave and the same skew would otherwise log every
# wave. ``covering_build.reset_build_breakdown`` rearms it at each data
# op via :func:`reset_skew_warning`; telemetry records the ratio for
# every wave regardless.
_skew_warned: bool = False

from hyperspace_tpu.ops.hash import bucket_ids_host
from hyperspace_tpu.ops.sort import partition_by_bucket
from hyperspace_tpu.parallel.mesh import DCN_AXIS, ICI_AXIS, SHARD_AXIS

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

STRATEGY_AUTO = "auto"
STRATEGY_FLAT = "flat"
STRATEGY_COMPACT = "compact"
STRATEGY_HOST = "host"
STRATEGY_TWOSTAGE = "twostage"
STRATEGIES = (
    STRATEGY_FLAT,
    STRATEGY_COMPACT,
    STRATEGY_HOST,
    STRATEGY_TWOSTAGE,
)


def reset_skew_warning() -> None:
    """Rearm the once-per-build skew warning (called by
    ``covering_build.reset_build_breakdown`` at every data-op entry)."""
    global _skew_warned
    _skew_warned = False


# ---------------------------------------------------------------------------
# Shared host-side planning: bucket ids, counts, canonical order
# ---------------------------------------------------------------------------


def _host_bucket_ids(
    key_reps: np.ndarray, num_buckets: int, seed: int, chunk: int = 1 << 18
) -> np.ndarray:
    """Chunked host murmur3 bucket ids — bit-identical to the device
    hash (``ops/hash.py`` twins) and computed ONCE per exchange: every
    strategy reuses these ids for capacity planning, packing and
    ordering instead of re-hashing on device (the old flat program
    hashed every row a second time)."""
    n = key_reps.shape[1]
    out = np.empty(n, dtype=np.int32)
    for start in range(0, n, chunk):
        end = min(start + chunk, n)
        out[start:end] = bucket_ids_host(
            key_reps[:, start:end], num_buckets, seed
        )
    return out


def _peer_counts(
    owner: np.ndarray, valid: Optional[np.ndarray], n_local: int, D: int
) -> np.ndarray:
    """``[D, D]`` count of valid rows each source shard (contiguous
    ``n_local``-row blocks) sends to each owner shard — the slot-sizing
    and skew-telemetry input of every padded strategy."""
    src = (np.arange(len(owner)) // n_local).astype(np.int64)
    if valid is not None:
        src, owner = src[valid], owner[valid]
    return np.bincount(src * D + owner, minlength=D * D).reshape(D, D)


def _publish_stats(
    strategy: str, D: int, cap: int, counts: np.ndarray, extra: Dict
) -> None:
    """Build the telemetry snapshot + once-per-build skew warning.

    Publishes as ONE atomic rebind, never clear()+update(): a concurrent
    build copying the snapshot (covering_build telemetry) must see a
    whole dict, old or new — never the empty window between the two
    mutations (SHARED_STATE policy: rebind-only)."""
    from hyperspace_tpu.constants import (
        BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS,
        BUILD_SHUFFLE_SKEW_WARN_RATIO,
    )

    max_count = int(counts.max()) if counts.size else 0
    mean_count = float(counts.mean()) if counts.size else 0.0
    skew = max_count / mean_count if mean_count > 0 else 1.0
    stats: Dict = {
        "strategy": strategy,
        "devices": float(D),
        "cap": float(cap),
        "max_peer_count": float(max_count),
        "mean_peer_count": round(mean_count, 1),
        "skew_ratio": round(skew, 2),
    }
    stats.update(extra)
    global last_shuffle_stats, _skew_warned
    last_shuffle_stats = stats
    # stage spans from the exchange's own measured seconds (obs plane,
    # OBS_SITES-registered): the fused shuffle pass is opaque to any
    # outer timer, so only this built-in measurement can explain it
    from hyperspace_tpu.obs import trace as _obs_trace

    for _stage_name in ("pack", "exchange", "unpack"):
        _sec = extra.get(f"{_stage_name}_s")
        if _sec:
            _obs_trace.stage(_stage_name, seconds=float(_sec))
    if (
        skew > BUILD_SHUFFLE_SKEW_WARN_RATIO
        and max_count >= BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS
        and not _skew_warned
    ):
        _skew_warned = True
        _log.warning(
            "bucket shuffle skew: hottest (shard, peer) slot carries "
            "%.1fx the mean row count (max=%d, mean=%.0f, D=%d, "
            "strategy=%s) — padded exchange slots inflate accordingly; "
            "consider more buckets or less skewed key columns "
            "(warned once per build; telemetry records every wave)",
            skew,
            max_count,
            mean_count,
            D,
            strategy,
        )


def canonical_order(
    bucket_ids: np.ndarray, num_buckets: int, D: int
) -> Tuple[np.ndarray, np.ndarray]:
    """THE post-exchange row order, host-side: a stable permutation
    sorting rows by ``(owner = bucket % D, bucket)`` (ties keep original
    row order), plus the ``[D+1]`` per-owner-shard row extents.

    This reproduces the flat ``all_to_all`` output exactly: shard ``s``
    holds the buckets it owns in ascending bucket order, and within a
    bucket the received rows concatenate source-shard-major with each
    source's rows in local (= original) order — i.e. ascending original
    row index. Computed as a counting scatter over owner-major-remapped
    bucket ids (native ``hs_partition_by_bucket`` above its dispatch
    threshold), O(n)."""
    b = np.arange(num_buckets, dtype=np.int64)
    owner_rank = np.lexsort((b, b % D))  # buckets in (owner, bucket) order
    remap = np.empty(num_buckets, dtype=np.int32)
    remap[owner_rank] = np.arange(num_buckets, dtype=np.int32)
    order, offsets = partition_by_bucket(remap[bucket_ids], num_buckets)
    per_bucket = np.diff(offsets)
    per_owner = np.bincount(
        owner_rank % D, weights=per_bucket, minlength=D
    ).astype(np.int64)
    shard_offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(per_owner)]
    )
    return order, shard_offsets


def _shape_cap(exact: int) -> int:
    """Slot capacity rounded up to 3 significant bits (next multiple of
    ``2^(floor(log2 n) - 2)``): a streaming build's waves have slightly
    different max peer counts, and an EXACT cap would re-trace the
    exchange program once per wave. Three significant bits bound the
    padding at <25% (vs up to 2x for the flat path's power-of-two cap)
    while keeping the number of distinct compile shapes per octave at 4.
    Correctness never depends on it — the unpack reads exact per-peer
    extents from the count matrix either way."""
    exact = max(int(exact), 1)
    if exact <= 8:
        return exact
    step = 1 << (exact.bit_length() - 3)
    return -(-exact // step) * step


def _pair_ranks(slot_ids: np.ndarray, num_slots: int) -> np.ndarray:
    """Rank of each row within its (source, destination) slot, in
    original row order — the within-slot position the host pack and the
    closed-form receive positions share."""
    order, offsets = partition_by_bucket(slot_ids, num_slots)
    within = np.arange(len(order), dtype=np.int64) - np.repeat(
        offsets[:-1], np.diff(offsets)
    )
    rank = np.empty(len(order), dtype=np.int64)
    rank[order] = within
    return rank


def _threaded_gather(
    arrays: Sequence[np.ndarray], idx: np.ndarray
) -> List[np.ndarray]:
    """``[a[idx] for a in arrays]`` with per-column threading: 8-byte
    dtypes ride the threaded native gather (``hs_gather_*``, releases
    the GIL), the rest plain numpy. The "threaded numpy slicing" leg of
    the host-side exchange."""
    from concurrent.futures import ThreadPoolExecutor

    from hyperspace_tpu import native
    from hyperspace_tpu.io.columnar import _gather

    workers = min(len(arrays), max(1, min(native._cores(), 8)))
    if workers <= 1 or len(idx) < (1 << 16):
        return [_gather(a, idx) for a in arrays]
    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="hs-exchange"
    ) as pool:
        return list(pool.map(lambda a: _gather(a, idx), arrays))


# ---------------------------------------------------------------------------
# Strategy: flat all_to_all (the baseline)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_buckets", "num_payload", "cap")
)
def _flat_program(mesh, bucket_host, valid, payloads, num_buckets, num_payload, cap):
    """The compiled flat multi-chip shuffle. Shapes: bucket_host [N]
    int32 (HOST-computed ids — the device no longer re-hashes; the host
    twin is bit-exact and already computed for capacity planning), valid
    [N], payloads tuple of [N]-arrays; N divisible by D = mesh size.

    ``cap`` is the per-(shard, peer) send capacity, computed on the host
    from the actual destination counts and padded to a power of two. The
    exchange buffer is [D, cap] per shard — sized to the real traffic —
    instead of the worst-case [D, n_local] (which inflates memory D× and
    was flagged as the first thing to OOM on a large mesh)."""
    del num_payload  # encoded in payloads pytree structure
    D = mesh.devices.size

    def local(bkt, vld, cols):
        n = bkt.shape[0]
        # invalid (padding) rows route to sentinel destination D: they
        # never occupy exchange slots, so cap tracks VALID traffic only
        # (host counts valid rows only)
        dest = jnp.where(vld, bkt % D, jnp.int32(D))
        order = jnp.argsort(dest, stable=True)
        dest_s = dest[order]
        counts = jnp.bincount(dest_s, length=D + 1)
        offsets = jnp.concatenate(
            [jnp.zeros(1, dtype=counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        rank = jnp.arange(n) - offsets[dest_s]

        def scatter(col, fill=0):
            buf = jnp.full((D, cap), fill, dtype=col.dtype)
            # valid rows have dest_s < D and rank < cap (host-sized);
            # sentinel-dest rows index row D and are dropped by .at[]'s
            # out-of-bounds semantics. bucket_shuffle re-checks the
            # compacted row count, so an undersized cap fails loudly.
            return buf.at[dest_s, rank].set(col[order])

        exchange = lambda x: lax.all_to_all(x, SHARD_AXIS, 0, 0, tiled=True)
        recv_bucket = exchange(scatter(bkt))
        recv_valid = exchange(scatter(vld.astype(jnp.bool_), fill=False))
        recv_cols = tuple(exchange(scatter(c)) for c in cols)
        # Flatten the per-peer dimension; sort locally by (valid desc,
        # bucket) so each bucket is one contiguous run and invalid
        # slots sink to the tail.
        flat_bucket = recv_bucket.reshape(-1)
        flat_valid = recv_valid.reshape(-1)
        flat_cols = tuple(c.reshape(-1) for c in recv_cols)
        sort_bucket = jnp.where(flat_valid, flat_bucket, jnp.int32(num_buckets))
        perm = jnp.argsort(sort_bucket, stable=True)
        return (
            flat_bucket[perm],
            flat_valid[perm],
            tuple(c[perm] for c in flat_cols),
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
    )(bucket_host, valid, payloads)


def _process_local_operand(hmesh, local_block: np.ndarray):
    """This process's ``[1, L, B]`` send block -> the globally-sharded
    ``[H, L, B]`` device operand, built via
    ``make_array_from_process_local_data`` so the feed never round-trips
    through process 0 (docs/MULTIHOST.md; exercised by
    ``scripts/dryrun_multihost.py``)."""
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(hmesh, P(DCN_AXIS, ICI_AXIS)),
        np.ascontiguousarray(local_block),
    )


def _flat_exchange(mesh, key_reps, payloads, num_buckets, seed):
    """Strategy ``flat`` — the original padded-[D, cap] all_to_all path,
    kept as the baseline (and single-host accelerator default)."""
    from hyperspace_tpu.ops import pad_len

    D = mesh.devices.size
    n = key_reps.shape[1]
    t0 = _time.perf_counter()
    # power-of-two row count (ops/__init__ shape policy), then round up
    # to a multiple of D so shard_map divides evenly
    target = pad_len(n)
    target += (-target) % D
    pad = target - n
    if pad:
        key_reps = np.pad(key_reps, ((0, 0), (0, pad)))
        payloads = [np.pad(p, (0, pad)) for p in payloads]
    valid = np.ones(n + pad, dtype=bool)
    if pad:
        valid[n:] = False
    bucket_host = _host_bucket_ids(key_reps, num_buckets, seed)
    cap, counts = _flat_cap(bucket_host, valid, D)
    pack_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    bucket, vmask, cols = _flat_program(
        mesh,
        jnp.asarray(bucket_host),
        jnp.asarray(valid),
        tuple(jnp.asarray(p) for p in payloads),
        num_buckets,
        len(payloads),
        cap,
    )
    bucket = np.asarray(bucket)
    vmask = np.asarray(vmask)
    exchange_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    keep = np.nonzero(vmask)[0]
    if len(keep) != n:
        raise RuntimeError(
            f"bucket shuffle lost rows: sent {n}, received {len(keep)} "
            f"(cap={cap}) — host/device hash divergence?"
        )
    out_bucket = bucket[keep]
    out_cols = [np.asarray(c)[keep] for c in cols]
    # shard s's post-exchange slice is rows [s*D*cap, (s+1)*D*cap) of
    # the flat output; its compacted extent is the valid count per slice
    per_shard = vmask.reshape(D, D * cap).sum(axis=1)
    offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(per_shard, dtype=np.int64)]
    )
    unpack_s = _time.perf_counter() - t0
    _publish_stats(
        STRATEGY_FLAT,
        D,
        cap,
        counts,
        _timing(pack_s, exchange_s, unpack_s),
    )
    return out_bucket, out_cols, offsets


def _flat_cap(
    bucket_host: np.ndarray, valid: np.ndarray, D: int
) -> Tuple[int, np.ndarray]:
    """(cap, counts) for the flat program: the power-of-two-padded MAX
    count of VALID rows any shard sends to any peer, never larger than a
    shard's slice."""
    from hyperspace_tpu.ops import pad_len

    n_local = len(bucket_host) // D
    counts = _peer_counts(bucket_host % D, valid, n_local, D)
    max_count = max(int(counts.max()), 1)
    return min(pad_len(max_count), n_local), counts


def _timing(pack_s: float, exchange_s: float, unpack_s: float) -> Dict:
    return {
        "pack_s": round(pack_s, 4),
        "exchange_s": round(exchange_s, 4),
        "unpack_s": round(unpack_s, 4),
    }


def _exchange_cap(
    key_reps: np.ndarray,
    valid: np.ndarray,
    num_buckets: int,
    D: int,
    seed: int,
    chunk: int = 1 << 18,
) -> int:
    """Back-compat capacity probe (tests): per-(shard, peer) exchange
    capacity of the flat strategy for an already-padded input, also
    publishing the skew telemetry snapshot."""
    ids = _host_bucket_ids(key_reps, num_buckets, seed, chunk)
    cap, counts = _flat_cap(ids, valid, D)
    _publish_stats(STRATEGY_FLAT, D, cap, counts, {})
    return cap


# ---------------------------------------------------------------------------
# Strategy: host-side exchange (no device round trip)
# ---------------------------------------------------------------------------


def _host_exchange(mesh, key_reps, payloads, num_buckets, seed):
    """Strategy ``host`` — the exchange as a pure host reorder.

    On a CPU mesh the "exchange" moves rows between buffers that live in
    the same RAM; emulating ICI (pad, scatter, collective, device
    argsorts, host↔device copies) is pure overhead. The canonical
    permutation is computed once from the host bucket ids and applied
    with threaded native/numpy gathers. Also the per-host leg of a
    multi-host decomposition (each host regrouping its local rows)."""
    D = mesh.devices.size
    n = key_reps.shape[1]
    t0 = _time.perf_counter()
    bucket_ids = _host_bucket_ids(key_reps, num_buckets, seed)
    n_local = -(-n // D) if n else 1
    counts = _peer_counts(bucket_ids % D, None, n_local, D)
    perm, shard_offsets = canonical_order(bucket_ids, num_buckets, D)
    pack_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    out_cols = _threaded_gather(payloads, perm)
    out_bucket = bucket_ids[perm]
    exchange_s = _time.perf_counter() - t0
    _publish_stats(
        STRATEGY_HOST,
        D,
        int(counts.max()) if counts.size else 0,
        counts,
        _timing(pack_s, exchange_s, 0.0),
    )
    return out_bucket, out_cols, shard_offsets


# ---------------------------------------------------------------------------
# Strategy: compact variable-length exchange
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("mesh",))
def _compact_program(mesh, payloads):
    """ONE tiled all_to_all per payload — no on-device hashing, scatter
    or argsort; the host packed exact (source, peer) extents and unpacks
    by closed-form receive positions."""

    def local(cols):
        return tuple(
            lax.all_to_all(c, SHARD_AXIS, 0, 0, tiled=True) for c in cols
        )

    return shard_map(
        local, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P(SHARD_AXIS)
    )(payloads)


def _compact_exchange(mesh, key_reps, payloads, num_buckets, seed):
    """Strategy ``compact`` — host-packed exact-extent device exchange.

    The host bucket ids drive a counting-scatter pack into ``[D*D,
    cap]`` send buffers (slot per (source, peer) pair, cap = the exact
    max count — not power-of-two padded), each payload rides one
    ``all_to_all``, and the unpack gathers each row from its closed-form
    receive position ``(owner*D + source)*cap + rank`` straight into
    canonical order. Compared to ``flat`` this drops the second hash
    pass, both device argsorts, the bucket/validity planes from the
    wire, and the pow2 cap blowup; the exchanged bytes are exactly
    ``D*D*cap`` slots per payload."""
    D = mesh.devices.size
    n = key_reps.shape[1]
    t0 = _time.perf_counter()
    bucket_ids = _host_bucket_ids(key_reps, num_buckets, seed)
    owner = bucket_ids % D
    n_local = -(-n // D) if n else 1
    src = (np.arange(n, dtype=np.int64) // n_local).astype(np.int64)
    counts = _peer_counts(owner, None, n_local, D)
    cap = _shape_cap(counts.max())
    slot = (src * D + owner).astype(np.int32)
    rank = _pair_ranks(slot, D * D)
    send_pos = slot.astype(np.int64) * cap + rank
    recv_pos = (owner.astype(np.int64) * D + src) * cap + rank
    sends = []
    for p in payloads:
        buf = np.zeros(D * D * cap, dtype=p.dtype)
        buf[send_pos] = p
        sends.append(buf.reshape(D * D, cap))
    pack_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    out = _compact_program(
        mesh,
        tuple(jnp.asarray(s) for s in sends),
    )
    flats = [np.asarray(o).reshape(-1) for o in out]
    exchange_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    out_perm, shard_offsets = canonical_order(bucket_ids, num_buckets, D)
    gather_idx = recv_pos[out_perm]
    out_cols = _threaded_gather(flats, gather_idx)
    out_bucket = bucket_ids[out_perm]
    unpack_s = _time.perf_counter() - t0
    _publish_stats(
        STRATEGY_COMPACT,
        D,
        cap,
        counts,
        _timing(pack_s, exchange_s, unpack_s),
    )
    return out_bucket, out_cols, shard_offsets


# ---------------------------------------------------------------------------
# Strategy: two-stage DCN/ICI decomposition
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("hmesh", "caps"))
def _twostage_program(hmesh, payloads, caps):
    """The cross-host leg: one ``ppermute`` per peer host over the
    ``dcn`` axis, each round's slot sized to ITS (source host, peer
    host) max — skew-aware per-peer caps, not one global max. The
    intra-host leg already ran host-side (rows were packed into their
    destination ici lane's buffer), so no ``ici`` collective is needed
    — lane l's buffer lands on device (dest_host, l) directly."""
    H = hmesh.shape[DCN_AXIS]
    offs = [0]  # static slice offsets from the static per-round caps
    for c in caps:
        offs.append(offs[-1] + c)

    def local(cols):
        def route(x):
            b = x.reshape(-1)
            parts = [b[offs[0] : offs[1]]]  # round 0: rows staying on-host
            for r in range(1, H):
                seg = b[offs[r] : offs[r + 1]]
                parts.append(
                    lax.ppermute(
                        seg,
                        DCN_AXIS,
                        [(h, (h + r) % H) for h in range(H)],
                    )
                )
            return jnp.concatenate(parts).reshape(x.shape)

        return tuple(route(c) for c in cols)

    return shard_map(
        local,
        mesh=hmesh,
        in_specs=(P(DCN_AXIS, ICI_AXIS),),
        out_specs=P(DCN_AXIS, ICI_AXIS),
    )(payloads)


def hierarchical_view(mesh, hosts: int):
    """(H, L) (dcn, ici) mesh over the SAME devices as the flat build
    mesh — process-major device order makes row h the h-th host's
    devices on a real multi-host job; on a single-controller simulation
    ``hosts`` carves the flat mesh into simulated hosts."""
    D = mesh.devices.size
    if D % hosts:
        raise ValueError(
            f"twostage exchange: {hosts} hosts do not divide the "
            f"{D}-device mesh"
        )
    return jax.sharding.Mesh(
        mesh.devices.reshape(hosts, D // hosts), (DCN_AXIS, ICI_AXIS)
    )


def _twostage_exchange_mp(mesh, key_reps, payloads, num_buckets, seed):
    """The REAL multi-host leg of the twostage strategy: every process
    passes only ITS rows (the per-host scan feed — global row order is
    process-major) and receives back only the rows of the buckets its
    local devices own, in canonical order, plus ``[D+1]`` shard extents
    in which non-local shards are empty.

    Same slot layout as the single-controller simulation, built
    per-process: the host-side ici leg packs local rows into their
    destination lane's buffer, caps come from a ``process_allgather`` of
    the per-(host, lane) count matrix (every process must compile the
    same SPMD shapes), the send block feeds the global array via
    ``make_array_from_process_local_data`` (no round-trip through
    process 0), bucket ids ride as one extra int32 payload (the receiver
    cannot re-derive them without re-hashing), and the local unpack
    stable-sorts each lane's received rows by (bucket, source host,
    slot rank) — exactly the canonical (bucket, global row) order.
    Exercised cross-process by ``scripts/dryrun_multihost.py``."""
    from jax.experimental import multihost_utils as mhu

    H = jax.process_count()
    pid = jax.process_index()
    D = mesh.devices.size
    L = D // H
    n = key_reps.shape[1]
    t0 = _time.perf_counter()
    bucket_ids = _host_bucket_ids(key_reps, num_buckets, seed)
    owner = bucket_ids % D
    dst_h = owner // L
    lane = owner % L
    rnd = (dst_h - pid) % H
    hl_local = np.bincount(dst_h * L + lane, minlength=H * L).reshape(H, L)
    hl_all = np.asarray(mhu.process_allgather(hl_local))  # [H, H, L]
    caps = tuple(
        _shape_cap(hl_all[np.arange(H), (np.arange(H) + r) % H, :].max())
        for r in range(H)
    )
    offs = np.concatenate([[0], np.cumsum(caps)]).astype(np.int64)
    B = int(offs[-1])
    rank = _pair_ranks(owner.astype(np.int32), D)
    send_pos = lane * B + offs[rnd] + rank
    sends = []
    for p in [bucket_ids] + list(payloads):
        buf = np.zeros(L * B, dtype=p.dtype)
        buf[send_pos] = p
        sends.append(buf.reshape(1, L, B))
    pack_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    hmesh = hierarchical_view(mesh, H)
    out = _twostage_program(
        hmesh, tuple(_process_local_operand(hmesh, s) for s in sends), caps
    )
    local = []
    for arr in out:
        shards = sorted(arr.addressable_shards, key=lambda s: s.index)
        local.append(
            np.concatenate(
                [np.asarray(s.data).reshape(-1) for s in shards]
            ).reshape(L, B)
        )
    exchange_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    recv_ids, recv_cols = local[0], local[1:]
    # valid extents per (lane, round) from the global count matrix; round
    # r of lane l carries hl_all[(pid - r) % H, pid, l] rows — reorder
    # rounds by SOURCE HOST so concatenation follows global row order
    out_bucket_parts: List[np.ndarray] = []
    out_col_parts: List[List[np.ndarray]] = [[] for _ in recv_cols]
    per_shard = np.zeros(D, dtype=np.int64)
    for l in range(L):
        ids_parts, col_parts = [], [[] for _ in recv_cols]
        for src_h in range(H):
            r = (pid - src_h) % H
            cnt = int(hl_all[src_h, pid, l])
            lo = int(offs[r])
            ids_parts.append(recv_ids[l, lo : lo + cnt])
            for i, c in enumerate(recv_cols):
                col_parts[i].append(c[l, lo : lo + cnt])
        ids_l = np.concatenate(ids_parts)
        order = np.argsort(ids_l, kind="stable")
        out_bucket_parts.append(ids_l[order])
        for i in range(len(recv_cols)):
            out_col_parts[i].append(np.concatenate(col_parts[i])[order])
        per_shard[pid * L + l] = len(ids_l)
    out_bucket = (
        np.concatenate(out_bucket_parts)
        if out_bucket_parts
        else np.zeros(0, dtype=np.int32)
    )
    out_cols = [
        np.concatenate(parts)
        if parts
        else np.zeros(0, dtype=c.dtype)
        for parts, c in zip(out_col_parts, recv_cols)
    ]
    shard_offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(per_shard)]
    )
    expect = int(hl_all[:, pid, :].sum())
    if len(out_bucket) != expect:
        raise RuntimeError(
            f"multi-host bucket shuffle lost rows on process {pid}: "
            f"expected {expect}, received {len(out_bucket)}"
        )
    unpack_s = _time.perf_counter() - t0
    _publish_stats(
        STRATEGY_TWOSTAGE,
        D,
        int(max(caps)),
        hl_all[pid],  # this process's per-(peer host, lane) send counts
        {
            "hosts": float(H),
            "process_local": 1.0,
            "round_cap_max": float(max(caps)),
            "round_cap_min": float(min(caps)),
            **_timing(pack_s, exchange_s, unpack_s),
        },
    )
    return out_bucket, out_cols, shard_offsets


def _twostage_exchange(mesh, key_reps, payloads, num_buckets, seed, hosts):
    """Strategy ``twostage`` — docs/MULTIHOST.md's DCN/ICI decomposition.

    Intra-host leg on the host: each host's rows are packed (in RAM) into
    per-(peer-host, destination-lane) slots, aggregating its L devices'
    sends into one buffer per peer host. Cross-host leg on the device:
    H-1 ``ppermute`` rounds over ``dcn``, round r's slot sized to
    ``max(count[src_host → (src_host+r) % H host, lane])`` — the
    per-(shard, peer) count matrix (the skew telemetry) IS the slot
    sizing, so a hot destination host inflates only the rounds that
    target it. Row volume over DCN is unchanged vs flat; message count
    per host drops to one buffer per peer host and no row pays a second
    device hash or argsort.

    On a REAL multi-process job the per-process variant runs instead
    (:func:`_twostage_exchange_mp`): per-host inputs, per-host outputs,
    ``make_array_from_process_local_data`` feed. The single-controller
    body below simulates the same decomposition by carving the flat mesh
    into ``hosts`` groups of contiguous devices."""
    if jax.process_count() > 1:
        return _twostage_exchange_mp(mesh, key_reps, payloads, num_buckets, seed)
    D = mesh.devices.size
    H = int(hosts) if hosts and hosts > 0 else max(jax.process_count(), 1)
    H = min(H, D)
    while D % H:
        H -= 1
    L = D // H
    n = key_reps.shape[1]
    t0 = _time.perf_counter()
    bucket_ids = _host_bucket_ids(key_reps, num_buckets, seed)
    owner = bucket_ids % D
    n_local = -(-n // D) if n else 1
    counts = _peer_counts(owner, None, n_local, D)
    src_dev = (np.arange(n, dtype=np.int64) // n_local).astype(np.int64)
    src_h = src_dev // L
    dst_h = owner // L
    lane = owner % L
    rnd = (dst_h - src_h) % H
    # per-round slot caps from the count matrix, uniform over (host,
    # lane) senders of that round (SPMD shapes must agree) but NOT over
    # rounds — the skew-aware sizing
    hl_counts = np.bincount(
        (src_h * H + dst_h) * L + lane, minlength=H * H * L
    ).reshape(H, H, L)
    caps = tuple(
        _shape_cap(hl_counts[np.arange(H), (np.arange(H) + r) % H, :].max())
        for r in range(H)
    )
    offs = np.concatenate([[0], np.cumsum(caps)]).astype(np.int64)
    B = int(offs[-1])
    slot = ((src_h * H + dst_h) * L + lane).astype(np.int32)
    rank = _pair_ranks(slot, H * H * L)
    # sender of a row is device (src_h, lane): the host already moved it
    # to its destination lane's buffer (the RAM ici leg)
    send_pos = (src_h * L + lane) * B + offs[rnd] + rank
    recv_pos = (dst_h * L + lane) * B + offs[rnd] + rank
    sends = []
    for p in payloads:
        buf = np.zeros(D * B, dtype=p.dtype)
        buf[send_pos] = p
        sends.append(buf.reshape(H, L, B))
    pack_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    hmesh = hierarchical_view(mesh, H)
    out = _twostage_program(
        hmesh,
        tuple(jnp.asarray(s) for s in sends),
        caps,
    )
    flats = [np.asarray(o).reshape(-1) for o in out]
    exchange_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    out_perm, shard_offsets = canonical_order(bucket_ids, num_buckets, D)
    gather_idx = recv_pos[out_perm]
    out_cols = _threaded_gather(flats, gather_idx)
    out_bucket = bucket_ids[out_perm]
    unpack_s = _time.perf_counter() - t0
    _publish_stats(
        STRATEGY_TWOSTAGE,
        D,
        int(max(caps)),
        counts,
        {
            "hosts": float(H),
            "round_cap_max": float(max(caps)),
            "round_cap_min": float(min(caps)),
            **_timing(pack_s, exchange_s, unpack_s),
        },
    )
    return out_bucket, out_cols, shard_offsets


# ---------------------------------------------------------------------------
# Resolution + host entry
# ---------------------------------------------------------------------------


def resolve_strategy(strategy: str, mesh, n_rows: int) -> str:
    """Map the configured strategy (``hyperspace.build.exchange.
    strategy``) to a concrete one. ``auto``:

    * multi-process job → ``twostage`` (the DCN leg is the bottleneck;
      docs/MULTIHOST.md);
    * CPU mesh → ``host`` (the simulation must never pay ICI-emulation
      costs);
    * single-host accelerator → ``compact`` when the calibration probe
      measured it beating ``flat`` at this row count
      (``exchange_compact_min_rows``), else ``flat`` (the baseline and
      TPU default).
    """
    s = (strategy or STRATEGY_AUTO).strip().lower()
    if s != STRATEGY_AUTO and s not in STRATEGIES:
        raise ValueError(
            f"unknown exchange strategy {strategy!r}; expected one of "
            f"{(STRATEGY_AUTO,) + STRATEGIES}"
        )
    if jax.process_count() > 1:
        # a multi-process job has per-host inputs; only the twostage
        # decomposition moves rows across the process boundary
        if s not in (STRATEGY_AUTO, STRATEGY_TWOSTAGE):
            _log.debug(
                "exchange strategy %r coerced to twostage on a "
                "multi-process job",
                s,
            )
        return STRATEGY_TWOSTAGE
    if s != STRATEGY_AUTO:
        return s
    if mesh.devices.flat[0].platform == "cpu":
        return STRATEGY_HOST
    from hyperspace_tpu.native import calibrate

    t = calibrate.thresholds().exchange_compact_min_rows
    if t and n_rows >= t:
        return STRATEGY_COMPACT
    return STRATEGY_FLAT


def bucket_shuffle(
    mesh,
    key_reps: np.ndarray,
    payloads: Sequence[np.ndarray],
    num_buckets: int,
    seed: int = 42,
    with_shard_offsets: bool = False,
    strategy: str = STRATEGY_AUTO,
    twostage_hosts: int = 0,
):
    """Host entry: shuffle rows into bucket-contiguous order across the
    mesh, via the selected exchange strategy (see module docstring).

    Returns ``(bucket_ids, payload_cols)`` with all rows grouped by
    bucket (global order: all rows of buckets owned by shard 0, then
    shard 1, …; within a shard, ascending bucket id; within a bucket,
    original row order). Every strategy produces bit-identical output.
    The caller does the final within-bucket key sort (``ops/sort.py``)
    before writing.

    ``with_shard_offsets=True`` additionally returns the ``[D+1]`` row
    offsets of each shard's slice — rows ``offsets[s]:offsets[s+1]`` are
    exactly the buckets shard ``s`` owns (``bucket % D == s``), the
    handle the sharded build/serve tail needs to keep bucket ownership
    device-local past the exchange. A peer that owns no rows gets an
    empty extent.
    """
    payloads = list(payloads)
    name = resolve_strategy(strategy, mesh, key_reps.shape[1])
    if name == STRATEGY_FLAT:
        bucket, cols, offsets = _flat_exchange(
            mesh, key_reps, payloads, num_buckets, seed
        )
    elif name == STRATEGY_HOST:
        bucket, cols, offsets = _host_exchange(
            mesh, key_reps, payloads, num_buckets, seed
        )
    elif name == STRATEGY_COMPACT:
        bucket, cols, offsets = _compact_exchange(
            mesh, key_reps, payloads, num_buckets, seed
        )
    else:
        bucket, cols, offsets = _twostage_exchange(
            mesh, key_reps, payloads, num_buckets, seed, twostage_hosts
        )
    if with_shard_offsets:
        return bucket, cols, offsets
    return bucket, cols
