"""Fused serve-pipeline compiler (docs/serve-compiler.md).

The serve path used to execute a ``Filter→Project→Aggregate`` subtree as
a chain of individually-fast vectorized ops separated by materialized
numpy intermediates: evaluate the mask (2 passes/conjunct), ``nonzero``
it, gather EVERY needed column into a filtered copy, radix-lexsort the
group planes to factorize, then run one ``ufunc.at`` reduction per
aggregate. Flare's argument (PAPERS.md) is that the win comes from
compiling the *query's* pipeline end to end; this module does that for
the hottest serve shape: it detects the subtree over a pruned index
scan in ``executor._exec`` and lowers it to ONE fused native pass per
row-group chunk (``hs_fused_filter_agg``) that evaluates the conjunct
predicates, groups, and folds partial COUNT/SUM/MIN/MAX in a single
sweep — no mask, no filtered batch, no factorize. Partials are carried
across chunks (reads overlap compute on the shared ``scan_pool``) and
merged once at the edge. Plain ``Filter→Project`` lowers to a fused
select (``hs_fused_filter_select``): pass/fail and index compaction in
one pass, with the existing threaded native gathers doing the
projection.

Parity contract (the ``KERNEL_TWINS`` doctrine generalized from single
kernels to whole pipelines): the interpreted chain stays in place as
the differential twin (:func:`interpreted_filter_aggregate` /
:func:`filter_select_interpreted`), the fused pass is bit-identical to
it — including float-sum accumulation order (the kernel is deliberately
sequential over rows, exactly like ``np.add.at``), numpy's
replace-on-equal min/max rule, NULL/NaN/-0.0 group canonicalization
(``Column.key_rep``), group output order (ascending key-rep planes) and
first-occurrence group key values — and
``hyperspace.serve.fusedpipeline.enabled=false`` restores the old
op-at-a-time path. One scoped caveat: above ``_HOST_AGG_MAX_ROWS``
(1M FILTERED rows, ``ops/aggregate.py``) the interpreted chain itself
hands float sums to the device segment ops, which may reassociate —
there fused ≡ interpreted holds exactly for everything except float
SUM/AVG ulps, the same caveat the host/device switch already carries. Dispatch is calibrated per machine like every other
kernel (``native_fused_pipeline_min_rows``, probe v5).

Lowered shapes are cached in the serve cache under ``("fusedplan", …)``
keys (evictable via ``ServeCache.evict_kind``); anything outside the
supported shape — non-conjunct predicates, string/bool/sub-8-byte
group keys or aggregate inputs, hybrid unions, delete compensation —
falls back to the interpreted chain unchanged.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu import constants as C
from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Project,
    Scan,
    _agg_output_type,
)

# Telemetry of the LAST fused execution in this process (bench +
# tests assert the fused path actually ran): mode "agg" | "select",
# rows scanned vs rows passed, group count, chunk count, wall seconds.
last_fused_stats: Dict[str, Any] = {}

# Telemetry of the LAST metadata-plane aggregate (docs/agg-serve.md):
# how many row groups were answered from persisted partials vs scanned
# vs provably empty, and how many rows the boundary chunks actually read
# — the smoke gate asserts row_groups_scanned == 0 for a fully-covered
# point aggregate.
last_aggplane_stats: Dict[str, Any] = {}


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

# At or above this SCANNED-row count the fused native pass dispatches;
# below it the interpreted chain's vectorized numpy twins win on
# kernel-call overhead. FALLBACK DEFAULT: the effective threshold comes
# from the per-machine calibration probe (native/calibrate.py, probe
# v5); an explicit module-attribute override wins (tests, bench A/B).
_NATIVE_FUSED_PIPELINE_MIN_ROWS_DEFAULT = C.NATIVE_FUSED_PIPELINE_MIN_ROWS_DEFAULT
_NATIVE_FUSED_PIPELINE_MIN_ROWS = _NATIVE_FUSED_PIPELINE_MIN_ROWS_DEFAULT


def _native_fused_pipeline_min_rows() -> int:
    if _NATIVE_FUSED_PIPELINE_MIN_ROWS != _NATIVE_FUSED_PIPELINE_MIN_ROWS_DEFAULT:
        return _NATIVE_FUSED_PIPELINE_MIN_ROWS  # explicit override wins
    from hyperspace_tpu.native import calibrate

    return (
        calibrate.thresholds().native_fused_pipeline_min_rows
        or _NATIVE_FUSED_PIPELINE_MIN_ROWS
    )


def fused_pipeline_on(session) -> bool:
    """``hyperspace.serve.fusedpipeline.enabled`` (default on). Like the
    range plane — and unlike the join pipeline's thread fan-out — this
    also applies to sessionless execution: the fused pass is a pure
    compute substitution with identical output."""
    if session is None:
        return C.SERVE_FUSEDPIPELINE_ENABLED_DEFAULT
    return session.conf.serve_fusedpipeline_enabled


# ---------------------------------------------------------------------------
# Type lowering
# ---------------------------------------------------------------------------


def _np_kind(t: pa.DataType) -> str:
    """The decoded numpy dtype KIND a column of arrow type ``t`` gets
    from ``Column.from_arrow`` — the pre-read half of the batch-based
    kind check in ``ops/filter.lower_range_terms``."""
    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return "S"
    if pa.types.is_boolean(t):
        return "b"
    if pa.types.is_unsigned_integer(t):
        return "u"
    if pa.types.is_integer(t):
        return "i"
    if pa.types.is_floating(t):
        return "f"
    if pa.types.is_temporal(t):
        return "i"  # datetime64/timedelta64 int views, time32 → int32
    return "O"


def _fusable_f64(t: pa.DataType) -> Optional[bool]:
    """True → decodes to a float64 array, False → an 8-byte int64-view
    array (int64 / datetime64 / timedelta64), None → not fusable (the
    interpreted chain keeps the column). Mirrors ``Column.from_arrow``:
    time32 decodes to int32 (4 bytes), float32 stays float32 — both out."""
    if pa.types.is_float64(t):
        return True
    if pa.types.is_int64(t):
        return False
    if (
        pa.types.is_timestamp(t)
        or pa.types.is_date(t)
        or pa.types.is_duration(t)
        or pa.types.is_time64(t)
    ):
        return False
    return None


def _col_arr_8b(col: Column) -> Optional[np.ndarray]:
    """The contiguous 8-byte kernel view of a numeric column (float64
    as-is, int64/datetime/timedelta as an int64 view), or None."""
    if col.kind != "numeric":
        return None
    v = col.values
    if v.ndim != 1 or v.dtype.itemsize != 8:
        return None
    if v.dtype.kind == "f":
        if v.dtype != np.float64:
            return None
        arr = v
    elif v.dtype.kind in "iMm":
        arr = v.view(np.int64)
    else:
        return None
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr


# ---------------------------------------------------------------------------
# Interpreted twins (the KERNEL_TWINS references; hslint HS105 requires
# fused-pipeline exports to register these, not a numpy single op)
# ---------------------------------------------------------------------------


def filter_select_interpreted(batch: ColumnarBatch, terms) -> np.ndarray:
    """The interpreted chain ``hs_fused_filter_select`` replaces: the
    fused numpy mask, then ``np.nonzero`` — ascending passing-row
    indices, what ``ColumnarBatch.filter`` gathers through."""
    from hyperspace_tpu.ops.filter import range_mask_numpy

    return np.nonzero(range_mask_numpy(batch, terms))[0]


def interpreted_filter_aggregate(
    batch: ColumnarBatch, terms, group_by, aggs, child_schema
) -> ColumnarBatch:
    """The interpreted chain ``hs_fused_filter_agg`` replaces: fused
    numpy mask → materialized filtered batch → hash-aggregate
    (factorize + segment reductions). The differential twin every fused
    result is compared against, bit for bit."""
    from hyperspace_tpu.execution.aggregate_exec import execute_aggregate
    from hyperspace_tpu.ops.filter import range_mask_numpy

    fb = batch.filter(range_mask_numpy(batch, terms))
    return execute_aggregate(fb, list(group_by), list(aggs), child_schema)


# ---------------------------------------------------------------------------
# Plan lowering
# ---------------------------------------------------------------------------

# Kernel agg op codes (hs_fused_filter_agg):
_OP_COUNT_STAR = 0
_OP_COUNT_COL = 1
_OP_SUM_I64 = 2
_OP_SUM_F64 = 3
_OP_MIN_I64 = 4
_OP_MAX_I64 = 5
_OP_MIN_F64 = 6
_OP_MAX_F64 = 7


@dataclasses.dataclass(frozen=True)
class FusedAggPlan:
    """A compiled Filter→Aggregate lowering: everything derivable from
    (condition, group_by, aggs, schema) alone — no per-query row state —
    so it is cacheable under a ``("fusedplan", fingerprint, …)`` serve-
    cache key and reusable across serves of the same index version."""

    read_cols: Tuple[str, ...]
    terms: Tuple  # lower_range_terms output
    term_f64: Tuple[bool, ...]
    bounds: Tuple  # (lo_i, hi_i, lo_f, hi_f, flags) — native_range_bounds
    group_by: Tuple[str, ...]
    key_f64: Tuple[bool, ...]
    key_types: Tuple
    agg_ops: Tuple[Tuple[int, Optional[str]], ...]
    aggs: Tuple
    out_types: Tuple

    # what the LRU accounting charges: symbolic lowering only
    nbytes: int = 2048


def _lower_from_terms(
    terms,
    group_by: Sequence[str],
    aggs,
    child_schema,
    rel_col_order: Optional[Sequence[str]] = None,
) -> Optional[FusedAggPlan]:
    """FusedAggPlan from ALREADY-LOWERED range terms (tests and the
    calibration probe construct terms directly), or None when a group
    key / aggregate input / term column is outside the fused type set."""
    if terms is None or len(group_by) > 16:
        return None
    term_f64 = []
    for name, *_rest in terms:
        if name not in child_schema:
            return None
        f64 = _fusable_f64(child_schema[name])
        if f64 is None:
            return None
        term_f64.append(f64)
    from hyperspace_tpu.ops.filter import NEVER_MATCH, native_range_bounds

    bounds = native_range_bounds(terms, term_f64)
    if bounds is None or bounds == NEVER_MATCH:
        # unrepresentable / never-matching bounds: the interpreted chain
        # decides (rare, and an all-pruned scan is already fast)
        return None
    key_f64 = []
    key_types = []
    for c in group_by:
        f64 = _fusable_f64(child_schema[c])
        if f64 is None:
            return None
        key_f64.append(f64)
        key_types.append(child_schema[c])
    agg_ops: List[Tuple[int, Optional[str]]] = []
    out_types = []
    for spec in aggs:
        out_types.append(_agg_output_type(spec, child_schema))
        if spec.func == "count":
            if spec.column is None:
                agg_ops.append((_OP_COUNT_STAR, None))
            else:
                # COUNT(col) only reads the valid mask: any column type
                # (strings included) is countable
                agg_ops.append((_OP_COUNT_COL, spec.column))
            continue
        f64 = _fusable_f64(child_schema[spec.column])
        if f64 is None:
            return None
        if spec.func in ("sum", "avg"):
            agg_ops.append((_OP_SUM_F64 if f64 else _OP_SUM_I64, spec.column))
        elif spec.func == "min":
            agg_ops.append((_OP_MIN_F64 if f64 else _OP_MIN_I64, spec.column))
        else:  # max
            agg_ops.append((_OP_MAX_F64 if f64 else _OP_MAX_I64, spec.column))
    needed = set(group_by) | {t[0] for t in terms} | {
        c for _op, c in agg_ops if c is not None
    }
    order = rel_col_order if rel_col_order is not None else sorted(needed)
    read_cols = tuple(c for c in order if c in needed)
    return FusedAggPlan(
        read_cols=read_cols,
        terms=tuple(terms),
        term_f64=tuple(term_f64),
        bounds=tuple(bounds),
        group_by=tuple(group_by),
        key_f64=tuple(key_f64),
        key_types=tuple(key_types),
        agg_ops=tuple(agg_ops),
        aggs=tuple(aggs),
        out_types=tuple(out_types),
    )


def _lower_fused_agg(
    cond: E.Expr,
    group_by,
    aggs,
    child_schema,
    rel_col_order=None,
) -> Optional[FusedAggPlan]:
    from hyperspace_tpu.ops.filter import lower_range_terms_typed

    cols = {
        name: (_np_kind(t), t) for name, t in child_schema.items()
    }
    terms = lower_range_terms_typed(cond, cols)
    if terms is None:
        return None
    return _lower_from_terms(terms, group_by, aggs, child_schema, rel_col_order)


# ---------------------------------------------------------------------------
# Accumulator state (carried across row-group chunks)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AggPartials:
    """The PUBLIC snapshot of one fused aggregation's carried chunk
    state — the stable hook through which the build-time sidecar capture
    (``indexes/aggindex.py``), the serve-time metadata merge and the
    kernel sweep all share ONE state layout instead of re-deriving it.

    Arrays are sliced to the live group count ``G``; group order is the
    producer's insertion/first-occurrence order (output ordering happens
    once, in :func:`finalize_partials`). Per agg slot the accumulators
    mean exactly what the kernel's mean: ``acc_cnt`` = valid-row count
    (passing-row count for COUNT(*)), ``acc_i`` = wrapped int64 sums or
    int min/max (identity-filled when the group has no valid rows),
    ``acc_f`` = float sums or min/max over CLEAN (non-NaN valid) values,
    ``acc_aux`` = the float min/max side channel (clean count for MIN,
    NaN count for MAX)."""

    n_groups: int
    rows_scanned: int
    rows_passed: int
    g_reps: np.ndarray  # (nk, G) canonical key reps (Column.key_rep)
    g_nulls: np.ndarray  # (nk, G) uint8 null plane
    g_kvals: np.ndarray  # (nk, G) first-occurrence raw key bits (int64 view)
    g_kvalid: np.ndarray  # (nk, G) uint8 validity of the stored key value
    key_has_validity: Tuple[bool, ...]
    acc_i: np.ndarray  # (na, G) int64 accumulators
    acc_f: np.ndarray  # (na, G) float64 accumulators
    acc_cnt: np.ndarray  # (na, G) valid/pass counts
    acc_aux: np.ndarray  # (na, G) float min/max aux counts


class _AggState:
    """Python-owned state of one fused aggregation: the group hash
    table, per-group key identity + first-occurrence values, and the
    per-agg accumulators, all sized ``cap`` and grown geometrically when
    the kernel reports a full table (it stops BEFORE the overflowing
    row; growth rebuilds the hash table from the stored group hashes
    inside the kernel, so Python never re-implements the hash)."""

    _INIT_CAP = 1024

    def __init__(self, plan: FusedAggPlan):
        self.plan = plan
        self.cap = self._INIT_CAP
        self._alloc(self.cap)
        self.n_groups = 1 if not plan.group_by else 0
        self.rows_passed = 0
        self.rows_scanned = 0
        self.chunks = 0
        self.key_has_validity = [False] * len(plan.group_by)
        self.rebuild = False

    def _alloc(self, cap: int) -> None:
        nk = len(self.plan.group_by)
        na = len(self.plan.agg_ops)
        self.ht = np.full(cap * 4, -1, dtype=np.int64)
        self.g_hash = np.zeros(cap, dtype=np.int64)
        self.g_reps = np.zeros((nk, cap), dtype=np.int64)
        self.g_nulls = np.zeros((nk, cap), dtype=np.uint8)
        self.g_kvals = np.zeros((nk, cap), dtype=np.int64)
        self.g_kvalid = np.zeros((nk, cap), dtype=np.uint8)
        self.acc_i = np.zeros((na, cap), dtype=np.int64)
        self.acc_f = np.zeros((na, cap), dtype=np.float64)
        self.acc_cnt = np.zeros((na, cap), dtype=np.int64)
        self.acc_aux = np.zeros((na, cap), dtype=np.int64)
        self._init_acc(0)

    def _init_acc(self, start: int) -> None:
        for a, (op, _c) in enumerate(self.plan.agg_ops):
            if op == _OP_MIN_I64:
                self.acc_i[a, start:] = np.iinfo(np.int64).max
            elif op == _OP_MAX_I64:
                self.acc_i[a, start:] = np.iinfo(np.int64).min
            elif op == _OP_MIN_F64:
                self.acc_f[a, start:] = np.inf
            elif op == _OP_MAX_F64:
                self.acc_f[a, start:] = -np.inf

    def _grow(self) -> None:
        old = (
            self.g_hash, self.g_reps, self.g_nulls, self.g_kvals,
            self.g_kvalid, self.acc_i, self.acc_f, self.acc_cnt,
            self.acc_aux,
        )
        self.cap *= 4
        self._alloc(self.cap)
        g = self.n_groups
        for dst, src in zip(
            (
                self.g_hash, self.g_reps, self.g_nulls, self.g_kvals,
                self.g_kvalid, self.acc_i, self.acc_f, self.acc_cnt,
                self.acc_aux,
            ),
            old,
        ):
            dst[..., :g] = src[..., :g]
        self.rebuild = True

    def accumulate(self, batch: ColumnarBatch) -> bool:
        """Fold one chunk into the state (False = native unavailable or
        a column fell outside the fused set — caller runs the
        interpreted chain instead)."""
        from hyperspace_tpu import native

        plan = self.plan
        n = batch.num_rows
        self.rows_scanned += n
        self.chunks += 1
        if n == 0:
            return True
        f_cols, f_valids = [], []
        for name, *_rest in plan.terms:
            col = batch.column(name)
            arr = _col_arr_8b(col)
            if arr is None:
                return False
            f_cols.append(arr)
            f_valids.append(col.validity)
        k_cols, k_valids = [], []
        for j, name in enumerate(plan.group_by):
            col = batch.column(name)
            arr = _col_arr_8b(col)
            if arr is None:
                return False
            k_cols.append(arr)
            k_valids.append(col.validity)
            if col.validity is not None:
                self.key_has_validity[j] = True
        a_cols, a_valids, a_ops = [], [], []
        for op, cname in plan.agg_ops:
            a_ops.append(op)
            if cname is None:
                a_cols.append(None)
                a_valids.append(None)
                continue
            col = batch.column(cname)
            if op >= _OP_SUM_I64:
                arr = _col_arr_8b(col)
                if arr is None:
                    return False
                a_cols.append(arr)
            else:
                a_cols.append(None)
            if col.kind == "numeric":
                a_valids.append(col.validity)
            else:
                # string COUNT(col): valid mask from the codes
                nm = col.null_mask
                a_valids.append(None if nm is None else ~nm)
        lo_i, hi_i, lo_f, hi_f, flags = plan.bounds
        row_start = 0
        while row_start < n:
            res = native.fused_filter_agg(
                f_cols, f_valids, plan.term_f64,
                lo_i, hi_i, lo_f, hi_f, flags,
                k_cols, k_valids, plan.key_f64,
                a_cols, a_valids, a_ops,
                n, row_start,
                self.ht, self.g_hash, self.g_reps, self.g_nulls,
                self.g_kvals, self.g_kvalid,
                self.acc_i, self.acc_f, self.acc_cnt, self.acc_aux,
                self.n_groups, self.rows_passed, self.rebuild,
            )
            if res is None:
                return False
            consumed, self.n_groups, self.rows_passed = res
            self.rebuild = False
            row_start += consumed
            if row_start < n:
                self._grow()
        return True

    def partials(self, copy: bool = True) -> AggPartials:
        """Snapshot the carried chunk state as :class:`AggPartials` —
        the stable public hook (the per-chunk partials used to be
        folded away inside the sweep; the sidecar capture and the
        metadata merge consume this instead of re-deriving the layout).
        ``copy=False`` returns VIEWS of the live state for callers that
        discard the state immediately (the fused finalize) — never hold
        such a snapshot across another ``accumulate``."""

        def sl(a):
            s = a[:, : self.n_groups]
            return s.copy() if copy else s

        return AggPartials(
            n_groups=self.n_groups,
            rows_scanned=self.rows_scanned,
            rows_passed=self.rows_passed,
            g_reps=sl(self.g_reps),
            g_nulls=sl(self.g_nulls),
            g_kvals=sl(self.g_kvals),
            g_kvalid=sl(self.g_kvalid),
            key_has_validity=tuple(self.key_has_validity),
            acc_i=sl(self.acc_i),
            acc_f=sl(self.acc_f),
            acc_cnt=sl(self.acc_cnt),
            acc_aux=sl(self.acc_aux),
        )


#: public name of the chunk-state carrier (kept underscore-free for the
#: capture/metadata consumers; the historical private name stays bound)
AggState = _AggState


def partials_from_batch(
    plan, batch: ColumnarBatch, rows_scanned: Optional[int] = None
) -> Optional[AggPartials]:
    """Numpy twin of the kernel chunk sweep at the PARTIALS level: one
    already-filtered batch -> :class:`AggPartials`, bit-identical to
    ``AggState.accumulate(...).partials()`` over the same rows (wrapped
    int sums, +0.0-for-null float sums, replace-on-equal min/max, clean/
    NaN aux counts, first-occurrence key values). Shared by the sidecar
    capture (``indexes/aggindex.py`` runs it per row group at build
    time) and the metadata plane's kernel-less boundary chunks. ``plan``
    only needs ``group_by`` + ``agg_ops`` (a full FusedAggPlan or the
    capture's lightweight spec). None when a column falls outside the
    fused 8-byte type set."""
    from hyperspace_tpu.execution.aggregate_exec import _factorize

    n = batch.num_rows
    gid, first, G = _factorize(batch, list(plan.group_by))
    nk = len(plan.group_by)
    na = len(plan.agg_ops)
    g_reps = np.zeros((nk, G), dtype=np.int64)
    g_nulls = np.zeros((nk, G), dtype=np.uint8)
    g_kvals = np.zeros((nk, G), dtype=np.int64)
    g_kvalid = np.ones((nk, G), dtype=np.uint8)
    khv = []
    for j, name in enumerate(plan.group_by):
        col = batch.column(name)
        arr = _col_arr_8b(col)
        if arr is None:
            return None
        g_reps[j] = col.key_rep()[first]
        nm = col.null_mask
        if nm is not None:
            g_nulls[j] = nm[first].astype(np.uint8)
        g_kvals[j] = arr.view(np.int64)[first]
        if col.validity is not None:
            g_kvalid[j] = col.validity[first].astype(np.uint8)
        khv.append(col.validity is not None)
    acc_i = np.zeros((na, G), dtype=np.int64)
    acc_f = np.zeros((na, G), dtype=np.float64)
    acc_cnt = np.zeros((na, G), dtype=np.int64)
    acc_aux = np.zeros((na, G), dtype=np.int64)
    for a, (op, cname) in enumerate(plan.agg_ops):
        if op == _OP_COUNT_STAR:
            acc_cnt[a] = np.bincount(gid, minlength=G)[:G]
            continue
        col = batch.column(cname)
        nm = col.null_mask
        valid = np.ones(n, dtype=bool) if nm is None else ~nm
        acc_cnt[a] = np.bincount(gid[valid], minlength=G)[:G]
        if op == _OP_COUNT_COL:
            continue
        arr = _col_arr_8b(col)
        if arr is None:
            return None
        if op == _OP_SUM_I64:
            v = np.where(valid, arr.view(np.int64), np.int64(0))
            s = np.zeros(G, dtype=np.int64)
            np.add.at(s, gid, v)
            acc_i[a] = s
        elif op == _OP_SUM_F64:
            v = np.where(valid, arr, np.float64(0.0))
            s = np.zeros(G, dtype=np.float64)
            np.add.at(s, gid, v)
            acc_f[a] = s
        elif op in (_OP_MIN_I64, _OP_MAX_I64):
            iv = arr.view(np.int64)
            if op == _OP_MIN_I64:
                fill = np.iinfo(np.int64).max
                red = np.full(G, fill, dtype=np.int64)
                np.minimum.at(red, gid, np.where(valid, iv, fill))
            else:
                fill = np.iinfo(np.int64).min
                red = np.full(G, fill, dtype=np.int64)
                np.maximum.at(red, gid, np.where(valid, iv, fill))
            acc_i[a] = red
        else:  # _OP_MIN_F64 / _OP_MAX_F64
            isn = np.isnan(arr)
            clean = valid & ~isn
            if op == _OP_MIN_F64:
                red = np.full(G, np.inf, dtype=np.float64)
                np.minimum.at(red, gid, np.where(clean, arr, np.inf))
                acc_aux[a] = np.bincount(gid[clean], minlength=G)[:G]
            else:
                red = np.full(G, -np.inf, dtype=np.float64)
                np.maximum.at(red, gid, np.where(clean, arr, -np.inf))
                acc_aux[a] = np.bincount(gid[valid & isn], minlength=G)[:G]
            acc_f[a] = red
    return AggPartials(
        n_groups=G,
        rows_scanned=n if rows_scanned is None else rows_scanned,
        rows_passed=n,
        g_reps=g_reps,
        g_nulls=g_nulls,
        g_kvals=g_kvals,
        g_kvalid=g_kvalid,
        key_has_validity=tuple(khv),
        acc_i=acc_i,
        acc_f=acc_f,
        acc_cnt=acc_cnt,
        acc_aux=acc_aux,
    )


class PartialsAccumulator:
    """Order-preserving fold of :class:`AggPartials` snapshots into one
    group table — the serve-time merge point where sidecar-persisted
    partials and scanned boundary-chunk partials meet.

    Folding is bit-exact ONLY for the merge-associative ops — COUNT,
    int SUM/AVG (wraps mod 2^64), MIN/MAX (``np.minimum``/``maximum``
    binary semantics, so replace-on-equal folds like the row sweep) —
    which is exactly the set the metadata plane admits; float SUM is
    order-sensitive and never reaches a fold (``try_metadata_aggregate``
    declines it up front). Callers must fold in the interpreted chain's
    row order (file order, row-group order within a file): first-
    occurrence group key values and equal-value min/max bit patterns
    depend on it."""

    _INIT_CAP = 64

    def __init__(self, plan):
        self.plan = plan
        self._nk = len(plan.group_by)
        self._na = len(plan.agg_ops)
        self._slots: Dict[tuple, int] = {}
        self._n = 0
        self._alloc(self._INIT_CAP)
        self.rows_scanned = 0
        self.rows_passed = 0
        self.key_has_validity = [False] * self._nk
        if not plan.group_by:
            # ungrouped aggregation always yields exactly one global
            # group, even over zero folded rows (COUNT 0 / NULL min)
            self._slots[()] = 0
            self._n = 1

    def _alloc(self, cap: int) -> None:
        nk, na = self._nk, self._na
        n = self._n
        old = getattr(self, "_g_reps", None)
        self._cap = cap
        for name, dt, fill in (
            ("_g_reps", np.int64, 0),
            ("_g_nulls", np.uint8, 0),
            ("_g_kvals", np.int64, 0),
            ("_g_kvalid", np.uint8, 1),
        ):
            arr = np.full((nk, cap), fill, dtype=dt)
            if old is not None:
                arr[:, :n] = getattr(self, name)[:, :n]
            setattr(self, name, arr)
        acc_i = np.zeros((na, cap), dtype=np.int64)
        acc_f = np.zeros((na, cap), dtype=np.float64)
        acc_cnt = np.zeros((na, cap), dtype=np.int64)
        acc_aux = np.zeros((na, cap), dtype=np.int64)
        for a, (op, _c) in enumerate(self.plan.agg_ops):
            if op == _OP_MIN_I64:
                acc_i[a] = np.iinfo(np.int64).max
            elif op == _OP_MAX_I64:
                acc_i[a] = np.iinfo(np.int64).min
            elif op == _OP_MIN_F64:
                acc_f[a] = np.inf
            elif op == _OP_MAX_F64:
                acc_f[a] = -np.inf
        if old is not None:
            acc_i[:, :n] = self._acc_i[:, :n]
            acc_f[:, :n] = self._acc_f[:, :n]
            acc_cnt[:, :n] = self._acc_cnt[:, :n]
            acc_aux[:, :n] = self._acc_aux[:, :n]
        self._acc_i, self._acc_f = acc_i, acc_f
        self._acc_cnt, self._acc_aux = acc_cnt, acc_aux

    def fold(self, p: Optional[AggPartials]) -> None:
        if p is None:
            return
        self.rows_scanned += p.rows_scanned
        self.rows_passed += p.rows_passed
        for j, hv in enumerate(p.key_has_validity):
            self.key_has_validity[j] |= hv
        G = p.n_groups
        if G == 0:
            return
        while self._n + G > self._cap:
            self._alloc(self._cap * 4)
        # slot resolution is the one per-group Python loop; the
        # accumulation below is vectorized — safe with direct indexed
        # ops because group keys WITHIN one snapshot are distinct, so
        # ``idx`` never repeats a destination
        nk = self._nk
        idx = np.empty(G, dtype=np.int64)
        for g in range(G):
            key = tuple(
                (int(p.g_reps[j, g]), int(p.g_nulls[j, g])) for j in range(nk)
            )
            gi = self._slots.get(key)
            if gi is None:
                gi = self._n
                self._slots[key] = gi
                self._n += 1
                for j in range(nk):
                    self._g_reps[j, gi] = p.g_reps[j, g]
                    self._g_nulls[j, gi] = p.g_nulls[j, g]
                    self._g_kvals[j, gi] = p.g_kvals[j, g]
                    self._g_kvalid[j, gi] = p.g_kvalid[j, g]
            idx[g] = gi
        for a, (op, _c) in enumerate(self.plan.agg_ops):
            self._acc_cnt[a][idx] += p.acc_cnt[a]
            if op == _OP_SUM_I64:
                # int64 two's-complement addition wraps like the
                # kernel's uint64 accumulate
                self._acc_i[a][idx] += p.acc_i[a]
            elif op == _OP_SUM_F64:
                self._acc_f[a][idx] += p.acc_f[a]
            elif op == _OP_MIN_I64:
                self._acc_i[a][idx] = np.minimum(self._acc_i[a][idx], p.acc_i[a])
            elif op == _OP_MAX_I64:
                self._acc_i[a][idx] = np.maximum(self._acc_i[a][idx], p.acc_i[a])
            elif op == _OP_MIN_F64:
                self._acc_f[a][idx] = np.minimum(self._acc_f[a][idx], p.acc_f[a])
                self._acc_aux[a][idx] += p.acc_aux[a]
            elif op == _OP_MAX_F64:
                self._acc_f[a][idx] = np.maximum(self._acc_f[a][idx], p.acc_f[a])
                self._acc_aux[a][idx] += p.acc_aux[a]

    def snapshot(self) -> AggPartials:
        G = self._n
        return AggPartials(
            n_groups=G,
            rows_scanned=self.rows_scanned,
            rows_passed=self.rows_passed,
            g_reps=self._g_reps[:, :G].copy(),
            g_nulls=self._g_nulls[:, :G].copy(),
            g_kvals=self._g_kvals[:, :G].copy(),
            g_kvalid=self._g_kvalid[:, :G].copy(),
            key_has_validity=tuple(self.key_has_validity),
            acc_i=self._acc_i[:, :G].copy(),
            acc_f=self._acc_f[:, :G].copy(),
            acc_cnt=self._acc_cnt[:, :G].copy(),
            acc_aux=self._acc_aux[:, :G].copy(),
        )


def finalize_partials(plan, pt: AggPartials) -> ColumnarBatch:
    """Assemble the output batch from a partials snapshot — the exact
    post-processing of ``aggregate_exec.execute_aggregate`` (shared
    ``finalize_*`` helpers), with groups ordered like ``_factorize``:
    ascending lexicographic key-rep planes (rep major, null plane minor
    per key). The ONE finalization for the fused sweep, the metadata
    merge and the capture round-trip tests."""
    from hyperspace_tpu.execution import aggregate_exec as AE

    G = pt.n_groups
    out: Dict[str, Column] = {}
    if plan.group_by:
        planes: List[np.ndarray] = []
        for j in range(len(plan.group_by)):
            planes.append(pt.g_reps[j])
            planes.append(pt.g_nulls[j].astype(np.int64))
        # np.lexsort keys are minor→major; planes are major→minor
        order = np.lexsort(planes[::-1])
        for j, name in enumerate(plan.group_by):
            raw = pt.g_kvals[j][order]
            vals = raw.view(np.float64) if plan.key_f64[j] else raw
            validity = (
                pt.g_kvalid[j][order].astype(bool)
                if pt.key_has_validity[j]
                else None
            )
            out[name] = Column(
                "numeric", plan.key_types[j], values=vals, validity=validity
            )
    else:
        order = np.arange(G, dtype=np.int64)  # exactly one global group
    for a, (spec, (op, _c), out_type) in enumerate(
        zip(plan.aggs, plan.agg_ops, plan.out_types)
    ):
        cnt = pt.acc_cnt[a][order]
        if op in (_OP_COUNT_STAR, _OP_COUNT_COL):
            out[spec.name] = AE.finalize_count(out_type, cnt)
        elif op in (_OP_SUM_I64, _OP_SUM_F64):
            sums = (pt.acc_i if op == _OP_SUM_I64 else pt.acc_f)[a][order]
            if spec.func == "avg":
                out[spec.name] = AE.finalize_avg(out_type, sums, cnt)
            else:
                out[spec.name] = AE.finalize_sum(out_type, sums, cnt)
        elif op in (_OP_MIN_I64, _OP_MAX_I64):
            red = pt.acc_i[a][order]
            out[spec.name] = AE.finalize_minmax(
                out_type, red, cnt, np.dtype(np.int64)
            )
        elif op == _OP_MIN_F64:
            acc = pt.acc_f[a][order]
            has_clean = pt.acc_aux[a][order] > 0
            red = np.where(has_clean, acc, np.float64(np.nan))
            out[spec.name] = AE.finalize_minmax(
                out_type, red, cnt, np.dtype(np.float64)
            )
        else:  # _OP_MAX_F64
            acc = pt.acc_f[a][order]
            has_nan = pt.acc_aux[a][order] > 0
            red = np.where(has_nan, np.float64(np.nan), acc)
            out[spec.name] = AE.finalize_minmax(
                out_type, red, cnt, np.dtype(np.float64)
            )
    return ColumnarBatch(out)


def _finalize(state: _AggState) -> ColumnarBatch:
    """The fused sweep's finalization: snapshot the carried state and run
    the shared partials finalization (views, not copies — the state is
    discarded right after, and finalize_partials reorders into fresh
    arrays anyway)."""
    return finalize_partials(state.plan, state.partials(copy=False))


def kernel_filter_aggregate(
    batches, terms, group_by, aggs, child_schema
) -> Optional[ColumnarBatch]:
    """The kernel-driven fused pass over one batch or an ordered list of
    chunk batches — the direct counterpart of
    :func:`interpreted_filter_aggregate` for differential tests and the
    calibration probe. Returns None when the native kernel is
    unavailable or the shape is outside the fused set."""
    if isinstance(batches, ColumnarBatch):
        batches = [batches]
    plan = _lower_from_terms(terms, group_by, aggs, child_schema)
    if plan is None:
        return None
    state = _AggState(plan)
    for b in batches:
        if not state.accumulate(b):
            return None
    return _finalize(state)


# ---------------------------------------------------------------------------
# Executor entry points
# ---------------------------------------------------------------------------


def fused_filter_batch(cond: E.Expr, batch: ColumnarBatch, session):
    """Fused Filter(→Project) lowering over an in-memory batch: one
    native pass computes pass/fail AND compacts the passing row indices
    (``hs_fused_filter_select``); the projection gathers through them
    (native threaded gathers). Bit-identical to
    ``batch.filter(mask)`` — ``filter`` IS ``take(nonzero(mask))``.
    Returns None (caller runs the interpreted mask) off the fused shape,
    below the calibrated crossover, or in the device-mask regime."""
    global last_fused_stats
    n = batch.num_rows
    # the select's true crossover is mask-shaped (one-pass compaction vs
    # mask+nonzero), not agg-shaped: gate on the LOWER of the fused and
    # range-mask calibrated thresholds so a machine whose hash-agg
    # crossover lands high still dispatches the select where it wins
    # (and the test/bench module override on the fused threshold still
    # forces dispatch)
    from hyperspace_tpu.ops.filter import _native_range_mask_min_rows

    threshold = min(
        _native_fused_pipeline_min_rows(), _native_range_mask_min_rows()
    )
    if n == 0 or n < threshold:
        return None
    dev_min = (
        session.conf.device_filter_min_rows
        if session is not None
        else C.EXECUTION_DEVICE_FILTER_MIN_ROWS_DEFAULT
    )
    if n >= dev_min:
        return None  # the XLA mask path owns device-resident regimes
    from hyperspace_tpu.ops import filter as F

    terms = F.lower_range_terms(cond, batch)
    if terms is None:
        return None
    t0 = time.perf_counter()
    prep = F.native_terms_for_batch(batch, terms)
    if prep is None:
        return None
    if prep == F.NEVER_MATCH:
        idx = np.zeros(0, dtype=np.int64)
    else:
        from hyperspace_tpu import native

        idx = native.fused_filter_select(*prep, n)
        if idx is None:
            return None
    out = batch.take(idx)
    last_fused_stats = {
        "mode": "select",
        "rows_scanned": n,
        "rows_passed": int(len(idx)),
        "rows_materialized": int(len(idx)),
        "chunks": 1,
        "wall_s": time.perf_counter() - t0,
    }
    return out


def try_fused_aggregate(plan: Aggregate, session) -> Optional[ColumnarBatch]:
    """Serve ``Aggregate(…, [Project(…,)] Filter(cond, Scan))`` over a
    pruned index scan as the fused pipeline. None = any gate failed;
    the caller runs the interpreted chain (bit-identical either way)."""
    global last_fused_stats
    if not fused_pipeline_on(session):
        return None
    node = plan.child
    while isinstance(node, Project):
        node = node.child
    if not isinstance(node, Filter) or not isinstance(node.child, Scan):
        return None
    from hyperspace_tpu import native

    if native.load(wait=False) is None:
        return None
    from hyperspace_tpu.execution import executor as X

    # both pruning passes are memoized (bucket ids per file tuple, zone
    # maps per file identity), so a later bail-out's interpreted re-run
    # repeats only the cheap intersection, not the metadata reads
    pruned = X._bucket_pruned_scan(node.child, node.condition)
    pruned = X._range_pruned_scan(pruned, node.condition, session)
    if not isinstance(pruned, Scan):
        return None
    rel = pruned.relation
    # the clean-index-scan gate is _cacheable_scan's exact condition set
    # (index data, parquet-like, no delete compensation, no injected
    # partition constants): one definition, so a future query-shaped
    # relation field added there excludes the fused pass automatically
    if not X._cacheable_scan(rel):
        return None
    # the Project above the Filter prunes to the aggregate's inputs, so
    # the condition's columns live in the SCAN's schema, not the child's;
    # types agree wherever both carry a column (projection never retypes)
    child_schema = dict(rel.schema)
    child_schema.update(plan.child.schema())
    fplan = _compiled_plan(node.condition, plan, rel, child_schema, session)
    if fplan is None:
        return None
    cache = X._serve_cache(session)
    if cache is not None:  # rel passed _cacheable_scan above
        # serve-server mode keeps the decoded scan in RAM: run the fused
        # pass over the cached batch (no read at all) instead of
        # streaming parquet chunks past a warm cache
        hit = X._scan_cache_entry(rel, set(fplan.read_cols), session)
        if hit is None:
            return None
        entry, _cols = hit
        batch = entry.batch_for(fplan.read_cols)
        if batch is None or batch.num_rows < _native_fused_pipeline_min_rows():
            return None
        t0 = time.perf_counter()
        state = _AggState(fplan)
        if not state.accumulate(batch):
            return None
        out = _finalize(state)
        last_fused_stats = _agg_stats(state, t0)
        return out
    total = _scan_row_total(rel)
    if total < _native_fused_pipeline_min_rows():
        return None
    return _run_chunked(fplan, rel)


def _agg_stats(state: _AggState, t0: float) -> Dict[str, Any]:
    return {
        "mode": "agg",
        "rows_scanned": state.rows_scanned,
        "rows_passed": state.rows_passed,
        # the fused pass materializes GROUPS, never filtered rows — the
        # interpreted chain materializes rows_passed rows per column
        "rows_materialized": int(
            state.n_groups if state.plan.group_by else 1
        ),
        "groups": int(state.n_groups),
        "chunks": state.chunks,
        "wall_s": time.perf_counter() - t0,
    }


def _compiled_plan(
    cond: E.Expr, plan: Aggregate, rel, child_schema, session
) -> Optional[FusedAggPlan]:
    """The lowered plan, served from the serve cache when available
    (``("fusedplan", fingerprint, …)`` kind — evictable like zone maps
    and deltas via ``ServeCache.evict_kind("fusedplan")``)."""
    from hyperspace_tpu.execution import executor as X

    cache = X._serve_cache(session)
    key = None
    if cache is not None:
        from hyperspace_tpu.execution.serve_cache import file_fingerprint

        fp = file_fingerprint(rel.files)
        if fp is not None:
            key = (
                "fusedplan",
                fp,
                repr(cond),
                tuple(plan.group_by),
                tuple(plan.aggs),
            )
            hit = cache.get(key)
            if hit is not None:
                return hit
    fplan = _lower_fused_agg(
        cond, plan.group_by, plan.aggs, child_schema, rel.column_names
    )
    if fplan is not None and key is not None:
        cache.put(key, fplan, fplan.nbytes)
    return fplan


# ---------------------------------------------------------------------------
# Chunked execution (reads overlap the fused compute on scan_pool)
# ---------------------------------------------------------------------------


def _scan_row_total(rel) -> int:
    """Rows the fused pass would scan (surviving row groups), from the
    zone-map plane's memoized footer metadata (``zonemaps.footer_zones``
    — the range-pruning pass has usually just parsed these footers, so
    this is a cache hit, and there is ONE definition of per-row-group
    row counts). Unreadable footers count as large: the read will raise
    the same error the interpreted path would."""
    from hyperspace_tpu.indexes import zonemaps

    total = 0
    groups = rel.file_row_groups or (None,) * len(rel.files)
    for f, g in zip(rel.files, groups):
        zones = zonemaps.footer_zones(f)
        if zones is None:
            return 1 << 62
        rows = zones["rg_rows"]
        if g is None:
            total += sum(rows)
        else:
            total += sum(rows[i] for i in g if i < len(rows))
    return total


def _read_chunk(path: str, groups, cols: List[str]) -> pa.Table:
    """One file's surviving row groups, via the SAME per-file read the
    interpreted chain's ``read_table_row_groups`` uses — a shared
    definition, so the two paths can never read different bytes."""
    from hyperspace_tpu.io.parquet import read_file_row_groups

    return read_file_row_groups(path, groups, cols)


def _run_chunked(fplan: FusedAggPlan, rel) -> Optional[ColumnarBatch]:
    """Stream the pruned scan through the fused pass file by file:
    chunk reads are submitted to the shared scan pool up front, decode +
    the fused kernel run on the consumer thread while later chunks are
    still reading — accumulation order stays exactly file order, which
    is what makes float sums bit-identical to the interpreted chain."""
    global last_fused_stats
    from hyperspace_tpu.io.scan import scan_pool

    t0 = time.perf_counter()
    cols = list(fplan.read_cols)
    groups = (
        list(rel.file_row_groups)
        if rel.file_row_groups is not None
        else [None] * len(rel.files)
    )
    state = _AggState(fplan)
    if len(rel.files) > 1:
        futs = [
            scan_pool().submit(_read_chunk, f, g, cols)
            for f, g in zip(rel.files, groups)
        ]
        tables = (fut.result() for fut in futs)
    else:
        tables = (
            _read_chunk(f, g, cols) for f, g in zip(rel.files, groups)
        )
    for table in tables:
        if not state.accumulate(ColumnarBatch.from_arrow(table)):
            return None  # executor falls back to the interpreted chain
    out = _finalize(state)
    last_fused_stats = _agg_stats(state, t0)
    return out


# ---------------------------------------------------------------------------
# Metadata plane: answer point aggregates from persisted partials
# (docs/agg-serve.md; sidecar capture/assembly in indexes/aggindex.py)
# ---------------------------------------------------------------------------


def agg_plane_on(session) -> bool:
    """``hyperspace.index.agg.enabled`` (default on). Like the fused
    pass, a pure serving substitution with identical output, so it also
    applies to sessionless execution."""
    if session is None:
        return C.INDEX_AGG_ENABLED_DEFAULT
    return session.conf.index_agg_enabled


#: ops whose partials fold associatively bit-for-bit (see
#: PartialsAccumulator): float SUM/AVG is excluded — merging per-row-
#: group float sums would reassociate vs the row-sequential chain
_METADATA_MERGE_OPS = frozenset(
    {
        _OP_COUNT_STAR,
        _OP_COUNT_COL,
        _OP_SUM_I64,
        _OP_MIN_I64,
        _OP_MAX_I64,
        _OP_MIN_F64,
        _OP_MAX_F64,
    }
)


def _chunk_partials(fplan: FusedAggPlan, batch: ColumnarBatch):
    """Partials of one boundary chunk: the fused kernel when available
    (same sweep the fused pass runs), else the numpy twin over the
    masked batch — bit-identical either way (partials-level twin
    contract, differential-tested in tests/test_agg_index.py)."""
    from hyperspace_tpu import native

    if fplan.terms and batch.num_rows and native.load(wait=False) is not None:
        state = _AggState(fplan)
        if state.accumulate(batch):
            return state.partials()
    if fplan.terms:
        from hyperspace_tpu.ops.filter import range_mask_numpy

        fb = batch.filter(range_mask_numpy(batch, fplan.terms))
    else:
        fb = batch
    return partials_from_batch(fplan, fb, rows_scanned=batch.num_rows)


def try_metadata_aggregate(plan: Aggregate, session) -> Optional[ColumnarBatch]:
    """Serve ``Aggregate(…, [Project] [Filter(cond,)] Scan)`` over a
    clean index scan from the persisted partial-aggregate sidecars
    (``_aggstate.json``): row groups whose zone provably satisfies EVERY
    conjunct fold their stored partials without opening a single parquet
    file; boundary row groups are scanned through the fused kernel (or
    its numpy twin) for just those chunks; everything merges through
    :class:`PartialsAccumulator` + :func:`finalize_partials`, so the
    result stays bit-identical to the interpreted chain. None = any gate
    failed; the caller runs the fused pass / interpreted chain instead
    (bit-identical whichever path answers)."""
    global last_aggplane_stats
    if not agg_plane_on(session):
        return None
    node = plan.child
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, Filter) and isinstance(node.child, Scan):
        cond, scan = node.condition, node.child
    elif isinstance(node, Scan):
        cond, scan = None, node
    else:
        return None
    if len(plan.group_by) > 1:
        return None  # grouped partials are captured per single key column
    from hyperspace_tpu.execution import executor as X

    if cond is not None:
        pruned = X._bucket_pruned_scan(scan, cond)
        pruned = X._range_pruned_scan(pruned, cond, session)
        if not isinstance(pruned, Scan):
            return None
    else:
        pruned = scan
    rel = pruned.relation
    if not X._cacheable_scan(rel):
        return None
    t0 = time.perf_counter()
    child_schema = dict(rel.schema)
    child_schema.update(plan.child.schema())
    if cond is None:
        ivs: Dict[str, Any] = {}
        fplan = _lower_from_terms(
            (), plan.group_by, plan.aggs, child_schema, rel.column_names
        )
    else:
        from hyperspace_tpu.indexes import zonemaps

        # STRICT lowering: full-coverage classification is sound only
        # when the intervals ARE the predicate (IN hulls, OR trees, !=
        # etc. abstain and the whole plane declines)
        ivs = zonemaps.predicate_intervals_complete(cond, rel.schema)
        if ivs is None:
            return None
        fplan = _lower_fused_agg(
            cond, plan.group_by, plan.aggs, child_schema, rel.column_names
        )
    if fplan is None:
        return None
    for op, _c in fplan.agg_ops:
        if op not in _METADATA_MERGE_OPS:
            return None
    from hyperspace_tpu.indexes import aggindex

    key = plan.group_by[0] if plan.group_by else None
    data = aggindex.agg_data_for(
        rel,
        X._serve_cache(session),
        session.conf if session is not None else None,
        key,
    )
    if data is None:
        return None
    cells = aggindex.classify_row_groups(data, rel, ivs, key, fplan)
    if cells is None:
        return None
    n_full = sum(1 for _f, _g, kind in cells if kind == "full")
    if n_full == 0:
        # nothing answerable from metadata: no win over the fused pass,
        # and engaging would only shadow its telemetry
        return None
    cols = list(fplan.read_cols)
    partial_cells = [
        (i, fi, gi)
        for i, (fi, gi, kind) in enumerate(cells)
        if kind == "partial"
    ]
    cache = X._serve_cache(session)
    if partial_cells and cache is not None:
        # serve-server mode with a WARM decoded scan: the fused pass
        # serves boundary rows straight from RAM — re-reading them from
        # parquet here would make partial coverage slower than the path
        # it preempts. (A cold cache still favors metadata + boundary
        # disk reads; and full coverage never reads at all.)
        from hyperspace_tpu.execution.serve_cache import file_fingerprint

        fp = file_fingerprint(rel.files)
        if fp is not None:
            entry = cache.peek(("scan", fp))
            if entry is not None and entry.batch_for(cols) is not None:
                return None
    # boundary chunk reads overlap the metadata folds on the scan pool;
    # folding stays strictly in (file, row-group) order — the
    # interpreted chain's row order (see PartialsAccumulator)
    from hyperspace_tpu.io.scan import scan_pool

    reads = {}
    if len(partial_cells) > 1:
        for i, fi, gi in partial_cells:
            reads[i] = scan_pool().submit(
                _read_chunk,
                rel.files[fi],
                None if gi is None else [gi],
                cols,
            )
    acc = PartialsAccumulator(fplan)
    rows_read = 0
    n_empty = n_partial = 0
    for i, (fi, gi, kind) in enumerate(cells):
        if kind == "empty":
            n_empty += 1
            continue
        if kind == "full":
            acc.fold(aggindex.rg_partials(data, fi, gi, fplan, key))
            continue
        n_partial += 1
        fut = reads.get(i)
        table = (
            fut.result()
            if fut is not None
            else _read_chunk(
                rel.files[fi], None if gi is None else [gi], cols
            )
        )
        batch = ColumnarBatch.from_arrow(table)
        rows_read += batch.num_rows
        p = _chunk_partials(fplan, batch)
        if p is None:
            # column outside the fused set mid-stream: bail to the
            # interpreted chain, releasing not-yet-started reads so the
            # pool doesn't keep scanning data nobody will consume
            for j, fut2 in reads.items():
                if j > i:
                    fut2.cancel()
            return None
        acc.fold(p)
    out = finalize_partials(fplan, acc.snapshot())
    last_aggplane_stats = {
        "mode": "agg_metadata",
        "row_groups_total": len(cells),
        "row_groups_metadata": n_full,
        "row_groups_empty": n_empty,
        "row_groups_scanned": n_partial,
        "rows_scanned": rows_read,
        "groups": int(out.num_rows),
        "wall_s": time.perf_counter() - t0,
    }
    return out
