"""Hash-aggregate execution: host factorize + device segment reductions.

The reference's aggregates run inside Spark's HashAggregateExec; here the
engine is the serve path. Group ids are computed host-side (one O(rows)
factorize over the group key reps), then every aggregate is an XLA
segment reduction (``ops/aggregate.py``) over those ids.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.ops import aggregate as agg_ops
from hyperspace_tpu.ops.sort import order_rep, sort_permutation
from hyperspace_tpu.plan.nodes import AggSpec, _agg_output_type


def _grouping_planes(col: Column) -> List[np.ndarray]:
    """Per-column int64 plane(s) where row equality == SQL group-by
    equality.

    Strings use dictionary codes (exact within a batch — no hash
    collisions; code -1 is null, one group as SQL requires). Numerics use
    ``key_rep`` (canonicalizes NaN/-0.0) plus, when the column has nulls,
    an explicit null plane — the rep maps null to an in-band value a real
    key could equal, so the plane is what keeps nulls a separate group.
    """
    if col.kind == "string":
        return [col.codes.astype(np.int64)]
    planes = [col.key_rep()]
    null = col.null_mask
    if null is not None:
        planes.append(null.astype(np.int64))
    return planes


def _factorize(batch: ColumnarBatch, group_by: List[str]) -> Tuple[np.ndarray, np.ndarray, int]:
    """-> (group_ids [n], first_occurrence_row_per_group, num_groups).

    Sort-based grouping: stable lexsort of the grouping planes (rides the
    native radix kernel via ``lexsort_perm``), then group boundaries from
    adjacent-row inequality. Replaced a void-view ``np.unique`` — the
    same comparison-based pattern the join path already abandoned —
    measured 6.9x faster at 4M rows / 2.7M groups. Groups come out
    ordered by key rep (deterministic); stability makes ``first`` the
    true first occurrence of each group in the original batch."""
    n = batch.num_rows
    if not group_by or n == 0:
        return (
            np.zeros(n, dtype=np.int64),
            np.zeros(0, dtype=np.int64),
            0 if (group_by and n == 0) else 1,
        )
    planes: List[np.ndarray] = []
    for c in group_by:
        planes.extend(_grouping_planes(batch.column(c)))
    reps = np.stack(planes)
    perm = sort_permutation(reps)
    sorted_rows = reps[:, perm]
    neq = np.any(sorted_rows[:, 1:] != sorted_rows[:, :-1], axis=0)
    starts = np.concatenate([[0], np.nonzero(neq)[0] + 1])
    gid_sorted = np.zeros(n, dtype=np.int64)
    gid_sorted[1:] = np.cumsum(neq)
    gid = np.empty(n, dtype=np.int64)
    gid[perm] = gid_sorted
    return gid, perm[starts], len(starts)


def _valid_mask(col: Column) -> Optional[np.ndarray]:
    null = col.null_mask
    return None if null is None else ~null


def _numeric_values(col: Column, spec: AggSpec) -> np.ndarray:
    if col.kind != "numeric":
        raise HyperspaceException(
            f"{spec.func}() over non-numeric column {spec.column!r}"
        )
    return col.values


def _string_minmax(
    col: Column, gid: np.ndarray, num_groups: int, mode: str
) -> Column:
    """min/max over a string column: reduce per-batch dictionary ranks on
    device, then map winning ranks back to strings."""
    sorted_dict = sorted(col.dictionary)
    ranks = order_rep(col)
    valid = _valid_mask(col)
    win = agg_ops.segment_minmax(gid, ranks, valid, num_groups, mode)
    counts = agg_ops.segment_count(gid, valid, len(ranks), num_groups)
    has = counts > 0
    codes = np.where(has, np.clip(win, 0, max(len(sorted_dict) - 1, 0)), -1)
    return Column(
        "string",
        col.arrow_type,
        codes=codes.astype(np.int32),
        dictionary=sorted_dict,
    )


# -- per-spec finalization ---------------------------------------------------
# Shared by this interpreted engine and the fused serve-pipeline compiler
# (execution/pipeline_compiler.py): the fused native pass produces the
# same raw reductions (counts / sums / min-max accumulators) and runs the
# IDENTICAL finalization, so output columns (types, zero-fills, validity
# presence) cannot diverge between the two paths.


def finalize_count(out_type, counts: np.ndarray) -> Column:
    return Column("numeric", out_type, values=counts)


def finalize_minmax(out_type, red: np.ndarray, counts: np.ndarray, vals_dtype) -> Column:
    """``red`` = raw per-group reduction (NaN rules already applied for
    floats), ``counts`` = per-group count of VALID input rows."""
    has = counts > 0
    red = red.astype(vals_dtype, copy=False)
    return Column(
        "numeric",
        out_type,
        values=np.where(has, red, np.zeros_like(red)),
        validity=None if has.all() else has,
    )


def finalize_sum(out_type, sums: np.ndarray, counts: np.ndarray) -> Column:
    has = counts > 0
    target = np.float64 if pa.types.is_floating(out_type) else np.int64
    sums = sums.astype(target, copy=False)
    return Column(
        "numeric",
        out_type,
        values=np.where(has, sums, np.zeros_like(sums)),
        validity=None if has.all() else has,
    )


def finalize_avg(out_type, sums: np.ndarray, counts: np.ndarray) -> Column:
    has = counts > 0
    with np.errstate(invalid="ignore", divide="ignore"):
        avg = sums.astype(np.float64) / np.maximum(counts, 1)
    return Column(
        "numeric",
        out_type,
        values=np.where(has, avg, 0.0),
        validity=None if has.all() else has,
    )


def execute_aggregate(
    batch: ColumnarBatch,
    group_by: List[str],
    aggs: List[AggSpec],
    child_schema,
) -> ColumnarBatch:
    gid, first, num_groups = _factorize(batch, group_by)
    n = batch.num_rows

    out = {}
    if group_by:
        keys = batch.take(first)
        for c in group_by:
            out[c] = keys.column(c)

    for spec in aggs:
        out_type = _agg_output_type(spec, child_schema)
        if spec.func == "count":
            if spec.column is None:
                counts = agg_ops.segment_count(gid, None, n, num_groups)
            else:
                col = batch.column(spec.column)
                counts = agg_ops.segment_count(
                    gid, _valid_mask(col), n, num_groups
                )
            out[spec.name] = finalize_count(out_type, counts)
            continue

        col = batch.column(spec.column)
        if spec.func in ("min", "max"):
            if col.kind == "string":
                out[spec.name] = _string_minmax(
                    col, gid, num_groups, spec.func
                )
                continue
            vals = _numeric_values(col, spec)
            valid = _valid_mask(col)
            red = agg_ops.segment_minmax(gid, vals, valid, num_groups, spec.func)
            counts = agg_ops.segment_count(gid, valid, n, num_groups)
            out[spec.name] = finalize_minmax(out_type, red, counts, vals.dtype)
            continue

        # sum / avg
        vals = _numeric_values(col, spec)
        valid = _valid_mask(col)
        sums, counts = agg_ops.segment_sum_count(gid, vals, valid, num_groups)
        if spec.func == "sum":
            out[spec.name] = finalize_sum(out_type, sums, counts)
        else:  # avg
            out[spec.name] = finalize_avg(out_type, sums, counts)
    return ColumnarBatch(out)
