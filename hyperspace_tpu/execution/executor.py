"""Plan executor: walks the (optimized) logical plan and produces Arrow.

Equivalent role to Spark's physical planning + execution under the
reference (scan → FileSourceScanExec etc.). Column pruning is pushed into
the scan (the reference gets this from Parquet + Catalyst for free);
predicates are evaluated with the XLA kernel (``ops/filter.py``) with a
host fallback for expressions the device path does not cover.
"""

from __future__ import annotations

from typing import Optional, Set

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.ops.filter import Unsupported, device_filter_mask
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Filter,
    Join,
    LogicalPlan,
    Project,
    Scan,
    Union,
)


def execute(plan: LogicalPlan, session=None):
    """Execute -> pyarrow.Table (column order = plan.output)."""
    batch = _exec(plan, set(plan.output), session)
    return batch.select(plan.output).to_arrow()


def _exec(plan: LogicalPlan, needed: Set[str], session) -> ColumnarBatch:
    if isinstance(plan, Scan):
        return _exec_scan(plan, needed, session)
    if isinstance(plan, Filter):
        child_needed = set(needed) | E.references(plan.condition)
        batch = _exec(plan.child, child_needed, session)
        return batch.filter(_filter_mask(plan.condition, batch))
    if isinstance(plan, Project):
        batch = _exec(plan.child, set(plan.columns), session)
        return batch.select(plan.columns)
    if isinstance(plan, Union):
        cols = [c for c in plan.output if c in needed] or plan.output[:1]
        left = _exec(plan.left, set(cols), session).select(cols)
        right = _exec(plan.right, set(cols), session).select(cols)
        return ColumnarBatch.concat([left, right])
    if isinstance(plan, Join):
        pairs = E.equi_join_pairs(plan.condition)
        if pairs is None:
            raise HyperspaceException(
                f"Only conjunctive equi-joins are executable: {plan.condition!r}"
            )
        lcols = set(plan.left.output)
        on = []
        for a, b in pairs:
            if a in lcols:
                on.append((a, b))
            else:
                on.append((b, a))
        l_needed = (needed & lcols) | {l for l, _ in on}
        rcols = set(plan.right.output)
        r_needed = (needed & rcols) | {r for _, r in on}
        left = _exec(plan.left, l_needed, session)
        right = _exec(plan.right, r_needed, session)
        from hyperspace_tpu.execution.join_exec import inner_join

        return inner_join(left, right, on)
    raise HyperspaceException(f"Unknown plan node: {type(plan).__name__}")


def _filter_mask(cond: E.Expr, batch: ColumnarBatch) -> np.ndarray:
    try:
        return device_filter_mask(cond, batch)
    except Unsupported:
        return E.filter_mask(cond, batch)


def _exec_scan(plan: Scan, needed: Set[str], session) -> ColumnarBatch:
    rel = plan.relation
    cols = [c for c in rel.column_names if c in needed] or rel.column_names[:1]
    read_cols = list(cols)
    # Hybrid-Scan delete compensation: the lineage column must be read to
    # apply the NOT-IN filter (CoveringIndexRuleUtils.scala:244-253), even
    # if the query does not project it.
    from hyperspace_tpu.constants import DATA_FILE_NAME_ID

    if rel.excluded_file_ids is not None and DATA_FILE_NAME_ID not in read_cols:
        read_cols.append(DATA_FILE_NAME_ID)
    if not rel.files:
        import pyarrow as pa

        empty = pa.table(
            {c: pa.array([], type=rel.schema[c]) for c in cols}
        )
        return ColumnarBatch.from_arrow(empty)
    table = pio.read_table(list(rel.files), read_cols, rel.fmt)
    batch = ColumnarBatch.from_arrow(table)
    if rel.excluded_file_ids is not None:
        lineage = batch.column(DATA_FILE_NAME_ID).values
        mask = ~np.isin(lineage, np.array(rel.excluded_file_ids, dtype=np.int64))
        batch = batch.filter(mask)
    return batch.select(cols)
