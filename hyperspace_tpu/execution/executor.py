"""Plan executor: walks the (optimized) logical plan and produces Arrow.

Equivalent role to Spark's physical planning + execution under the
reference (scan → FileSourceScanExec etc.). Column pruning is pushed into
the scan (the reference gets this from Parquet + Catalyst for free);
predicates are evaluated with the XLA kernel (``ops/filter.py``) with a
host fallback for expressions the device path does not cover.
"""

from __future__ import annotations

import threading as _threading
from functools import lru_cache as _lru_cache
from typing import Dict, Optional, Set

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.obs import trace as _obs_trace
from hyperspace_tpu.ops.filter import Unsupported, device_filter_mask
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
)


def execute(plan: LogicalPlan, session=None):
    """Execute -> pyarrow.Table (column order = plan.output)."""
    batch = _exec(plan, set(plan.output), session)
    return batch.select(plan.output).to_arrow()


def _exec(plan: LogicalPlan, needed: Set[str], session) -> ColumnarBatch:
    if isinstance(plan, Scan):
        return _exec_scan(plan, needed, session)
    if isinstance(plan, Filter):
        child = _bucket_pruned_scan(plan.child, plan.condition)
        child = _range_pruned_scan(child, plan.condition, session)
        child_needed = set(needed) | E.references(plan.condition)
        if isinstance(child, Scan):
            cached = _cached_filter(child, plan.condition, child_needed, session)
            if cached is not None:
                return cached
            batch = _exec_scan(
                child,
                child_needed,
                session,
                pushdown=_pushdown_filters(plan.condition, child.relation),
            )
        else:
            batch = _exec(child, child_needed, session)
        if isinstance(child, Scan) and _fused_pipeline_on(session):
            # fused Filter(→Project) lowering (docs/serve-compiler.md):
            # one native pass computes the conjunct mask AND compacts
            # the passing indices — bit-identical to filter(mask),
            # which IS take(nonzero(mask))
            from hyperspace_tpu.execution.pipeline_compiler import (
                fused_filter_batch,
            )

            fused = fused_filter_batch(plan.condition, batch, session)
            if fused is not None:
                return fused
        return batch.filter(_filter_mask(plan.condition, batch, session))
    if isinstance(plan, Project):
        batch = _exec(plan.child, set(plan.columns), session)
        return batch.select(plan.columns)
    if isinstance(plan, Union):
        cols = [c for c in plan.output if c in needed] or plan.output[:1]
        left = _exec(plan.left, set(cols), session).select(cols)
        right = _exec(plan.right, set(cols), session).select(cols)
        return ColumnarBatch.concat([left, right])
    if isinstance(plan, Join):
        return _exec_join(plan, needed, session)
    if isinstance(plan, Aggregate):
        from hyperspace_tpu.execution.pipeline_compiler import (
            try_fused_aggregate,
            try_metadata_aggregate,
        )

        # aggregate index plane (docs/agg-serve.md): a strictly-lowered
        # Filter(→Project)→Aggregate over a clean index scan answers
        # fully-covered row groups from the persisted partial-aggregate
        # sidecar WITHOUT reading them, scans only the boundary chunks,
        # and merges through the shared partials layer — bit-identical
        # to the chains below. The agg/finalize stage span is the only
        # serve-side visibility into these fused passes (OBS_SITES).
        with _obs_trace.span("agg"):
            served = try_metadata_aggregate(plan, session)
            if served is not None:
                return served
            # fused serve-pipeline compiler (docs/serve-compiler.md): a
            # Filter(→Project)→Aggregate subtree over a pruned index scan
            # runs as one fused native pass per row-group chunk —
            # predicate, grouping and partial aggregates in a single
            # sweep, partials merged at the edge; bit-identical to the
            # chain below
            fused = try_fused_aggregate(plan, session)
            if fused is not None:
                return fused
            batch = _exec(plan.child, plan.input_columns, session)
            from hyperspace_tpu.execution.aggregate_exec import (
                execute_aggregate,
            )

            return execute_aggregate(
                batch, plan.group_by, plan.aggs, plan.child.schema()
            )
    if isinstance(plan, Sort):
        from hyperspace_tpu.ops.sort import ordering_permutation

        child_needed = set(needed) | {c for c, _ in plan.keys}
        batch = _exec(plan.child, child_needed, session)
        if batch.num_rows == 0:
            return batch
        return batch.take(ordering_permutation(batch, plan.keys))
    if isinstance(plan, Limit):
        return _exec_limit(plan.n, plan.child, needed, session)
    raise HyperspaceException(f"Unknown plan node: {type(plan).__name__}")


def _exec_limit(n: int, child: LogicalPlan, needed: Set[str], session) -> ColumnarBatch:
    """Limit execution that avoids materializing the full child.

    * Limit∘Sort = top-n: sort the permutation, materialize only n rows;
    * Limit pushes through Project and Union (row order is the child's
      deterministic order, so taking the first n of the left side first
      is exactly what the naive path produced);
    * Limit∘Scan / Limit∘Filter∘Scan stream file-by-file and stop as
      soon as n rows are produced.
    The reference gets all of this from Spark's CollectLimitExec /
    LocalLimit pushdown; the naive path here executed and sorted the
    entire child before truncating.
    """
    import dataclasses

    if n <= 0:
        import pyarrow as pa

        schema = child.schema()
        cols = [c for c in child.output if c in needed] or child.output[:1]
        return ColumnarBatch.from_arrow(
            pa.table({c: pa.array([], type=schema[c]) for c in cols})
        )
    if isinstance(child, Sort):
        from hyperspace_tpu.ops.sort import ordering_permutation

        child_needed = set(needed) | {c for c, _ in child.keys}
        batch = _exec(child.child, child_needed, session)
        if batch.num_rows == 0:
            return batch
        perm = ordering_permutation(batch, child.keys)
        return batch.take(perm[: min(n, batch.num_rows)])
    if isinstance(child, Project):
        return _exec_limit(
            n, child.child, set(child.columns), session
        ).select(child.columns)
    if isinstance(child, Union):
        cols = [c for c in child.output if c in needed] or child.output[:1]
        left = _exec_limit(n, child.left, set(cols), session).select(cols)
        if left.num_rows >= n:
            return left.take(np.arange(n))
        right = _exec_limit(
            n - left.num_rows, child.right, set(cols), session
        ).select(cols)
        return ColumnarBatch.concat([left, right])
    # file-by-file streaming for Scan / Filter(Scan) over footer-counted
    # formats without post-read row filtering
    scan = child.child if isinstance(child, Filter) else child
    streamable = (
        isinstance(scan, Scan)
        and scan.relation.fmt in ("parquet", "delta", "iceberg")
        and scan.relation.excluded_file_ids is None
        and len(scan.relation.files) > 1
    )
    if streamable:
        # geometric group sizes (1, 2, 4, …): a selective filter that ends
        # up reading everything still gets the threaded multi-file read
        # after the first few probes (log-many read_table calls total),
        # while a satisfied limit stops after one small group
        parts: list = []
        got = 0
        files = list(scan.relation.files)
        pos = 0
        group = 1
        while pos < len(files) and got < n:
            chunk = tuple(files[pos : pos + group])
            sub_scan = Scan(dataclasses.replace(scan.relation, files=chunk))
            sub: LogicalPlan = (
                Filter(child.condition, sub_scan)
                if isinstance(child, Filter)
                else sub_scan
            )
            b = _exec(sub, needed, session)
            parts.append(b)
            got += b.num_rows
            pos += len(chunk)
            group *= 2
        batch = ColumnarBatch.concat(parts)
        return batch.take(np.arange(min(n, batch.num_rows)))
    batch = _exec(child, needed, session)
    return batch.take(np.arange(min(n, batch.num_rows)))


def _serve_cache(session):
    """The session's ServeCache, or None when serve-server mode is off."""
    if session is None:
        return None
    return session.serve_cache


def _serve_pipeline_on(session) -> bool:
    """Pipelined serve path enabled (``hyperspace.serve.pipeline.enabled``,
    default on). Sessionless callers run the sequential path — the
    pipeline's thread fan-out is a serve-process feature, not a library
    default for one-shot embedding."""
    return session is not None and session.conf.serve_pipeline_enabled


def _serve_stream_on(session) -> bool:
    """Streaming per-bucket join serve (``hyperspace.serve.stream.enabled``,
    default off — docs/out-of-core.md). Session-gated like the serve
    pipeline: the wave loop's thread fan-out and byte budgeting are a
    serve-process feature, not a library default for one-shot embedding."""
    return session is not None and session.conf.serve_stream_enabled


def _io_mmap_on(session) -> bool:
    """Memory-mapped Arrow reads (``hyperspace.io.mmap.enabled``, default
    off — docs/out-of-core.md): serve-path parquet reads borrow pages from
    the OS file mapping instead of copying onto the heap."""
    return session is not None and session.conf.io_mmap_enabled


# Streaming-serve telemetry (docs/out-of-core.md): wave counters of the
# LAST streamed join, reset at the start of each — the stream analogue of
# ``join_exec.last_serve_breakdown`` (same process-global, last-writer-
# wins diagnostic scope: bench.py and the smoke gate read it between
# queries; concurrent streams only blur this attribution, never results).
last_stream_stats: Dict[str, int] = {}
_stream_stats_lock = _threading.Lock()


def stream_stats_reset() -> None:
    with _stream_stats_lock:
        last_stream_stats.clear()


def _stream_stats_add(key: str, amount: int = 1) -> None:
    with _stream_stats_lock:
        last_stream_stats[key] = last_stream_stats.get(key, 0) + amount


def _serve_shards(session) -> int:
    """Shard count for the device-local serve tail
    (``hyperspace.build.shardedTail.enabled``, one flag for both
    planes): the session mesh size when the flag is on and the mesh has
    more than one device, else 1 (single-tail scheduling). The shard
    layout is the build's bucket ownership (``bucket % D``) — each
    worker prepares and merges only the buckets its shard owns, with a
    per-bucket union at the edge (bit-identical output)."""
    if session is None or not session.conf.build_sharded_tail:
        return 1
    return int(session.runtime.mesh.devices.size)


def _cacheable_scan(rel) -> bool:
    """Only clean INDEX scans are cached (index data files are immutable
    and bounded; pinning arbitrary source tables in RAM is not this
    feature): no row-level delete compensation, no injected partition
    constants (both are query-shaped state that must not leak between
    queries)."""
    return (
        rel.index_info is not None
        and rel.fmt in ("parquet", "delta", "iceberg")
        and rel.excluded_file_ids is None
        and not rel.file_partition_values
        and bool(rel.files)
    )


def _scan_cache_entry(rel, needed: Set[str], session):
    """(ScanCacheEntry, cols) for a clean index scan from the serve
    cache — one entry per file set, columns accruing on demand so
    overlapping projections share a single decoded copy per column —
    or None when serve-server mode is off / the scan is not cacheable."""
    cache = _serve_cache(session)
    if cache is None or not _cacheable_scan(rel):
        return None
    from hyperspace_tpu.execution.serve_cache import (
        ScanCacheEntry,
        file_fingerprint,
    )

    fp = file_fingerprint(rel.files)
    if fp is None:
        return None
    cols = tuple(c for c in rel.column_names if c in needed) or (
        rel.column_names[0],
    )
    key = ("scan", fp)
    state = cache.get(key)
    if state is None:
        counts = pio.file_row_counts(list(rel.files))
        segs = []
        pos = 0
        for c in counts:
            segs.append((pos, pos + c))
            pos += c
        state = ScanCacheEntry(segs)
    missing = [c for c in cols if c not in state.columns]
    if missing:
        table = pio.read_table(list(rel.files), missing, rel.fmt)
        from hyperspace_tpu.io.columnar import Column

        new_cols = {c: Column.from_arrow(table.column(c)) for c in missing}
        # copy-on-write publication (ScanCacheEntry concurrency
        # contract): never mutate an entry other threads may hold, and
        # merge onto the FRESHEST published entry (non-counting peek) so
        # a racing thread's just-published columns survive. The union
        # also keeps THIS thread's stale-entry columns — the freshest
        # entry may lack them after an evict/recreate race — so the
        # returned entry always covers ``cols``.
        latest = cache.peek(key)
        base = latest if latest is not None else state
        stale_extra = {
            c: col
            for c, col in state.columns.items()
            if c not in base.columns
        }
        state = base.with_new_columns({**stale_extra, **new_cols})
        cache.put(key, state, state.budget_nbytes)
    return state, cols


def _cached_filter(
    scan: Scan, cond: E.Expr, child_needed: Set[str], session
) -> Optional[ColumnarBatch]:
    """Serve a Filter∘Scan from the serve cache (None = cache off/miss
    path not applicable; caller runs the normal read).

    On a cached key-sorted index bucket a pinned-key conjunct narrows the
    candidate rows by binary search (``ScanCacheEntry``) before the
    full mask runs — the RAM-resident analogue of the parquet row-group
    pruning the cold path gets from ``_pushdown_filters``, but without
    re-reading anything.
    """
    hit = _scan_cache_entry(scan.relation, child_needed, session)
    if hit is None:
        return None
    state, cols = hit
    rel = scan.relation
    batch = state.batch_for(cols)
    idx = _sorted_narrow(state, cond, rel)
    if idx is not None:
        sub = batch.take(idx)
        return sub.filter(_filter_mask(cond, sub, session))
    return batch.filter(_filter_mask(cond, batch, session))


def _sorted_narrow(state, cond: E.Expr, rel) -> Optional[np.ndarray]:
    """Candidate row indices (ascending) from the first conjunct that can
    binary-search a segment-sorted cached column, else None.

    Soundness: the returned set must be a SUPERSET of the rows matching
    the full condition (the caller re-applies the whole mask on the
    subset). Equality/IN search by key rep is a superset for every type
    (value equality ⇒ rep equality). Range conjuncts additionally need
    rep order == value order, which holds for signed ints / temporals /
    bools but NOT floats (sign-bit view) or strings (hashes) — those fall
    through to the full mask.
    """
    cols = {c.lower(): c for c in rel.column_names}
    import pyarrow as pa

    def order_preserving(t: pa.DataType) -> bool:
        return (
            pa.types.is_signed_integer(t)
            or pa.types.is_temporal(t)
            or pa.types.is_boolean(t)
        )

    for cj in E.split_conjuncts(cond):
        col = None
        pts = None  # list of key reps for =/IN
        bound = None  # (op, rep) for range conjuncts
        norm = E.normalize_comparison(cj)
        if norm is not None:
            op, name, lit = norm
            col = cols.get(name.lower())
            if col is None or lit is None:
                continue
            rep = _literal_key_rep(lit, rel.schema[col])
            if rep is None:
                continue
            if op == "=":
                pts = [rep]
            elif op in ("<", "<=", ">", ">=") and order_preserving(
                rel.schema[col]
            ):
                bound = (op, rep)
            else:
                continue
        elif isinstance(cj, E.In) and isinstance(cj.child, E.Col):
            col = cols.get(cj.child.name.lower())
            if col is None:
                continue
            vals = [v for v in cj.values if v is not None]
            if not vals or len(vals) > _MAX_PRUNE_COMBOS:
                continue
            pts = []
            for v in vals:
                rep = _literal_key_rep(v, rel.schema[col])
                if rep is None:
                    pts = None
                    break
                pts.append(rep)
            if pts is None:
                continue
        else:
            continue
        if col not in state.columns:
            continue
        krep, sorted_ok = state.column_state(col)
        if not sorted_ok:
            continue
        parts = []
        for s, e in state.segments:
            seg = krep[s:e]
            if pts is not None:
                for p in set(pts):
                    a = int(np.searchsorted(seg, p, side="left"))
                    b = int(np.searchsorted(seg, p, side="right"))
                    if b > a:
                        parts.append(np.arange(s + a, s + b, dtype=np.int64))
            else:
                op, rep = bound
                if op == "<":
                    a, b = 0, int(np.searchsorted(seg, rep, side="left"))
                elif op == "<=":
                    a, b = 0, int(np.searchsorted(seg, rep, side="right"))
                elif op == ">":
                    a, b = int(np.searchsorted(seg, rep, side="right")), e - s
                else:  # >=
                    a, b = int(np.searchsorted(seg, rep, side="left")), e - s
                if b > a:
                    parts.append(np.arange(s + a, s + b, dtype=np.int64))
        if not parts:
            return np.zeros(0, dtype=np.int64)
        idx = np.concatenate(parts)
        # ascending row order (IN points may interleave within a segment);
        # ranges are disjoint after the per-point dedup, so no unique needed
        return np.sort(idx)
    return None


def _exec_join(plan: Join, needed: Set[str], session) -> ColumnarBatch:
    pairs = E.equi_join_pairs(plan.condition)
    if pairs is None:
        raise HyperspaceException(
            f"Only conjunctive equi-joins are executable: {plan.condition!r}"
        )
    lcols = set(plan.left.output)
    on = []
    for a, b in pairs:
        if a in lcols:
            on.append((a, b))
        else:
            on.append((b, a))
    l_needed = (needed & lcols) | {l for l, _ in on}
    rcols = set(plan.right.output)
    r_needed = (needed & rcols) | {r for _, r in on}
    from hyperspace_tpu.execution.join_exec import (
        co_bucketed_join_prepared,
        inner_join,
    )

    layout = _aligned_bucket_layouts(plan, on)
    if layout is not None:
        # Shuffle-free co-bucketed join (the JoinIndexRule payoff; the
        # physical analogue of Spark SMJ over co-bucketed index scans with
        # no Exchange, JoinIndexRule.scala:619-634): the per-bucket merge
        # runs as one compiled program, buckets sharded across the mesh.
        # Prepared sides (concat + key reps + sortedness) are retained by
        # the serve cache, so a warm serve pays only match + assemble.
        num_buckets, l_bucket_cols, r_bucket_cols = layout
        from hyperspace_tpu.execution.join_exec import serve_breakdown_reset

        serve_breakdown_reset()
        l_keys = [l for l, _ in on]
        r_keys = [r for _, r in on]
        if _serve_stream_on(session):
            # Out-of-core serve (docs/out-of-core.md): buckets stream
            # through in waves sized by hyperspace.serve.stream.maxBytes —
            # prepared sides are produced, matched, expanded and RELEASED
            # per wave instead of materialized whole. Returns None when
            # either side's shape does not stream (this materializing
            # path then runs unchanged).
            streamed = _exec_join_streaming(
                plan, needed, session, layout, on, l_needed, r_needed
            )
            if streamed is not None:
                return streamed
        # Pipelined serve: both sides prepare CONCURRENTLY (each side's
        # per-bucket reads already overlap its prepare via the scan
        # pool). Gated on both children being clean index-scan shapes —
        # exactly the shapes whose execution touches no device kernels
        # and no query-shaped state, so the thread fan-out is safe. A
        # SELF-join whose sides would resolve to the same serve-cache
        # entry stays sequential: racing both sides past the shared miss
        # would double the full read+prepare the second side gets for
        # free from the first side's put.
        rels_l = _joinside_cache_relations(plan.left)
        rels_r = _joinside_cache_relations(plan.right)
        same_cached_side = (
            _serve_cache(session) is not None
            and rels_l is not None
            and rels_l == rels_r
            and l_needed == r_needed
            and l_keys == r_keys
        )
        if (
            _serve_pipeline_on(session)
            and rels_l is not None
            and rels_r is not None
            and not same_cached_side
        ):
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="hs-joinside"
            ) as side_pool:
                # trace.carry: contextvars do not cross pool threads —
                # the side prepares' stage spans must still attach to
                # the query's root span (identity when obs is off)
                fl = side_pool.submit(
                    _obs_trace.carry(_prepared_join_side),
                    plan.left, l_needed, session, l_bucket_cols, l_keys,
                )
                fr = side_pool.submit(
                    _obs_trace.carry(_prepared_join_side),
                    plan.right, r_needed, session, r_bucket_cols, r_keys,
                )
                lp = fl.result()
                rp = fr.result()
        else:
            lp = _prepared_join_side(
                plan.left, l_needed, session, l_bucket_cols, l_keys
            )
            rp = _prepared_join_side(
                plan.right, r_needed, session, r_bucket_cols, r_keys
            )
        mesh = session.runtime.mesh if session is not None else None
        min_rows = (
            session.conf.device_join_min_rows if session is not None else 0
        )
        joined = (
            co_bucketed_join_prepared(
                lp, rp, on, mesh, min_rows, num_shards=_serve_shards(session)
            )
            if lp is not None and rp is not None
            else None
        )
        if joined is not None:
            return joined
        import pyarrow as pa

        schema = plan.schema()
        out_cols = [c for c in plan.output if c in (needed | set(
            [x for p in on for x in p]))]
        return ColumnarBatch.from_arrow(
            pa.table({c: pa.array([], type=schema[c]) for c in out_cols})
        )
    left = _exec(plan.left, l_needed, session)
    right = _exec(plan.right, r_needed, session)
    return inner_join(left, right, on)


def _joinside_cache_relations(plan):
    """Relations whose combined file fingerprints key a cacheable
    prepared join side, or None when the child's shape is not cacheable.

    Two shapes qualify: a clean Project*(Scan) chain over an index scan
    (index-only serve), and a clean Project*(Union(Project*(Scan),
    Project*(Scan))) where the left is an index scan and the right is the
    Hybrid-Scan APPEND compensation over immutable source files — keying
    on both file sets means a further append (new file) or refresh (new
    index version) changes the fingerprint and can never serve stale.
    Delete compensation (excluded_file_ids / lineage filters) breaks the
    shape and stays uncached."""

    def walk(node):
        while isinstance(node, Project):
            node = node.child
        return node

    node = walk(plan)
    if isinstance(node, Scan) and _cacheable_scan(node.relation):
        return [node.relation]
    if isinstance(node, Union):
        left, right = walk(node.left), walk(node.right)
        if (
            isinstance(left, Scan)
            and isinstance(right, Scan)
            and _cacheable_scan(left.relation)
            and right.relation.fmt in ("parquet", "delta", "iceberg")
            and right.relation.excluded_file_ids is None
            and not right.relation.file_partition_values
            and bool(right.relation.files)
        ):
            return [left.relation, right.relation]
    return None


def _prepared_join_side(
    plan: LogicalPlan, needed: Set[str], session, bucket_cols, key_cols
):
    """A PreparedJoinSide for one co-bucketed join child, served from the
    serve cache when the child is a clean Project*(Scan) chain (the plan
    shape of an index-only scan) or a Hybrid-Scan append union of two
    such chains. Returns None for an empty side.

    On a cache miss (or with the cache off) the pipelined serve path
    streams per-bucket batches straight into
    ``prepare_join_side_pipelined``: bucket *i*'s reps/combine run while
    the scan pool is still reading bucket *i+1*, and — on the hybrid
    Union shape — the appended-files delta prepares concurrently with
    the index-side reads. Falls back to the sequential
    ``_exec_bucketed`` + ``prepare_join_side`` whenever the shape or
    caching situation is anything but the clean serve case."""
    from hyperspace_tpu.execution.join_exec import (
        prepare_join_side,
        prepare_join_side_pipelined,
    )

    cache = _serve_cache(session)
    key = None
    if cache is not None:
        rels = _joinside_cache_relations(plan)
        if rels is not None:
            from hyperspace_tpu.execution.serve_cache import file_fingerprint

            fps = tuple(file_fingerprint(r.files) for r in rels)
            if None not in fps:
                key = (
                    "joinside",
                    fps,
                    tuple(sorted(needed)),
                    tuple(key_cols),
                )
                hit = cache.get(key)
                if hit is not None:
                    return hit
    # Pipelined path only when it cannot change caching behavior: with
    # the cache off nothing is cached either way; with a joinside key the
    # raw bucketed batches are deliberately NOT cached (see below). The
    # odd corner — cache on but the file set unfingerprintable — keeps
    # the sequential path and its ("bucketed", …) entries.
    if _serve_pipeline_on(session) and (cache is None or key is not None):
        stream = _bucket_stream(plan, needed, session, bucket_cols)
        if stream is not None:
            prep = prepare_join_side_pipelined(
                stream, key_cols, num_shards=_serve_shards(session)
            )
            if prep is not None and key is not None:
                cache.put(key, prep, prep.nbytes)
            return prep
    # when a joinside entry will be cached, don't ALSO cache the raw
    # bucketed batches — the prepared side contains the same decoded data
    # (a second full copy would halve effective cache capacity)
    bs = _exec_bucketed(plan, needed, session, bucket_cols, cache_scan=key is None)
    if not bs:
        return None
    prep = prepare_join_side(bs, key_cols)
    if key is not None:
        cache.put(key, prep, prep.nbytes)
    return prep


def _stream_side_probe(plan: LogicalPlan, needed: Set[str], session, bucket_cols):
    """Wave-streamable decomposition of one join side, or None when the
    shape does not support streaming (the caller falls back to the
    materializing path). Shape scope mirrors ``_exec_bucketed`` /
    ``_bucket_stream``: a Project* chain over a clean multi-file index
    Scan, optionally through one Hybrid-Scan append Union whose
    appended-files delta is prepared ONCE up front (``_prepare_delta`` —
    ratio-capped, so it is wave-independent fixed residency). The probe
    reads only parquet footers: per-bucket row counts seed the wave
    planner's byte estimates without touching data pages."""
    from hyperspace_tpu.io.parquet import bucket_id_of_file

    sel_chain = []  # Project selects outermost-first; applied reversed
    node = plan
    nd = set(needed)
    while isinstance(node, Project):
        cols = [c for c in node.columns if c in nd] or node.columns
        sel_chain.append(cols)
        nd = set(cols)
        node = node.child
    read_cols = None
    delta_parts = None
    inner_chain = []
    if isinstance(node, Union):
        cols = [c for c in node.output if c in nd] or node.output[:1]
        read_cols = sorted(set(cols) | set(bucket_cols))
        spec = _bucket_layout(node.left)
        if spec is None:
            return None
        delta_parts = _prepare_delta(
            node.right, read_cols, session, bucket_cols, spec[0]
        )
        inner = node.left
        nd = set(read_cols)
        while isinstance(inner, Project):
            cols = [c for c in inner.columns if c in nd] or inner.columns
            inner_chain.append(cols)
            nd = set(cols)
            inner = inner.child
        node = inner
    if not isinstance(node, Scan):
        return None
    rel = node.relation
    groups: dict = {}
    for f in rel.files:
        b = bucket_id_of_file(f)
        groups.setdefault(b, []).append(f)
    streamable = (
        rel.fmt in ("parquet", "delta", "iceberg")
        and rel.excluded_file_ids is None
        and not rel.file_partition_values
        and len(rel.files) > 1
        and None not in groups
    )
    if not streamable:
        return None
    scan_cols = [c for c in rel.column_names if c in nd] or (
        rel.column_names[:1]
    )
    all_files = [f for b in sorted(groups) for f in groups[b]]
    counts = pio.file_row_counts(all_files)
    rows_of = dict(zip(all_files, counts))
    bucket_rows = {b: sum(rows_of[f] for f in groups[b]) for b in groups}
    return {
        "rel": rel,
        "groups": groups,
        "scan_cols": scan_cols,
        "bucket_rows": bucket_rows,
        "sel_chain": sel_chain,
        "inner_chain": inner_chain,
        "read_cols": read_cols,
        "delta_parts": delta_parts,
    }


def _stream_side_bytes(state) -> Dict[int, int]:
    """Estimated decoded bytes per bucket for wave packing: footer row
    counts × projected column count × 8 for the scan part (strings cost
    more than 8 bytes/row — the budget is a planning estimate, and the
    prepared side's reps/combined overhead rides on top; see
    docs/out-of-core.md for tuning), plus the real size of any delta
    part landing in the bucket."""
    est = {
        b: r * len(state["scan_cols"]) * 8
        for b, r in state["bucket_rows"].items()
    }
    if state["delta_parts"]:
        from hyperspace_tpu.execution.serve_cache import batch_nbytes

        for b, part in state["delta_parts"].items():
            est[b] = est.get(b, 0) + batch_nbytes(part)
    return est


def _stream_wave_side(state, wave, session):
    """One wave's worth of one side: the clean-scan shape returns
    ``(contiguous_batch, buckets, sizes)`` — a single threaded read whose
    decoded table IS the bucket-ordered concatenation, handed to
    ``prepare_join_side_contiguous`` with no per-bucket copies — while
    the hybrid Union shape returns a per-bucket dict (index slices merged
    with the precomputed delta parts, exactly the ``_exec_bucketed``
    Union recipe)."""
    import time as _t

    from hyperspace_tpu.execution import join_exec as _je

    groups = state["groups"]
    rel = state["rel"]
    in_scan = [b for b in wave if b in groups]
    table = None
    if in_scan:
        files = [f for b in in_scan for f in groups[b]]
        t0 = _t.perf_counter()
        table = pio.read_table(
            files, state["scan_cols"], rel.fmt,
            memory_map=_io_mmap_on(session),
        )
        _je._stage_add("scan", t0)
    if state["read_cols"] is None:
        # clean index scan: decode the wave read once, select once
        t0 = _t.perf_counter()
        batch = ColumnarBatch.from_arrow(table)
        for cols in reversed(state["sel_chain"]):
            batch = batch.select(
                [c for c in cols if c in batch.column_names]
            )
        _je._stage_add("prepare", t0)
        sizes = [state["bucket_rows"][b] for b in in_scan]
        return batch, in_scan, sizes
    # hybrid shape: per-bucket slices like _exec_bucketed's fast path,
    # inner selects, merge delta parts, outer selects
    t0 = _t.perf_counter()
    out = {}
    pos = 0
    for b in in_scan:
        c = state["bucket_rows"][b]
        bb = ColumnarBatch.from_arrow(table.slice(pos, c))
        pos += c
        for cols in reversed(state["inner_chain"]):
            bb = bb.select([x for x in cols if x in bb.column_names])
        out[b] = bb.select(state["read_cols"])
    for b in wave:
        part = state["delta_parts"].get(b)
        if part is None:
            continue
        if b in out:
            out[b] = ColumnarBatch.concat([out[b], part])
        else:
            out[b] = part
    for cols in reversed(state["sel_chain"]):
        out = {
            b: bb.select([x for x in cols if x in bb.column_names])
            for b, bb in out.items()
        }
    _je._stage_add("prepare", t0)
    return out


def _stream_wave_prepared(state, wave, key_cols, session):
    """PreparedJoinSide for one side's wave (None for an empty wave)."""
    from hyperspace_tpu.execution.join_exec import (
        prepare_join_side,
        prepare_join_side_contiguous,
    )

    side = _stream_wave_side(state, wave, session)
    if isinstance(side, dict):
        return prepare_join_side(side, key_cols) if side else None
    batch, buckets, sizes = side
    return prepare_join_side_contiguous(batch, tuple(buckets), sizes, key_cols)


def _exec_join_streaming(
    plan: Join, needed: Set[str], session, layout, on, l_needed, r_needed
):
    """Streaming per-bucket join serve: the bucket is the unit of
    residency (docs/out-of-core.md). Common buckets are packed into WAVES
    whose estimated decoded bytes across both sides fit the
    ``hyperspace.serve.stream.maxBytes`` budget (an oversized bucket runs
    as its own wave — correctness never depends on the estimate); each
    wave is read, prepared, matched, expanded, and RELEASED before the
    next wave's read begins, so peak prepared-side residency is one wave
    instead of the whole join. Wave outputs concatenate in ascending
    bucket order — bit-identical to the materializing path: buckets are
    independent, per-wave null sentinels are re-verified exactly like the
    full-side ones, and the presorted-bucket native fast path applies per
    wave whenever it applied to the full side. Returns None when either
    side's shape does not stream (caller falls back). This path
    deliberately skips the joinside/bucketed serve-cache entries: the
    point of streaming is sides too large to pin, and a wave-sized cache
    entry would alias the materializing path's keys."""
    import time as _t

    from hyperspace_tpu.execution import join_exec as _je
    from hyperspace_tpu.execution.join_exec import co_bucketed_join_prepared

    num_buckets, l_bucket_cols, r_bucket_cols = layout
    l_state = _stream_side_probe(plan.left, l_needed, session, l_bucket_cols)
    if l_state is None:
        return None
    r_state = _stream_side_probe(plan.right, r_needed, session, r_bucket_cols)
    if r_state is None:
        return None
    stream_stats_reset()
    l_keys = [l for l, _ in on]
    r_keys = [r for _, r in on]
    l_est = _stream_side_bytes(l_state)
    r_est = _stream_side_bytes(r_state)
    # only buckets present on BOTH sides can produce pairs; one-sided
    # buckets are never read at all (the materializing path reads them
    # and then drops them at the common-bucket subset)
    common = sorted(set(l_est) & set(r_est))
    budget = session.conf.serve_stream_max_bytes
    waves = []
    cur: list = []
    cur_bytes = 0
    for b in common:
        nb = l_est.get(b, 0) + r_est.get(b, 0)
        if cur and cur_bytes + nb > budget:
            waves.append(cur)
            cur, cur_bytes = [], 0
        cur.append(b)
        cur_bytes += nb
    if cur:
        waves.append(cur)
    mesh = session.runtime.mesh if session is not None else None
    min_rows = (
        session.conf.device_join_min_rows if session is not None else 0
    )
    parts = []
    if waves:
        from concurrent.futures import ThreadPoolExecutor

        # both sides of a wave read+prepare concurrently (the same
        # 2-worker side fan-out as the materializing pipelined path;
        # trace.carry keeps their stage spans on the query's root span)
        with ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="hs-stream"
        ) as side_pool:
            for wave in waves:
                t0 = _t.perf_counter()
                fl = side_pool.submit(
                    _obs_trace.carry(_stream_wave_prepared),
                    l_state, wave, l_keys, session,
                )
                fr = side_pool.submit(
                    _obs_trace.carry(_stream_wave_prepared),
                    r_state, wave, r_keys, session,
                )
                lp = fl.result()
                rp = fr.result()
                joined = (
                    co_bucketed_join_prepared(
                        lp, rp, on, mesh, min_rows,
                        num_shards=_serve_shards(session),
                    )
                    if lp is not None and rp is not None
                    else None
                )
                if joined is not None:
                    parts.append(joined)
                # lp/rp (and their reps/combined) release here — the wave
                # is the residency high-water mark, not the join
                lp = rp = None
                _stream_stats_add("stream_waves")
                _stream_stats_add("stream_buckets", len(wave))
                _je._stage_add("stream_wave", t0)
    if parts:
        return ColumnarBatch.concat(parts)
    import pyarrow as pa

    schema = plan.schema()
    out_cols = [c for c in plan.output if c in (needed | set(
        [x for p in on for x in p]))]
    return ColumnarBatch.from_arrow(
        pa.table({c: pa.array([], type=schema[c]) for c in out_cols})
    )


def _literal_key_rep(value, arrow_type):
    """The literal's int64 key rep under the same path data takes
    (Column.key_rep), or None when it cannot be represented losslessly."""
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import Column

    try:
        arr = pa.array([value], type=arrow_type)
    except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError, TypeError):
        return None
    col = Column.from_arrow(arr)
    if col.null_mask is not None:
        return None
    return int(col.key_rep()[0])


_MAX_PRUNE_COMBOS = 64


def _bucket_pruned_scan(plan: LogicalPlan, cond: E.Expr) -> LogicalPlan:
    """Bucket pruning: when a filter over a bucketed index scan pins every
    bucket column to literals (Eq / In conjuncts), drop the bucket files
    that cannot contain matching rows.

    The executor-side payoff of FilterIndexRule's bucketSpec — the
    reference gets this from Spark's bucket pruning when
    ``index.filterRule.useBucketSpec`` is on (IndexConstants.scala:56-57);
    here it turns a point lookup into a read of 1/num_buckets of the index.
    """
    import dataclasses
    import itertools

    from hyperspace_tpu.ops.hash import bucket_ids_np

    if not isinstance(plan, Scan) or plan.relation.bucket_spec is None:
        return plan
    rel = plan.relation
    num_buckets, bucket_cols = rel.bucket_spec
    schema = rel.schema
    conjuncts = E.split_conjuncts(cond)
    value_lists = []
    for bc in bucket_cols:
        vals = None
        for cj in conjuncts:
            norm = E.normalize_comparison(cj)
            if norm is not None:
                op, name, lit = norm
                if op == "=" and name.lower() == bc.lower():
                    vals = [lit]
                    break
            elif (
                isinstance(cj, E.In)
                and isinstance(cj.child, E.Col)
                and cj.child.name.lower() == bc.lower()
            ):
                vals = [v for v in cj.values if v is not None]
                break
        if not vals:
            return plan  # bucket column not pinned: no pruning
        value_lists.append(vals)
    n_combos = 1
    for vl in value_lists:
        n_combos *= len(vl)
    if n_combos > _MAX_PRUNE_COMBOS:
        return plan
    rep_lists = []
    for bc, vals in zip(bucket_cols, value_lists):
        reps = []
        for v in vals:
            rep = _literal_key_rep(v, schema[bc])
            if rep is None:
                return plan
            reps.append(rep)
        rep_lists.append(reps)
    # one kernel dispatch over all combinations: [k, n_combos]
    combos = np.array(
        list(itertools.product(*rep_lists)), dtype=np.int64
    ).T.reshape(len(bucket_cols), -1)
    keep_buckets = set(bucket_ids_np(combos, num_buckets).tolist())
    bucket_of = _bucket_ids_of_files(rel.files)
    kept = tuple(
        f
        for f, b in zip(rel.files, bucket_of)
        if b is None or b in keep_buckets
    )
    if len(kept) == len(rel.files):
        return plan
    return Scan(dataclasses.replace(rel, files=kept))


@_lru_cache(maxsize=1024)
def _bucket_ids_of_files(files) -> tuple:
    """Per-file bucket ids for a relation's file tuple, memoized.

    ``_bucket_pruned_scan`` used to re-run the filename regex over every
    file on every query; a relation's file tuple is its content identity
    for this purpose (bucket ids are a pure function of the immutable
    file NAMES, and a refresh/optimize changes the file set and thereby
    the key), so one parse per distinct file set suffices."""
    from hyperspace_tpu.io.parquet import bucket_id_of_file

    return tuple(bucket_id_of_file(f) for f in files)


def _fused_pipeline_on(session) -> bool:
    """Fused serve-pipeline compiler
    (``hyperspace.serve.fusedpipeline.enabled``, default on). Applies to
    sessionless execution too — a pure compute substitution with
    bit-identical output, like range pruning."""
    from hyperspace_tpu.execution.pipeline_compiler import fused_pipeline_on

    return fused_pipeline_on(session)


def _rangeprune_on(session) -> bool:
    """Zone-map range pruning (``hyperspace.serve.rangeprune.enabled``,
    default on). Unlike the serve pipeline this also applies to
    sessionless execution — pruning is a pure read-side narrowing with no
    thread fan-out of its own."""
    from hyperspace_tpu import constants as C

    if session is None:
        return C.SERVE_RANGEPRUNE_ENABLED_DEFAULT
    return session.conf.serve_rangeprune_enabled


def _range_pruned_scan(
    plan: LogicalPlan, cond: E.Expr, session
) -> LogicalPlan:
    """Zone-map pruning for index scans under a Filter: drop index files
    (and narrow survivors to matching row groups) that the predicate's
    range/Eq/In conjuncts cannot touch, per ``indexes/zonemaps.py``. The
    executor-side payoff the reference gets from Spark's parquet min/max
    pruning — generalized to whole-file drops, a vectorized pass over
    all files at once, and z-address range decomposition for z-order
    relations (docs/range-serve.md). Recurses through Project/Union so
    the Hybrid-Scan index side prunes too; non-index relations (e.g. the
    appended-files side) pass through untouched."""
    if not _rangeprune_on(session):
        return plan

    from hyperspace_tpu.indexes import zonemaps

    cache = _serve_cache(session)

    def walk(node):
        if isinstance(node, Scan):
            if cache is not None and _cacheable_scan(node.relation):
                # serve-server mode keeps FULL decoded files in RAM keyed
                # by the complete file set, shared across predicates and
                # narrowed by binary search — pruning a cacheable scan
                # would only fragment that entry into per-predicate file
                # subsets. Cold serves (cache off) and uncacheable index
                # scans (e.g. hybrid delete compensation) still prune.
                return node
            return zonemaps.prune_scan_relation(node, cond, cache)
        if isinstance(node, Project):
            child = walk(node.child)
            return node if child is node.child else Project(node.columns, child)
        if isinstance(node, Union):
            left, right = walk(node.left), walk(node.right)
            if left is node.left and right is node.right:
                return node
            return Union(left, right)
        return node

    return walk(plan)


def _pushable_literal(value, arrow_type):
    """Literal in a form pyarrow's parquet filters accept for a column of
    ``arrow_type``, or None when it must not be pushed (type-mismatched
    literals would make the dataset filter error at read time; the
    engine's own mask treats them as never-matching instead)."""
    import pyarrow as pa

    if value is None or arrow_type is None:
        return None
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        value = value.item()
    if pa.types.is_temporal(arrow_type):
        if pa.types.is_duration(arrow_type):
            # duration filters are not pushed: arrow's scalar coercion for
            # timedelta literals does not mirror the engine's tick
            # lowering; skipping pushdown is always superset-safe
            return None
        if getattr(arrow_type, "tz", None) is not None:
            # tz-aware columns: arrow refuses naive-vs-aware comparisons
            return None
        # only literals exactly representable in the column type: ±inf
        # clamps and between-tick values would overflow/err in arrow's cast
        if not isinstance(E.lower_literal(value, arrow_type), np.int64):
            return None
        return E.normalize_temporal_literal(value, arrow_type)
    if pa.types.is_boolean(arrow_type):
        return value if isinstance(value, bool) else None
    if pa.types.is_integer(arrow_type) or pa.types.is_floating(arrow_type):
        if isinstance(value, bool):
            return int(value)  # engine: flag == True matches 1
        if isinstance(value, int):
            # arrow converts through C long: out-of-int64-range literals
            # raise there; the engine treats them as never-matching
            if not (-(2**63) <= value < 2**63):
                return None
            return value
        return value if isinstance(value, float) else None
    t = arrow_type
    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return value if isinstance(value, str) else None
    return None


def _pushdown_filters(cond: E.Expr, rel):
    """Pyarrow DNF filter (single conjunction) from the predicate's
    simple conjuncts.

    Sound by construction under the ROW-LEVEL-superset invariant
    (``io/parquet.read_table``): pyarrow >= 14 applies these filters per
    row via the dataset API, so every pushed conjunct's pyarrow
    evaluation must keep a row-level superset of the rows the engine's
    own mask keeps — merely row-group-safe conjuncts (e.g. literals
    rounded toward engine semantics) must NOT be pushed. Today only
    plain col-op-literal and IN with exactly-representable literals
    qualify (null/NaN drop the same rows in both engines;
    ``_pushable_literal`` refuses lossy literal conversions), and the
    executor re-applies the full mask after the read. On a key-sorted
    index bucket this turns a point lookup into a read of the one row
    group whose min/max covers the key.
    """
    if rel.fmt not in ("parquet", "delta", "iceberg"):
        return None
    cols = {c.lower(): c for c in rel.column_names}
    out = []
    for cj in E.split_conjuncts(cond):
        norm = E.normalize_comparison(cj)
        if norm is not None:
            op, name, lit = norm
            col = cols.get(name.lower())
            if col is None:
                continue
            lit = _pushable_literal(lit, rel.schema[col])
            if lit is None:
                continue
            out.append((col, op if op != "=" else "==", lit))
        elif isinstance(cj, E.In) and isinstance(cj.child, E.Col):
            col = cols.get(cj.child.name.lower())
            if col is None:
                continue
            vals = [
                lv
                for v in cj.values
                if v is not None
                for lv in [_pushable_literal(v, rel.schema[col])]
                if lv is not None
            ]
            if not vals or len(vals) != len(
                [v for v in cj.values if v is not None]
            ):
                continue  # partial lists would under-keep: skip
            out.append((col, "in", vals))
    return out or None


def _bucket_layout(plan: LogicalPlan):
    """(num_buckets, bucket_cols) if the subtree preserves a bucketed scan
    layout (Scan with bucket_spec under Filter/Project/Union)."""
    if isinstance(plan, Scan):
        return plan.relation.bucket_spec
    if isinstance(plan, Filter):
        return _bucket_layout(plan.child)
    if isinstance(plan, Project):
        spec = _bucket_layout(plan.child)
        if spec and all(c in plan.columns for c in spec[1]):
            return spec
        return None
    if isinstance(plan, Union):
        # hybrid scan: the index side (left) defines the layout; the
        # appended side is re-bucketed at execution time
        return _bucket_layout(plan.left)
    return None


def _aligned_bucket_layouts(plan: Join, on):
    """Both sides bucketed, same count, and bucket columns positionally
    aligned through the join mapping (order matters: the bucket hash chains
    over columns in order — mirroring Spark's order-sensitive
    HashPartitioning compatibility)."""
    l_spec = _bucket_layout(plan.left)
    r_spec = _bucket_layout(plan.right)
    if not l_spec or not r_spec:
        return None
    (ln, lcols), (rn, rcols) = l_spec, r_spec
    if ln != rn or len(lcols) != len(rcols):
        return None
    mapping = {l: r for l, r in on}
    for lc, rc in zip(lcols, rcols):
        if mapping.get(lc) != rc:
            return None
    return ln, tuple(lcols), tuple(rcols)


def _exec_bucketed(
    plan: LogicalPlan, needed: Set[str], session, bucket_cols,
    cache_scan: bool = True,
):
    """Execute a linear subtree into per-bucket batches.

    Index scans recover the bucket id from file names; appended (hybrid)
    rows are hashed on device — the execution-time equivalent of the
    reference's on-the-fly shuffle of appended data
    (CoveringIndexRuleUtils.transformPlanToShuffleUsingBucketSpec:357-417).
    """
    import dataclasses

    from hyperspace_tpu.io.parquet import bucket_id_of_file
    from hyperspace_tpu.ops.hash import bucket_ids_np

    if isinstance(plan, Scan):
        rel = plan.relation
        groups = {}
        for f in rel.files:
            b = bucket_id_of_file(f)
            groups.setdefault(b, []).append(f)
        fast = (
            rel.fmt in ("parquet", "delta", "iceberg")
            and rel.excluded_file_ids is None
            and not rel.file_partition_values
            and len(rel.files) > 1
            and None not in groups
        )
        if fast:
            # one threaded read over every bucket's files, sliced back into
            # buckets via footer row counts — N small per-bucket reads pay
            # a per-call cost that dominates serve latency otherwise
            cols = [c for c in rel.column_names if c in needed] or (
                rel.column_names[:1]
            )
            cache = _serve_cache(session)
            key = None
            if cache_scan and cache is not None and _cacheable_scan(rel):
                from hyperspace_tpu.execution.serve_cache import (
                    file_fingerprint,
                )

                fp = file_fingerprint(rel.files)
                if fp is not None:
                    key = ("bucketed", fp, tuple(cols))
                    hit = cache.get(key)
                    if hit is not None:
                        return dict(hit)
            ordered = [(b, f) for b in sorted(groups) for f in groups[b]]
            counts = pio.file_row_counts([f for _, f in ordered])
            table = pio.read_table(
                [f for _, f in ordered], cols, rel.fmt,
                memory_map=_io_mmap_on(session),
            )
            per_bucket = {}
            for (b, _f), c in zip(ordered, counts):
                per_bucket[b] = per_bucket.get(b, 0) + c
            out = {}
            pos = 0
            for b in sorted(groups):
                c = per_bucket[b]
                # zero-copy arrow slice per bucket, decoded directly —
                # one decode copy total instead of decode-everything plus
                # a gather per bucket
                out[b] = ColumnarBatch.from_arrow(table.slice(pos, c))
                pos += c
            if key is not None:
                from hyperspace_tpu.execution.serve_cache import batch_nbytes

                cache.put(
                    key,
                    dict(out),
                    sum(batch_nbytes(b) for b in out.values()),
                )
            return out
        out = {}
        for b, files in groups.items():
            sub = Scan(dataclasses.replace(rel, files=tuple(files)))
            out[b] = _exec_scan(sub, needed, session)
        return out
    if isinstance(plan, Filter):
        child_needed = set(needed) | E.references(plan.condition)
        out = {}
        for b, batch in _exec_bucketed(
            plan.child, child_needed, session, bucket_cols, cache_scan
        ).items():
            out[b] = batch.filter(_filter_mask(plan.condition, batch, session))
        return out
    if isinstance(plan, Project):
        cols = [c for c in plan.columns if c in needed] or plan.columns
        return {
            b: batch.select([c for c in cols if c in batch.column_names])
            for b, batch in _exec_bucketed(
                plan.child, set(cols), session, bucket_cols, cache_scan
            ).items()
        }
    if isinstance(plan, Union):
        cols = [c for c in plan.output if c in needed] or plan.output[:1]
        read_cols = sorted(set(cols) | set(bucket_cols))
        left = {
            b: batch.select(read_cols)
            for b, batch in _exec_bucketed(
                plan.left, set(read_cols), session, bucket_cols, cache_scan
            ).items()
        }
        spec = _bucket_layout(plan.left)
        num_buckets = spec[0]
        appended = _exec(plan.right, set(read_cols), session).select(read_cols)
        if appended.num_rows:
            reps = appended.key_reps(list(bucket_cols))
            bids = bucket_ids_np(reps, num_buckets)
            for b in np.unique(bids):
                part = appended.filter(bids == b)
                key = int(b)
                if key in left:
                    left[key] = ColumnarBatch.concat([left[key], part])
                else:
                    left[key] = part
        return left
    raise HyperspaceException(
        f"Node not supported in bucketed execution: {type(plan).__name__}"
    )


def _bucket_stream(plan: LogicalPlan, needed: Set[str], session, bucket_cols):
    """Ordered ``[(bucket, fetch)]`` pairs for a clean linear subtree —
    the pipelined twin of :func:`_exec_bucketed` (docs/serve-pipeline.md).

    Per-bucket parquet reads are submitted to the shared scan pool
    (``io/scan.scan_pool``) up front; each ``fetch()`` blocks until its
    bucket's decoded batch is ready, so the consumer
    (``prepare_join_side_pipelined``) overlaps bucket *i*'s prepare with
    the reads of buckets *i+1…*. On the hybrid Union shape the
    appended-files delta prepare runs on the pool concurrently with the
    index-side reads (``_prepare_delta``). Batches are produced by the
    same select/concat calls as the sequential path, per bucket — the
    two paths are differential-tested bit-identical. Returns None when
    the shape/format does not support streaming (caller falls back)."""
    import time as _t

    from hyperspace_tpu.execution import join_exec as _je
    from hyperspace_tpu.io.parquet import bucket_id_of_file
    from hyperspace_tpu.io.scan import scan_pool

    if isinstance(plan, Scan):
        rel = plan.relation
        groups: dict = {}
        for f in rel.files:
            b = bucket_id_of_file(f)
            groups.setdefault(b, []).append(f)
        streamable = (
            rel.fmt in ("parquet", "delta", "iceberg")
            and rel.excluded_file_ids is None
            and not rel.file_partition_values
            and len(rel.files) > 1
            and None not in groups
        )
        if not streamable:
            return None
        cols = [c for c in rel.column_names if c in needed] or (
            rel.column_names[:1]
        )
        fmt = rel.fmt

        def read_bucket(files):
            # pure Arrow read in the worker — the C++ readers run on
            # Arrow's own pool and release the GIL, so N in-flight reads
            # genuinely overlap; the (GIL-bound) SoA decode happens on
            # the consumer thread as the first step of that bucket's
            # prepare, not here where it would serialize the workers
            t0 = _t.perf_counter()
            table = pio.read_table(files, cols, fmt)
            _je._stage_add("scan", t0)
            return table

        def decode(fut):
            def run():
                table = fut.result()
                t0 = _t.perf_counter()
                batch = ColumnarBatch.from_arrow(table)
                _je._stage_add("prepare", t0)
                return batch

            return run

        pool = scan_pool()
        # scan-pool workers record the "scan" stage span; carry the
        # query's trace context across the pool boundary (no-op obs-off)
        read_traced = _obs_trace.carry(read_bucket)
        return [
            (b, decode(pool.submit(read_traced, list(groups[b]))))
            for b in sorted(groups)
        ]
    if isinstance(plan, Project):
        cols = [c for c in plan.columns if c in needed] or plan.columns
        child = _bucket_stream(plan.child, set(cols), session, bucket_cols)
        if child is None:
            return None

        def project(fetch, cols=cols):
            def run():
                batch = fetch()
                return batch.select(
                    [c for c in cols if c in batch.column_names]
                )

            return run

        return [(b, project(fetch)) for b, fetch in child]
    if isinstance(plan, Union):
        cols = [c for c in plan.output if c in needed] or plan.output[:1]
        read_cols = sorted(set(cols) | set(bucket_cols))
        spec = _bucket_layout(plan.left)
        if spec is None:
            return None
        # the delta prepare is submitted FIRST so it takes a pool worker
        # immediately and runs concurrently with the index-side bucket
        # reads queued right after — off the serve critical path; with
        # the serve cache on, repeat queries skip it entirely
        # (fingerprint-keyed ("delta", …) entry)
        delta_fut = scan_pool().submit(
            _obs_trace.carry(_prepare_delta), plan.right, read_cols, session,
            bucket_cols, spec[0],
        )
        left = _bucket_stream(
            plan.left, set(read_cols), session, bucket_cols
        )
        if left is None:
            # rare fallback (e.g. single-file index side): surface any
            # delta read error here — the sequential path would hit the
            # same files — and let its cache entry warm the retry
            delta_fut.result()
            return None

        def select_left(fetch, read_cols=read_cols):
            return lambda: fetch().select(read_cols)

        left_map = {b: select_left(fetch) for b, fetch in left}

        def merged(b):
            def run():
                parts = delta_fut.result()
                part = parts.get(b)
                if b not in left_map:
                    return part
                batch = left_map[b]()
                if part is None:
                    return batch
                return ColumnarBatch.concat([batch, part])

            return run

        # Delta-only buckets (appended keys hashing into buckets the
        # index side has no file for) interleave by bucket id, exactly
        # like the sequential Union branch's dict after sorting. When the
        # index side already covers EVERY bucket of the layout — the
        # normal covering-index state — the delta cannot create new
        # buckets, so the bucket list is known without blocking on the
        # delta future and per-bucket prepare starts immediately (each
        # merged fetch blocks on the delta only when its own bucket
        # prepares). Only an index with empty buckets pays the upfront
        # wait for the delta's bucket set.
        if len(left_map) == spec[0]:
            all_buckets = sorted(left_map)
        else:
            all_buckets = sorted(
                set(left_map) | set(delta_fut.result().keys())
            )
        return [(b, merged(b)) for b in all_buckets]
    return None


def _prepare_delta(
    plan: LogicalPlan, read_cols, session, bucket_cols, num_buckets: int
):
    """Per-bucket parts of the hybrid-scan appended-files delta: read the
    appended source rows, hash them into the index's bucket layout, and
    split — the execution-time equivalent of the reference's on-the-fly
    shuffle of appended data, hoisted off the serve critical path.

    With serve-server mode on, the result is cached keyed by the delta
    FILE FINGERPRINT (+ columns, bucket columns, bucket count): appended
    source files are immutable once written, a further append changes
    the file set and therefore the key, so repeated hybrid queries pay
    only the per-bucket merge."""
    import time as _t

    from hyperspace_tpu.execution import join_exec as _je

    t0 = _t.perf_counter()
    cache = _serve_cache(session)
    key = None
    if cache is not None:
        node = plan
        while isinstance(node, Project):
            node = node.child
        if (
            isinstance(node, Scan)
            and node.relation.excluded_file_ids is None
            and not node.relation.file_partition_values
            and node.relation.files
        ):
            from hyperspace_tpu.execution.serve_cache import file_fingerprint

            fp = file_fingerprint(node.relation.files)
            if fp is not None:
                key = (
                    "delta",
                    fp,
                    tuple(read_cols),
                    tuple(bucket_cols),
                    num_buckets,
                )
                hit = cache.get(key)
                if hit is not None:
                    return hit
    appended = _exec(plan, set(read_cols), session).select(read_cols)
    parts = {}
    if appended.num_rows:
        from hyperspace_tpu.ops.hash import bucket_ids_np

        reps = appended.key_reps(list(bucket_cols))
        bids = bucket_ids_np(reps, num_buckets)
        for b in np.unique(bids):
            parts[int(b)] = appended.filter(bids == b)
    if key is not None:
        from hyperspace_tpu.execution.serve_cache import batch_nbytes

        cache.put(
            key, dict(parts), sum(batch_nbytes(p) for p in parts.values())
        )
    _je._stage_add("delta", t0)
    return parts


def _filter_mask(
    cond: E.Expr, batch: ColumnarBatch, session=None
) -> np.ndarray:
    from hyperspace_tpu import constants as C

    min_rows = (
        session.conf.device_filter_min_rows
        if session is not None
        else C.EXECUTION_DEVICE_FILTER_MIN_ROWS_DEFAULT
    )
    if batch.num_rows < min_rows:
        # host-resident batch below the device threshold: numpy beats the
        # host->device->host round trip (see constants.py rationale).
        # A pure range/Eq conjunction takes the fused single-pass mask
        # (native hs_range_mask / numpy twin, ops/filter.py) instead of
        # the per-conjunct interpreter chain — bit-identical output,
        # gated with the rest of the range serve plane.
        if _rangeprune_on(session):
            from hyperspace_tpu.ops.filter import fused_range_mask

            fused = fused_range_mask(cond, batch)
            if fused is not None:
                return fused
        return E.filter_mask(cond, batch)
    try:
        return device_filter_mask(cond, batch)
    except Unsupported:
        return E.filter_mask(cond, batch)


def _exec_scan(
    plan: Scan, needed: Set[str], session, pushdown=None
) -> ColumnarBatch:
    rel = plan.relation
    cols = [c for c in rel.column_names if c in needed] or rel.column_names[:1]
    read_cols = list(cols)
    # Hybrid-Scan delete compensation: the lineage column must be read to
    # apply the NOT-IN filter (CoveringIndexRuleUtils.scala:244-253), even
    # if the query does not project it.
    from hyperspace_tpu.constants import DATA_FILE_NAME_ID

    if rel.excluded_file_ids is not None and DATA_FILE_NAME_ID not in read_cols:
        read_cols.append(DATA_FILE_NAME_ID)
    if not rel.files:
        import pyarrow as pa

        empty = pa.table(
            {c: pa.array([], type=rel.schema[c]) for c in cols}
        )
        return ColumnarBatch.from_arrow(empty)
    if rel.file_row_groups is not None:
        # zone-map row-group narrowing (executor._range_pruned_scan):
        # read only the surviving row groups; the residual mask the
        # caller applies makes over-reading harmless and under-reading
        # impossible (superset contract, indexes/zonemaps.py). Pyarrow
        # pushdown filters don't compose with explicit row-group reads —
        # the narrowing already did the row-group half of their job.
        table = pio.read_table_row_groups(
            list(rel.files), list(rel.file_row_groups), read_cols, rel.fmt
        )
    else:
        table = pio.read_table(
            list(rel.files), read_cols, rel.fmt, filters=pushdown,
            memory_map=_io_mmap_on(session),
        )
    batch = ColumnarBatch.from_arrow(table)
    if rel.excluded_file_ids is not None:
        lineage = batch.column(DATA_FILE_NAME_ID).values
        mask = ~np.isin(lineage, np.array(rel.excluded_file_ids, dtype=np.int64))
        batch = batch.filter(mask)
    return batch.select(cols)
