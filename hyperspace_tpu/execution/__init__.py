"""Physical execution: host-orchestrated, device-computed.

The reference's physical layer is Spark's (``FileSourceScanExec``, SMJ,
plus its own ``BucketUnionExec``, ``execution/BucketUnionExec.scala``).
Here the host walks the logical plan, streams Arrow batches, and calls the
XLA kernels in :mod:`hyperspace_tpu.ops` for predicates, joins and sorts.
"""

from hyperspace_tpu.execution.executor import execute

__all__ = ["execute"]
