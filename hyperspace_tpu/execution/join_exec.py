"""Equi-join execution over key reps.

Generic (un-indexed) joins sort-merge on int64 key reps
(``io/columnar.py``); indexed joins reuse the same matcher per co-bucketed
shard pair without any shuffle — the payoff the reference gets from
bucketed indexes + SMJ (``covering/JoinIndexRule.scala:619-634``).

Matching combines each row's keys into one int64 (identity for a single
key, splitmix64 mix for composites), argsorts the right side once, and
binary-searches from the left; pairs are expanded per match range
arithmetically (vectorized, no Python loop). Single-key matching is
rep-exact; composite combines can collide, so multi-key joins re-verify
the numeric key columns, and string key columns are always re-verified
via dictionary remapping (murmur3-64 rep collisions), both O(matches).

The co-bucketed path is split into *prepare* (concat buckets, key reps,
combine, per-bucket sortedness — all query-independent) and *serve*
(match + expand + verify + assemble). The prepared side is exactly what
the serve cache (``execution/serve_cache.py``) retains between queries,
so a warm serve pays only the per-query match work.

Pipelined serve (round 7): on the uncached path the executor streams
per-bucket batches through :func:`prepare_join_side_pipelined` while
later buckets are still being read (``docs/serve-pipeline.md``), the
per-bucket match/expand runs on a thread pool, and the stage timings
accumulate in :data:`last_serve_breakdown` (same shape as the build's
``last_build_breakdown``) so regressions are attributable to a stage.
"""

from __future__ import annotations

import dataclasses
import threading as _threading
import time as _time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.obs import metrics as _obs_metrics
from hyperspace_tpu.obs import trace as _obs_trace

_SENTINEL_BASE = np.int64(-0x4000000000000000)

# At or above this combined per-bucket row count the host match uses the
# native linear merge-join (hyperspace_tpu/native). Below it numpy's
# searchsorted overhead is already microseconds and a first native call
# would pay the one-time g++ compile for nothing.
_NATIVE_JOIN_MIN_ROWS = 1 << 14

# At or above this combined row count the per-bucket match loop runs on
# a thread pool (the native count/emit/sort calls release the GIL);
# below it thread spawn overhead exceeds the whole match.
_PAR_MATCH_MIN_ROWS = 1 << 20

# Per-serve stage timing (seconds), reset by the executor at the start
# of each co-bucketed join and read by bench.py — the serve analogue of
# ``indexes/covering_build.last_build_breakdown``. Stages overlap under
# the pipelined serve (scan of bucket i+1 runs while bucket i prepares;
# per-bucket match fans out over threads), so stage values are BUSY time
# and may sum past wall time; the overlapped excess is the pipeline win.
# Diagnostic scope: PROCESS-GLOBAL and last-writer-wins, like the build
# breakdown — meaningful for one join at a time (bench, diagnosis);
# concurrent queries in a serve process interleave their timings here
# (results are unaffected; only this attribution blurs).
#
# Since the obs plane (docs/observability.md) this dict is the backing
# storage of a REGISTERED instrument: ``registry.stage_timer`` below
# adopts the exact dict + lock (one storage — the registry's Prometheus
# snapshot and every legacy reader see the same object; SHARED_STATE
# unchanged), and ``_stage_add`` ALSO records a stage span on the
# current trace, so a query's span timings and this breakdown are the
# same measurement by construction.
last_serve_breakdown: Dict[str, float] = {}
_serve_bd_lock = _threading.Lock()
_obs_metrics.registry.stage_timer(
    "hs_serve_stage_seconds",
    "serve stage busy seconds (breakdown view)",
    data=last_serve_breakdown,
    lock=_serve_bd_lock,
)


def serve_breakdown_reset() -> None:
    with _serve_bd_lock:
        last_serve_breakdown.clear()


def _stage_add(stage: str, t0: float) -> None:
    dt = _time.perf_counter() - t0
    with _serve_bd_lock:
        last_serve_breakdown[stage] = (
            last_serve_breakdown.get(stage, 0.0) + dt
        )
    _obs_trace.stage(stage, t0)


def _match_workers(n_tasks: int, total_rows: int) -> int:
    """Thread count for the match fan-out (1 = stay inline). The task
    unit is a bucket on a 1-shard serve, a whole shard's bucket range on
    a sharded serve."""
    if total_rows < _PAR_MATCH_MIN_ROWS or n_tasks <= 1:
        return 1
    from hyperspace_tpu import native

    return max(1, min(n_tasks, native._cores(), 8))


def _shard_tasks(buckets: Tuple[int, ...], num_shards: int) -> List[List[int]]:
    """Bucket POSITIONS grouped into match/prepare task units. With
    ``num_shards > 1`` each unit is one mesh shard's bucket range
    (``bucket % num_shards`` — the build's ownership layout, shared via
    ``parallel/mesh.bucket_owner_groups``), mirroring a device serving
    only its own buckets; otherwise one unit per bucket. Large shard
    ranges split within a shard so a small mesh never caps the thread
    fan-out below the core budget. Grouping only changes scheduling:
    results are always collected per bucket position and unioned in
    position order, so the output is identical for every grouping."""
    if num_shards <= 1:
        return [[i] for i in range(len(buckets))]
    from hyperspace_tpu import native
    from hyperspace_tpu.parallel.mesh import bucket_owner_groups

    return bucket_owner_groups(
        buckets, num_shards, min_tasks=max(1, min(native._cores(), 8))
    )


def _stable_argsort_i64(a: np.ndarray, n_threads: Optional[int] = None):
    """``np.argsort(a, kind="stable")`` for int64 keys, dispatching to the
    native threaded radix lexsort above its calibrated crossover —
    bit-identical output (signed int64 order == lexicographic order of
    the sign-flipped hi / lo uint32 planes; both engines are stable).
    Host-only by construction: never touches the device, so per-bucket
    serve sorts can fan out across threads (the native call releases the
    GIL; numpy's argsort does not)."""
    from hyperspace_tpu.ops import sort as sort_mod

    if len(a) >= sort_mod._native_sort_min_rows():
        from hyperspace_tpu import native

        perm = native.lexsort_u32(
            sort_mod._order_words_np(a[None, :]), n_threads=n_threads
        )
        if perm is not None:
            return perm
    return np.argsort(a, kind="stable")


def merge_join_indices(
    l_reps: np.ndarray, r_reps: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """[k, n] and [k, m] int64 reps -> (left_idx, right_idx) of matching
    pairs, ordered by left row.

    Matches on the COMBINED per-row key (identity for k == 1, splitmix64
    mix for k > 1): one argsort of the right side + binary search from the
    left — measured several times faster than the previous
    ``np.unique(axis=0)`` void-view grouping at millions of rows. For
    k > 1 the combine can collide, so pairs are superset-exact and the
    caller MUST re-verify key columns (``inner_join`` does)."""
    from hyperspace_tpu.ops.join import combine_reps_np, expand_match_ranges

    n, m = l_reps.shape[1], r_reps.shape[1]
    if n == 0 or m == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    l1 = combine_reps_np(l_reps)
    r1 = combine_reps_np(r_reps)
    order_r = _stable_argsort_i64(r1)
    rs = r1[order_r]
    lo = np.searchsorted(rs, l1, side="left")
    hi = np.searchsorted(rs, l1, side="right")
    # native single-pass expansion (numpy repeat/cumsum twin below the
    # calibrated crossover); order_r composes the right-side argsort
    # indirection into the same pass
    return expand_match_ranges(lo, hi - lo, r_map=order_r)


def _verify_keys(
    left: ColumnarBatch,
    right: ColumnarBatch,
    on: List[Tuple[str, str]],
    li: np.ndarray,
    ri: np.ndarray,
    l_reps: np.ndarray = None,
    r_reps: np.ndarray = None,
    verify_numeric: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact re-verification of every key column at the matched pairs:
    string columns via dictionary remap (murmur collision guard), numeric
    columns via rep equality (combine-hash / null-sentinel collision
    guard). ``l_reps``/``r_reps`` are the per-side [k, n] rep matrices
    when the caller already computed them; ``verify_numeric=False`` skips
    the numeric check for callers whose matching was already rep-exact."""
    from hyperspace_tpu.io.columnar import _gather

    keep = np.ones(len(li), dtype=bool)
    for j, (lname, rname) in enumerate(on):
        lc, rc = left.column(lname), right.column(rname)
        if lc.kind == "string" and rc.kind == "string":
            from hyperspace_tpu.io.columnar import remap_codes

            rcodes = remap_codes(lc.dictionary, rc)
            keep &= lc.codes[li] == rcodes[ri]
        elif verify_numeric:
            lr = l_reps[j] if l_reps is not None else lc.key_rep()
            rr = r_reps[j] if r_reps is not None else rc.key_rep()
            keep &= _gather(lr, li) == _gather(rr, ri)
    if keep.all():
        return li, ri
    return li[keep], ri[keep]


def _assemble(
    left: ColumnarBatch,
    right: ColumnarBatch,
    li: np.ndarray,
    ri: np.ndarray,
) -> ColumnarBatch:
    """Join output contract: left columns then right columns at the pairs."""
    out = {}
    for name, col in left.columns.items():
        out[name] = col.take(li)
    for name, col in right.columns.items():
        out[name] = col.take(ri)
    return ColumnarBatch(out)


@dataclasses.dataclass
class PreparedJoinSide:
    """Query-independent serve state of one co-bucketed join side.

    Everything here is derived from the per-bucket batches alone: bucket
    order, concatenated batch, per-bucket sizes/offsets, [k, n] key reps,
    the combined int64 key, the null-key mask, and whether every bucket's
    combined keys are already monotonic (true for clean single-version
    covering-index scans, whose bucket files are key-sorted on disk).
    The serve cache stores these keyed by the immutable index file set."""

    buckets: Tuple[int, ...]
    batch: ColumnarBatch
    sizes: np.ndarray  # [B] int64
    offs: np.ndarray  # [B] int64
    reps: np.ndarray  # [k, n] int64
    combined: np.ndarray  # [n] int64 (no null sentinels applied)
    nulls: Optional[np.ndarray]  # [n] bool, None when no null keys
    sorted_buckets: bool
    # Memoized per-bucket stable sort permutations of the SENTINELED
    # combined key, keyed by (bucket, sentinel parity). Query-independent
    # — the sentineled key is a pure function of (combined, nulls,
    # parity) — so a serve-cached unsorted side (hybrid tails) pays its
    # per-bucket argsorts once, not per query. Racing fills are benign
    # (identical values; dict assignment is atomic), the ScanCacheEntry
    # memo doctrine.
    sort_perms: Dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    @property
    def nbytes(self) -> int:
        from hyperspace_tpu.execution.serve_cache import batch_nbytes

        n = batch_nbytes(self.batch)
        n += self.reps.nbytes + self.combined.nbytes
        n += self.sizes.nbytes + self.offs.nbytes
        if self.nulls is not None:
            n += self.nulls.nbytes
        if not self.sorted_buckets or self.nulls is not None:
            # pre-charge the sort-perm memo at its worst case — BOTH
            # sentinel parities (a cached side can serve as left in one
            # query and right in another, e.g. a self-join), 8 bytes/row
            # each: sizes are fixed at put() time, so growth must be
            # charged up front or the byte cap stops bounding real memory.
            # A sorted side with null keys still fills the memo: the
            # sorted fast path requires nulls is None (see the serve
            # merge's l_sorted/r_sorted predicates), so sentinel
            # re-sorting falls back to bucket_sort_perm for it too.
            n += 2 * self.combined.nbytes
        return n

    def bucket_sort_perm(
        self,
        b: int,
        comb_slice: np.ndarray,
        parity: int,
        n_threads: Optional[int] = None,
    ) -> np.ndarray:
        """Stable argsort of one bucket's sentineled combined-key slice,
        memoized (see ``sort_perms``)."""
        key = (int(b), parity)
        perm = self.sort_perms.get(key)
        if perm is None:
            perm = _stable_argsort_i64(comb_slice, n_threads=n_threads)
            self.sort_perms[key] = perm
        return perm

    def subset(self, buckets: Tuple[int, ...]) -> "PreparedJoinSide":
        """Restrict to a bucket subset (sides with mismatched bucket sets,
        e.g. empty buckets on one side). Rebuilds contiguous arrays."""
        if buckets == self.buckets:
            return self
        pos = {b: i for i, b in enumerate(self.buckets)}
        idx_parts = []
        sizes = []
        for b in buckets:
            i = pos[b]
            o, s = int(self.offs[i]), int(self.sizes[i])
            idx_parts.append(np.arange(o, o + s, dtype=np.int64))
            sizes.append(s)
        idx = (
            np.concatenate(idx_parts)
            if idx_parts
            else np.zeros(0, dtype=np.int64)
        )
        sizes_a = np.array(sizes, dtype=np.int64)
        offs = np.concatenate([[0], np.cumsum(sizes_a)[:-1]]).astype(np.int64)
        nulls = None if self.nulls is None else self.nulls[idx]
        if nulls is not None and not nulls.any():
            nulls = None
        return PreparedJoinSide(
            buckets=tuple(buckets),
            batch=self.batch.take(idx),
            sizes=sizes_a,
            offs=offs,
            reps=self.reps[:, idx],
            combined=self.combined[idx],
            nulls=nulls,
            sorted_buckets=self.sorted_buckets,
        )


def prepare_join_side(
    bucket_batches: Dict[int, ColumnarBatch], key_cols: List[str]
) -> PreparedJoinSide:
    """Build the cacheable serve state from per-bucket batches."""
    from hyperspace_tpu.ops.join import combine_reps_np

    t0 = _time.perf_counter()
    buckets = tuple(sorted(bucket_batches))
    batch = ColumnarBatch.concat([bucket_batches[b] for b in buckets])
    sizes = np.array(
        [bucket_batches[b].num_rows for b in buckets], dtype=np.int64
    )
    offs = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int64)
    reps = batch.key_reps(key_cols)
    nulls_m = batch.null_any(key_cols)
    nulls = nulls_m if nulls_m.any() else None
    combined = combine_reps_np(reps)
    n = combined.shape[0]
    if n <= 1:
        sorted_buckets = True
    else:
        ge = combined[1:] >= combined[:-1]
        # bucket boundaries need not be ordered relative to each other;
        # offs[i] == 0 means every earlier bucket is empty (no boundary)
        # and offs[i] == n means this and all later buckets are empty
        # (boundary index n-1 would run past the length-(n-1) ge array)
        starts = offs[1:]
        cross_idx = starts[(starts > 0) & (starts < n)] - 1
        if len(cross_idx):
            ge = ge.copy()
            ge[cross_idx] = True
        sorted_buckets = bool(np.all(ge))
    _stage_add("prepare", t0)
    return PreparedJoinSide(
        buckets=buckets,
        batch=batch,
        sizes=sizes,
        offs=offs,
        reps=reps,
        combined=combined,
        nulls=nulls,
        sorted_buckets=sorted_buckets,
    )


def prepare_join_side_contiguous(
    batch: ColumnarBatch,
    wave_buckets: Tuple[int, ...],
    sizes,
    key_cols: List[str],
) -> Optional[PreparedJoinSide]:
    """Serve state from an ALREADY-CONTIGUOUS batch whose rows are ordered
    by ascending bucket (``sizes[i]`` rows belong to ``wave_buckets[i]``) — the
    streaming-wave twin of :func:`prepare_join_side`
    (docs/out-of-core.md). A wave's single decoded table IS the
    concatenation the materializing path would have built bucket by
    bucket, so the per-bucket concat copy disappears entirely and only
    the per-row passes remain: key reps, null mask, combine, and the same
    boundary-exempt sortedness test. Bit-identical to
    ``prepare_join_side`` over the equivalent per-bucket slices (reps/
    nulls/combined are per-row functions; the concat of slices of a
    contiguous batch is the batch). Returns None for an empty wave."""
    from hyperspace_tpu.ops.join import combine_reps_np

    if not wave_buckets:
        return None
    t0 = _time.perf_counter()
    sizes = np.asarray(sizes, dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int64)
    reps = batch.key_reps(key_cols)
    nulls_m = batch.null_any(key_cols)
    nulls = nulls_m if nulls_m.any() else None
    combined = combine_reps_np(reps)
    n = combined.shape[0]
    if n <= 1:
        sorted_buckets = True
    else:
        ge = combined[1:] >= combined[:-1]
        # same cross-bucket boundary exemption as prepare_join_side:
        # bucket boundaries need not be ordered relative to each other
        starts = offs[1:]
        cross_idx = starts[(starts > 0) & (starts < n)] - 1
        if len(cross_idx):
            ge = ge.copy()
            ge[cross_idx] = True
        sorted_buckets = bool(np.all(ge))
    _stage_add("prepare", t0)
    return PreparedJoinSide(
        buckets=tuple(wave_buckets),
        batch=batch,
        sizes=sizes,
        offs=offs,
        reps=reps,
        combined=combined,
        nulls=nulls,
        sorted_buckets=sorted_buckets,
    )


def prepare_join_side_pipelined(
    items: Iterable[Tuple[int, Callable[[], ColumnarBatch]]],
    key_cols: List[str],
    num_shards: int = 1,
) -> Optional[PreparedJoinSide]:
    """Streaming twin of :func:`prepare_join_side`: consumes
    ``(bucket, fetch)`` pairs in ascending bucket order, computing each
    bucket's serve state (key reps, combined key, null mask, sortedness)
    as soon as ``fetch()`` returns — while the executor's scan pool is
    still reading later buckets. Output is bit-identical to
    ``prepare_join_side`` over the same batches: reps/combined/nulls are
    per-row functions, so per-bucket computation concatenates to exactly
    the concat-then-compute result, and the global sortedness test
    ignores bucket boundaries in both formulations. Returns None for an
    empty stream (the executor's empty-side contract).

    ``num_shards > 1`` runs the prepare device-locally: one worker per
    mesh shard, each preparing only the buckets its shard owns
    (``bucket % num_shards``, the build's ownership layout), with the
    per-bucket states unioned back into ascending bucket order at the
    edge — the same rows in the same order either way."""
    from hyperspace_tpu.ops.join import combine_reps_np

    items = list(items)
    if not items:
        return None

    def prep_one(item):
        b, fetch = item
        batch = fetch()
        t0 = _time.perf_counter()
        reps = batch.key_reps(key_cols)
        nulls_m = batch.null_any(key_cols)
        combined = combine_reps_np(reps)
        sorted_b = len(combined) <= 1 or bool(
            np.all(combined[1:] >= combined[:-1])
        )
        _stage_add("prepare", t0)
        return b, batch, reps, nulls_m, combined, sorted_b

    # Per-bucket prepare fans out on its own small pool: each worker
    # blocks on that bucket's scan future (scan-pool tasks never wait on
    # other scan-pool futures — the deadlock discipline lives there),
    # then runs the reps/combine passes, whose numpy kernels release the
    # GIL on large arrays. Scaled to cores; 1 worker degenerates to the
    # plain in-order loop. On a sharded serve the unit of work is a
    # shard's whole bucket range instead of one bucket.
    from hyperspace_tpu import native

    if num_shards > 1 and len(items) > 1:
        from hyperspace_tpu.parallel.mesh import bucket_owner_groups

        # same ownership grouping as the match stage; the min_tasks
        # floor keeps a small mesh from capping prepare below the old
        # per-bucket pool's parallelism
        tasks = bucket_owner_groups(
            [it[0] for it in items],
            num_shards,
            min_tasks=max(1, min(4, native._cores() - 1)),
        )

        def prep_shard(group):
            return [prep_one(items[i]) for i in group]

        workers = min(len(tasks), max(1, native._cores() - 1))
        if workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="hs-shardprep"
            ) as pool:
                shard_rows = list(pool.map(_obs_trace.carry(prep_shard), tasks))
        else:
            shard_rows = [prep_shard(g) for g in tasks]
        # union at the edge: back to ascending bucket order (the items
        # order), exactly the single-tail concatenation
        rows = sorted(
            (r for sr in shard_rows for r in sr), key=lambda r: r[0]
        )
    else:
        workers = min(4, max(1, native._cores() - 1), len(items))
        if workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="hs-prep"
            ) as pool:
                rows = list(pool.map(_obs_trace.carry(prep_one), items))
        else:
            rows = [prep_one(x) for x in items]
    t0 = _time.perf_counter()
    batches = [r[1] for r in rows]
    sizes = np.array([b.num_rows for b in batches], dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int64)
    any_nulls = any(bool(r[3].any()) for r in rows)
    out = PreparedJoinSide(
        buckets=tuple(r[0] for r in rows),
        batch=ColumnarBatch.concat(batches),
        sizes=sizes,
        offs=offs,
        reps=np.concatenate([r[2] for r in rows], axis=1),
        combined=np.concatenate([r[4] for r in rows]),
        nulls=np.concatenate([r[3] for r in rows]) if any_nulls else None,
        sorted_buckets=all(r[5] for r in rows),
    )
    _stage_add("prepare", t0)
    return out


def _sentineled(prep: PreparedJoinSide, parity: int) -> np.ndarray:
    """Combined keys with null rows overwritten by unique sentinels so a
    null key can never match anything (SQL: null != null). Left uses even
    offsets and right odd, so the two sides' sentinels never collide with
    each other; a real key CAN equal a sentinel, which the caller guards
    by numeric re-verification."""
    if prep.nulls is None:
        return prep.combined
    combined = prep.combined.copy()
    bad = np.nonzero(prep.nulls)[0]
    combined[bad] = _SENTINEL_BASE - 2 * np.arange(len(bad)) - parity
    return combined


def _host_match_native_presorted(
    lp: PreparedJoinSide,
    rp: PreparedJoinSide,
    l_comb: np.ndarray,
    r_comb: np.ndarray,
    num_shards: int = 1,
):
    """All-buckets-presorted fast path: native count pass per bucket,
    then each bucket's pairs are emitted with its global row-offset bias
    straight into ONE preallocated (li, ri) — no per-bucket arrays, no
    offset-add passes, no final concatenate. Count and emit both fan out
    over a thread pool at serve scale (disjoint output slices; the
    native calls release the GIL); with ``num_shards > 1`` the fan-out
    unit is one shard's bucket range (each worker merges only the
    buckets its shard owns, the device-local serve layout) instead of
    one bucket. Returns None (caller falls back) when the native kernel
    is unavailable or a small workload wouldn't repay the per-call
    overhead."""
    from hyperspace_tpu import native

    total_rows = l_comb.shape[0] + r_comb.shape[0]
    if total_rows < _NATIVE_JOIN_MIN_ROWS or native.load(wait=False) is None:
        return None
    B = len(lp.sizes)
    spans = [
        (int(lp.sizes[b]), int(lp.offs[b]), int(rp.sizes[b]), int(rp.offs[b]))
        for b in range(B)
    ]
    tasks = _shard_tasks(lp.buckets, num_shards)

    def count_one(b):
        lsz, loff, rsz, roff = spans[b]
        if lsz == 0 or rsz == 0:
            return 0
        return native.merge_join_count_i64(
            l_comb[loff : loff + lsz], r_comb[roff : roff + rsz]
        )

    def count_group(group):
        return [(b, count_one(b)) for b in group]

    workers = _match_workers(len(tasks), total_rows)
    t0 = _time.perf_counter()
    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            grouped = list(pool.map(_obs_trace.carry(count_group), tasks))
    else:
        grouped = [count_group(g) for g in tasks]
    counts = [0] * B
    for pairs in grouped:
        for b, c in pairs:
            counts[b] = c
    if any(c is None for c in counts):
        return None
    _stage_add("match", t0)
    t0 = _time.perf_counter()
    total = sum(counts)
    li = np.empty(total, dtype=np.int64)
    ri = np.empty(total, dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    def emit_one(b):
        c = counts[b]
        if c == 0:
            return True
        lsz, loff, rsz, roff = spans[b]
        pos = int(offs[b])
        return native.merge_join_emit_into(
            l_comb[loff : loff + lsz],
            r_comb[roff : roff + rsz],
            li[pos : pos + c],
            ri[pos : pos + c],
            loff,
            roff,
        )

    def emit_group(group):
        return [emit_one(b) for b in group]

    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            oks = [ok for g in pool.map(_obs_trace.carry(emit_group), tasks) for ok in g]
    else:
        oks = [ok for g in tasks for ok in emit_group(g)]
    _stage_add("expand", t0)
    if not all(oks):
        return None
    return li, ri


def _host_match(
    lp: PreparedJoinSide,
    rp: PreparedJoinSide,
    l_comb: np.ndarray,
    r_comb: np.ndarray,
    num_shards: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-bucket host match on the UNPADDED slices -> global (li, ri).

    Sorted buckets binary-search directly; unsorted buckets (hybrid tails,
    multi-key combines, multi-version buckets) are stable-argsorted on
    host first — measured ~10x cheaper than the device sort+transfer
    round trip on one chip. No [B, W] padding is built at all (the
    padding only ever served the device kernel's static-shape contract).

    ``num_shards > 1`` makes the fan-out unit one mesh shard's bucket
    range (``bucket % num_shards``, the build's ownership layout) — each
    worker merges only the buckets its shard owns; the per-bucket pair
    arrays are then unioned in ascending bucket position, identical to
    the per-bucket scheduling."""
    l_sorted = lp.sorted_buckets and lp.nulls is None
    r_sorted = rp.sorted_buckets and rp.nulls is None
    if l_sorted and r_sorted:
        pair = _host_match_native_presorted(
            lp, rp, l_comb, r_comb, num_shards
        )
        if pair is not None:
            return pair
    from hyperspace_tpu.ops.join import expand_match_ranges

    B = len(lp.sizes)
    total_rows = l_comb.shape[0] + r_comb.shape[0]
    tasks = _shard_tasks(lp.buckets, num_shards)
    workers = _match_workers(len(tasks), total_rows)
    # when buckets fan out across threads, each per-bucket native sort
    # gets a slice of the core budget instead of claiming the machine
    sort_threads = None if workers == 1 else 1

    def match_bucket(b):
        lsz, loff = int(lp.sizes[b]), int(lp.offs[b])
        rsz, roff = int(rp.sizes[b]), int(rp.offs[b])
        if lsz == 0 or rsz == 0:
            return None
        t0 = _time.perf_counter()
        ls = l_comb[loff : loff + lsz]
        rs = r_comb[roff : roff + rsz]
        perm_l = perm_r = None
        if not l_sorted:
            perm_l = lp.bucket_sort_perm(b, ls, 0, n_threads=sort_threads)
            ls = ls[perm_l]
        if not r_sorted:
            perm_r = rp.bucket_sort_perm(b, rs, 1, n_threads=sort_threads)
            rs = rs[perm_r]
        pair = None
        if lsz + rsz >= _NATIVE_JOIN_MIN_ROWS:
            from hyperspace_tpu import native

            # both slices are sorted here, so the native linear merge
            # (O(n+m+pairs) sequential) replaces n binary searches into m
            # plus numpy's multi-pass pair expansion; identical output
            pair = native.merge_join_i64(ls, rs)
        if pair is not None:
            li_sorted, ri_sorted = pair
            _stage_add("match", t0)
            if len(li_sorted) == 0:
                return None
            li = perm_l[li_sorted] if perm_l is not None else li_sorted
            ri = perm_r[ri_sorted] if perm_r is not None else ri_sorted
            return li + loff, ri + roff
        lo = np.searchsorted(rs, ls, side="left")
        hi = np.searchsorted(rs, ls, side="right")
        _stage_add("match", t0)
        t0 = _time.perf_counter()
        li, ri = expand_match_ranges(
            lo, hi - lo, l_map=perm_l, r_map=perm_r,
            l_bias=loff, r_bias=roff,
        )
        _stage_add("expand", t0)
        if len(li) == 0:
            return None
        return li, ri

    def match_group(group):
        return [(b, match_bucket(b)) for b in group]

    results: List = [None] * B
    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            grouped = list(pool.map(_obs_trace.carry(match_group), tasks))
    else:
        grouped = [match_group(g) for g in tasks]
    for pairs_g in grouped:
        for b, p in pairs_g:
            results[b] = p
    pairs = [p for p in results if p is not None]
    z = np.zeros(0, dtype=np.int64)
    if not pairs:
        return z, z
    return (
        np.concatenate([p[0] for p in pairs]),
        np.concatenate([p[1] for p in pairs]),
    )


def _device_match(
    lp: PreparedJoinSide,
    rp: PreparedJoinSide,
    l_comb: np.ndarray,
    r_comb: np.ndarray,
    mesh,
    device_min_rows: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad to the device kernel's static-shape contract, run the compiled
    sharded match (``ops/join.bucketed_match_ranges``), expand ranges on
    host -> global (li, ri)."""
    from hyperspace_tpu.ops import pad_len
    from hyperspace_tpu.ops.join import bucketed_match_ranges

    def padded(prep, comb):
        width = pad_len(int(prep.sizes.max()) if len(prep.sizes) else 1)
        B = len(prep.sizes)
        pad = np.full((B, width), np.int64(0x7FFFFFFFFFFFFFFF))
        rowmap = np.zeros((B, width), dtype=np.int64)
        for i in range(B):
            sz, off = int(prep.sizes[i]), int(prep.offs[i])
            pad[i, :sz] = comb[off : off + sz]
            rowmap[i, :sz] = np.arange(off, off + sz)
        return pad, rowmap

    l_pad, l_rowmap = padded(lp, l_comb)
    r_pad, r_rowmap = padded(rp, r_comb)
    l_len = lp.sizes.copy()
    r_len = rp.sizes.copy()
    # pad the bucket dimension so shard_map divides evenly
    if mesh is not None and mesh.devices.size > 1:
        D = mesh.devices.size
        B = l_pad.shape[0]
        extra = (-B) % D
        if extra:

            def grow(a, fill):
                pad = np.full((extra,) + a.shape[1:], fill, dtype=a.dtype)
                return np.concatenate([a, pad])

            l_pad = grow(l_pad, np.int64(0x7FFFFFFFFFFFFFFF))
            r_pad = grow(r_pad, np.int64(0x7FFFFFFFFFFFFFFF))
            l_len = grow(l_len, 0)
            r_len = grow(r_len, 0)
            l_rowmap = grow(l_rowmap, 0)
            r_rowmap = grow(r_rowmap, 0)
    t0 = _time.perf_counter()
    perm_l, perm_r, lo, cnt = bucketed_match_ranges(
        mesh, l_pad, l_len, r_pad, r_len, device_min_rows
    )
    _stage_add("match", t0)
    t0 = _time.perf_counter()
    from hyperspace_tpu.ops.join import expand_match_ranges

    li_parts, ri_parts = [], []
    for b in range(len(l_len)):
        total = int(cnt[b].sum())
        if total == 0:
            continue
        # compose the sorted-space permutation with the pad rowmap once
        # per bucket (O(width)), then expand ranges in a single pass:
        # li = l_map[i], ri = r_map[lo[i]+j] — identical to the former
        # repeat/cumsum chain plus two gather passes
        li, ri = expand_match_ranges(
            lo[b],
            cnt[b],
            l_map=l_rowmap[b][perm_l[b]],
            r_map=r_rowmap[b][perm_r[b]],
        )
        li_parts.append(li)
        ri_parts.append(ri)
    _stage_add("expand", t0)
    z = np.zeros(0, dtype=np.int64)
    if not li_parts:
        return z, z
    return np.concatenate(li_parts), np.concatenate(ri_parts)


def co_bucketed_join_prepared(
    lp: PreparedJoinSide,
    rp: PreparedJoinSide,
    on: List[Tuple[str, str]],
    mesh=None,
    device_min_rows: int = 0,
    num_shards: int = 1,
) -> Optional[ColumnarBatch]:
    """Shuffle-free join of two prepared co-bucketed sides.

    The TPU equivalent of the reference's executor-parallel SMJ over
    co-bucketed scans (``covering/JoinIndexRule.scala:619-634``): no
    exchange ever happens — each bucket pair is matched independently
    (host binary-search per bucket, or the compiled sharded device
    program on a >1-device mesh). ``num_shards > 1`` routes the host
    match through the device-local layout: one worker per mesh shard,
    each merging only its own bucket range, pair arrays unioned in
    bucket order at the edge (bit-identical output for every value).

    Returns the joined batch, or None when the sides share no bucket (the
    caller builds the schema-correct empty result).
    """
    common = tuple(sorted(set(lp.buckets) & set(rp.buckets)))
    if not common:
        return None
    lp = lp.subset(common)
    rp = rp.subset(common)
    l_comb = _sentineled(lp, 0)
    r_comb = _sentineled(rp, 1)
    both_sorted = (
        lp.sorted_buckets
        and rp.sorted_buckets
        and lp.nulls is None
        and rp.nulls is None
    )
    single_device = mesh is None or mesh.devices.size <= 1
    total = int(lp.sizes.sum() + rp.sizes.sum())
    force_device = (
        single_device and device_min_rows > 0 and total >= device_min_rows
    )
    # PRESORTED fast path: covering-index buckets are key-sorted on disk,
    # so single-key joins over clean index scans binary-search directly —
    # re-sorting per query was the single largest serve cost (measured
    # 3.5-5.5s of a ~6.5s 4M-row join before round 4). The host branch
    # also wins for unsorted sides on one device (argsort on host beats
    # the device round trip); a >1-device mesh shards the general path.
    if both_sorted or (single_device and not force_device):
        li, ri = _host_match(lp, rp, l_comb, r_comb, num_shards)
    else:
        li, ri = _device_match(lp, rp, l_comb, r_comb, mesh, device_min_rows)
    # Single-key matching on the raw combined reps is exact (identity
    # combine, no sentinels in play when no side has null keys): only the
    # string hash-collision guard is needed. Multi-key combines can
    # collide, and sentinels can equal real keys — both require the
    # numeric re-verification.
    sentinels_used = lp.nulls is not None or rp.nulls is not None
    verify_numeric = len(on) > 1 or sentinels_used
    t0 = _time.perf_counter()
    li, ri = _verify_keys(
        lp.batch, rp.batch, on, li, ri, lp.reps, rp.reps, verify_numeric
    )
    _stage_add("verify", t0)
    t0 = _time.perf_counter()
    out = _assemble(lp.batch, rp.batch, li, ri)
    _stage_add("assemble", t0)
    return out


def co_bucketed_join(
    lbs: dict,
    rbs: dict,
    on: List[Tuple[str, str]],
    mesh=None,
    device_min_rows: int = 0,
) -> Optional[ColumnarBatch]:
    """Prepare both sides then serve (see ``co_bucketed_join_prepared``).
    Entry point for callers without a serve cache."""
    if not lbs or not rbs:
        return None
    lp = prepare_join_side(lbs, [l for l, _ in on])
    rp = prepare_join_side(rbs, [r for _, r in on])
    return co_bucketed_join_prepared(lp, rp, on, mesh, device_min_rows)


def inner_join(
    left: ColumnarBatch, right: ColumnarBatch, on: List[Tuple[str, str]]
) -> ColumnarBatch:
    """Inner equi-join; output = left columns then right columns (join keys
    from both sides kept, as in the logical Join's output contract)."""
    l_reps = left.key_reps([l for l, _ in on])
    r_reps = right.key_reps([r for _, r in on])
    # Null keys never match (SQL semantics): reps encode null as an in-band
    # value which would match null-to-null (and could equal a real key), so
    # exclude null rows via the explicit masks.
    l_ok = ~left.null_any([l for l, _ in on])
    r_ok = ~right.null_any([r for _, r in on])
    l_map = np.nonzero(l_ok)[0]
    r_map = np.nonzero(r_ok)[0]
    li, ri = merge_join_indices(l_reps[:, l_ok], r_reps[:, r_ok])
    li, ri = l_map[li], r_map[ri]
    # k == 1 matching is rep-exact (identity combine): only the string
    # hash-collision guard is needed; k > 1 combines can collide, so the
    # numeric columns are re-verified too
    li, ri = _verify_keys(
        left, right, on, li, ri, l_reps, r_reps, verify_numeric=len(on) > 1
    )
    return _assemble(left, right, li, ri)
