"""Equi-join execution over key reps.

Generic (un-indexed) joins sort-merge on int64 key reps
(``io/columnar.py``); indexed joins reuse the same matcher per co-bucketed
shard pair without any shuffle — the payoff the reference gets from
bucketed indexes + SMJ (``covering/JoinIndexRule.scala:619-634``).

Matching combines each row's keys into one int64 (identity for a single
key, splitmix64 mix for composites), argsorts the right side once, and
binary-searches from the left; pairs are expanded per match range
arithmetically (vectorized, no Python loop). Single-key matching is
rep-exact; composite combines can collide, so multi-key joins re-verify
the numeric key columns, and string key columns are always re-verified
via dictionary remapping (murmur3-64 rep collisions), both O(matches).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import ColumnarBatch


def merge_join_indices(
    l_reps: np.ndarray, r_reps: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """[k, n] and [k, m] int64 reps -> (left_idx, right_idx) of matching
    pairs, ordered by left row.

    Matches on the COMBINED per-row key (identity for k == 1, splitmix64
    mix for k > 1): one argsort of the right side + binary search from the
    left — measured several times faster than the previous
    ``np.unique(axis=0)`` void-view grouping at millions of rows. For
    k > 1 the combine can collide, so pairs are superset-exact and the
    caller MUST re-verify key columns (``inner_join`` does)."""
    from hyperspace_tpu.ops.join import combine_reps_np

    n, m = l_reps.shape[1], r_reps.shape[1]
    if n == 0 or m == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    l1 = combine_reps_np(l_reps)
    r1 = combine_reps_np(r_reps)
    order_r = np.argsort(r1, kind="stable")
    rs = r1[order_r]
    lo = np.searchsorted(rs, l1, side="left")
    hi = np.searchsorted(rs, l1, side="right")
    cnt = hi - lo
    total = int(cnt.sum())
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    li = np.repeat(np.arange(n, dtype=np.int64), cnt)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, cnt)
    ri = order_r[np.repeat(lo, cnt) + within]
    return li, ri


def _verify_keys(
    left: ColumnarBatch,
    right: ColumnarBatch,
    on: List[Tuple[str, str]],
    li: np.ndarray,
    ri: np.ndarray,
    l_reps: np.ndarray = None,
    r_reps: np.ndarray = None,
    verify_numeric: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact re-verification of every key column at the matched pairs:
    string columns via dictionary remap (murmur collision guard), numeric
    columns via rep equality (combine-hash / null-sentinel collision
    guard). ``l_reps``/``r_reps`` are the per-side [k, n] rep matrices
    when the caller already computed them; ``verify_numeric=False`` skips
    the numeric check for callers whose matching was already rep-exact."""
    keep = np.ones(len(li), dtype=bool)
    for j, (lname, rname) in enumerate(on):
        lc, rc = left.column(lname), right.column(rname)
        if lc.kind == "string" and rc.kind == "string":
            from hyperspace_tpu.io.columnar import remap_codes

            rcodes = remap_codes(lc.dictionary, rc)
            keep &= lc.codes[li] == rcodes[ri]
        elif verify_numeric:
            lr = l_reps[j] if l_reps is not None else lc.key_rep()
            rr = r_reps[j] if r_reps is not None else rc.key_rep()
            keep &= lr[li] == rr[ri]
    if keep.all():
        return li, ri
    return li[keep], ri[keep]


def _assemble(
    left: ColumnarBatch,
    right: ColumnarBatch,
    li: np.ndarray,
    ri: np.ndarray,
) -> ColumnarBatch:
    """Join output contract: left columns then right columns at the pairs."""
    out = {}
    for name, col in left.columns.items():
        out[name] = col.take(li)
    for name, col in right.columns.items():
        out[name] = col.take(ri)
    return ColumnarBatch(out)


def co_bucketed_join(
    lbs: dict,
    rbs: dict,
    on: List[Tuple[str, str]],
    mesh=None,
    device_min_rows: int = 0,
) -> Optional[ColumnarBatch]:
    """Shuffle-free join of co-bucketed per-bucket batches.

    The matching work (argsort + binary-search ranges per bucket) runs as
    ONE compiled device program vmapped over buckets and sharded over the
    mesh (``ops/join.py``) — the TPU equivalent of the reference's
    executor-parallel SMJ over co-bucketed scans
    (``covering/JoinIndexRule.scala:619-634``). The host expands match
    ranges (O(matches)) and re-verifies keys exactly.

    Returns the joined batch, or None when the sides share no bucket (the
    caller builds the schema-correct empty result).
    """
    from hyperspace_tpu.ops.join import bucketed_match_ranges, combine_reps_np

    buckets = sorted(set(lbs) & set(rbs))
    z = np.zeros(0, dtype=np.int64)
    if not buckets:
        return None
    l_all = ColumnarBatch.concat([lbs[b] for b in buckets])
    r_all = ColumnarBatch.concat([rbs[b] for b in buckets])
    l_sizes = [lbs[b].num_rows for b in buckets]
    r_sizes = [rbs[b].num_rows for b in buckets]
    l_offs = np.concatenate([[0], np.cumsum(l_sizes)[:-1]]).astype(np.int64)
    r_offs = np.concatenate([[0], np.cumsum(r_sizes)[:-1]]).astype(np.int64)

    def side_arrays(batch, sizes, offs, cols, parity):
        reps = batch.key_reps(cols)  # kept for exact verification below
        ok = ~batch.null_any(cols)  # explicit masks, not the in-band rep
        combined = combine_reps_np(reps)
        # exclude null keys from matching (SQL: null never equals null):
        # give each null row a unique sentinel; left uses even offsets and
        # right odd, so the two sides' sentinels can never collide either
        bad = np.nonzero(~ok)[0]
        combined[bad] = (
            np.int64(-0x4000000000000000) - 2 * np.arange(len(bad)) - parity
        )
        from hyperspace_tpu.ops import pad_len

        # bucket width padded to a power of two (ops/__init__ shape policy:
        # the match kernel compiles once per 2x band of max-bucket size)
        width = pad_len(max(sizes) if sizes else 1)
        B = len(sizes)
        padded = np.full((B, width), np.int64(0x7FFFFFFFFFFFFFFF))
        rowmap = np.zeros((B, width), dtype=np.int64)
        for i, (sz, off) in enumerate(zip(sizes, offs)):
            padded[i, :sz] = combined[off : off + sz]
            rowmap[i, :sz] = np.arange(off, off + sz)
        return padded, np.array(sizes, dtype=np.int64), rowmap, reps

    l_pad, l_len, l_rowmap, l_reps = side_arrays(
        l_all, l_sizes, l_offs, [l for l, _ in on], 0
    )
    r_pad, r_len, r_rowmap, r_reps = side_arrays(
        r_all, r_sizes, r_offs, [r for _, r in on], 1
    )
    # PRESORTED fast path: covering-index buckets are key-sorted on disk,
    # so for single-key joins over clean index scans the combined keys
    # arrive already monotonic per bucket (pads are +max at the tail).
    # Re-sorting them on device per query is the single largest serve
    # cost (measured: 3.5-5.5s of a ~6.5s 4M-row join) — detect
    # monotonicity in O(n) and binary-search directly. Multi-key combines
    # (hash, not order-preserving), hybrid-appended tails, null sentinels
    # and multi-version buckets all fail the check and take the general
    # sort path; correctness never depends on the hint.
    from hyperspace_tpu.ops.join import presorted_match_ranges, rows_monotonic

    single_device = mesh is None or mesh.devices.size <= 1
    total = int(l_len.sum() + r_len.sum())
    force_device = (
        single_device and device_min_rows > 0 and total >= device_min_rows
    )
    sorted_l, sorted_r = rows_monotonic(l_pad), rows_monotonic(r_pad)
    if (sorted_l and sorted_r) or (single_device and not force_device):
        # the pow2 bucket-width padding only serves the device kernel's
        # compile cache; numpy has no static-shape constraint, so the
        # host branch trims back to the real max bucket width
        w_l = max(max(l_sizes) if l_sizes else 1, 1)
        w_r = max(max(r_sizes) if r_sizes else 1, 1)
        l_pad, l_rowmap = l_pad[:, :w_l], l_rowmap[:, :w_l]
        r_pad, r_rowmap = r_pad[:, :w_r], r_rowmap[:, :w_r]
        # Not-sorted sides (hybrid tails, multi-key combines, multi-version
        # buckets) are stable-argsorted on HOST first: measured ~10x
        # cheaper than the device sort+transfer round trip on one chip.
        # On a >1-device mesh the device path wins (sort parallelizes
        # across shards); deviceJoinMinRows > 0 forces it on one device.
        if sorted_l:
            perm_l = np.broadcast_to(
                np.arange(l_pad.shape[1]), l_pad.shape
            )
        else:
            perm_l = np.argsort(l_pad, axis=1, kind="stable")
            l_pad = np.take_along_axis(l_pad, perm_l, axis=1)
        if sorted_r:
            perm_r = np.broadcast_to(
                np.arange(r_pad.shape[1]), r_pad.shape
            )
        else:
            perm_r = np.argsort(r_pad, axis=1, kind="stable")
            r_pad = np.take_along_axis(r_pad, perm_r, axis=1)
        _pl, _pr, lo, cnt = presorted_match_ranges(l_pad, l_len, r_pad, r_len)
        return _expand_and_assemble(
            l_all, r_all, on, l_reps, r_reps,
            l_rowmap, r_rowmap, l_len, perm_l, perm_r, lo, cnt, z,
        )
    # pad the bucket dimension so shard_map divides evenly
    if mesh is not None and mesh.devices.size > 1:
        D = mesh.devices.size
        B = l_pad.shape[0]
        extra = (-B) % D
        if extra:
            def grow(a, fill):
                pad = np.full((extra,) + a.shape[1:], fill, dtype=a.dtype)
                return np.concatenate([a, pad])

            l_pad = grow(l_pad, np.int64(0x7FFFFFFFFFFFFFFF))
            r_pad = grow(r_pad, np.int64(0x7FFFFFFFFFFFFFFF))
            l_len = grow(l_len, 0)
            r_len = grow(r_len, 0)
            l_rowmap = grow(l_rowmap, 0)
            r_rowmap = grow(r_rowmap, 0)
    perm_l, perm_r, lo, cnt = bucketed_match_ranges(
        mesh, l_pad, l_len, r_pad, r_len, device_min_rows
    )
    return _expand_and_assemble(
        l_all, r_all, on, l_reps, r_reps,
        l_rowmap, r_rowmap, l_len, perm_l, perm_r, lo, cnt, z,
    )


def _expand_and_assemble(
    l_all, r_all, on, l_reps, r_reps,
    l_rowmap, r_rowmap, l_len, perm_l, perm_r, lo, cnt, z,
):
    """Expand per-bucket match ranges into row pairs (O(matches),
    vectorized), re-verify keys exactly, assemble the output batch —
    shared by the presorted fast path and the general device/host path."""
    li_parts, ri_parts = [], []
    for b in range(len(l_len)):
        total = int(cnt[b].sum())
        if total == 0:
            continue
        c = cnt[b]
        li_sorted = np.repeat(np.arange(len(c), dtype=np.int64), c)
        starts = np.concatenate([[0], np.cumsum(c)[:-1]])
        within = np.arange(total, dtype=np.int64) - np.repeat(starts, c)
        ri_sorted = lo[b][li_sorted] + within
        li_parts.append(l_rowmap[b][perm_l[b][li_sorted]])
        ri_parts.append(r_rowmap[b][perm_r[b][ri_sorted]])
    if not li_parts:
        return _assemble(l_all, r_all, z, z)
    li = np.concatenate(li_parts)
    ri = np.concatenate(ri_parts)
    # numeric verification guards combine-hash and null-sentinel
    # collisions (a real key value can equal another row's sentinel)
    li, ri = _verify_keys(l_all, r_all, on, li, ri, l_reps, r_reps)
    return _assemble(l_all, r_all, li, ri)


def inner_join(
    left: ColumnarBatch, right: ColumnarBatch, on: List[Tuple[str, str]]
) -> ColumnarBatch:
    """Inner equi-join; output = left columns then right columns (join keys
    from both sides kept, as in the logical Join's output contract)."""
    l_reps = left.key_reps([l for l, _ in on])
    r_reps = right.key_reps([r for _, r in on])
    # Null keys never match (SQL semantics): reps encode null as an in-band
    # value which would match null-to-null (and could equal a real key), so
    # exclude null rows via the explicit masks.
    l_ok = ~left.null_any([l for l, _ in on])
    r_ok = ~right.null_any([r for _, r in on])
    l_map = np.nonzero(l_ok)[0]
    r_map = np.nonzero(r_ok)[0]
    li, ri = merge_join_indices(l_reps[:, l_ok], r_reps[:, r_ok])
    li, ri = l_map[li], r_map[ri]
    # k == 1 matching is rep-exact (identity combine): only the string
    # hash-collision guard is needed; k > 1 combines can collide, so the
    # numeric columns are re-verified too
    li, ri = _verify_keys(
        left, right, on, li, ri, l_reps, r_reps, verify_numeric=len(on) > 1
    )
    return _assemble(left, right, li, ri)
