"""Equi-join execution over key reps.

Generic (un-indexed) joins sort-merge on int64 key reps
(``io/columnar.py``); indexed joins reuse the same matcher per co-bucketed
shard pair without any shuffle — the payoff the reference gets from
bucketed indexes + SMJ (``covering/JoinIndexRule.scala:619-634``).

Matching uses a grouped merge: both sides' composite keys are mapped to
dense group ids (``np.unique`` over the rep rows — exact, no collisions at
the rep level), then pairs are expanded per group arithmetically
(vectorized, no Python loop). Reps are exact for numeric keys; for string
keys two different strings could share a rep only on a murmur3-64
collision, so string key columns are re-verified via dictionary remapping
(O(unique), vectorized).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import ColumnarBatch


def merge_join_indices(
    l_reps: np.ndarray, r_reps: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """[k, n] and [k, m] int64 reps -> (left_idx, right_idx) of all matching
    pairs, ordered by left row."""
    n, m = l_reps.shape[1], r_reps.shape[1]
    if n == 0 or m == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    both = np.concatenate([l_reps.T, r_reps.T])
    _uniq, inv = np.unique(both, axis=0, return_inverse=True)
    inv = inv.ravel()
    gl, gr = inv[:n], inv[n:]
    num_groups = int(inv.max()) + 1
    order_r = np.argsort(gr, kind="stable")
    counts_r = np.bincount(gr, minlength=num_groups)
    offsets_r = np.concatenate([[0], np.cumsum(counts_r)[:-1]])
    cnt = counts_r[gl]
    total = int(cnt.sum())
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    li = np.repeat(np.arange(n, dtype=np.int64), cnt)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, cnt)
    ri = order_r[np.repeat(offsets_r[gl], cnt) + within]
    return li, ri


def _verify_string_keys(
    left: ColumnarBatch,
    right: ColumnarBatch,
    on: List[Tuple[str, str]],
    li: np.ndarray,
    ri: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Drop rep-collision false positives on string key columns."""
    keep = np.ones(len(li), dtype=bool)
    for lname, rname in on:
        lc, rc = left.column(lname), right.column(rname)
        if lc.kind != "string" or rc.kind != "string":
            continue
        from hyperspace_tpu.io.columnar import remap_codes

        rcodes = remap_codes(lc.dictionary, rc)
        keep &= lc.codes[li] == rcodes[ri]
    if keep.all():
        return li, ri
    return li[keep], ri[keep]


def inner_join(
    left: ColumnarBatch, right: ColumnarBatch, on: List[Tuple[str, str]]
) -> ColumnarBatch:
    """Inner equi-join; output = left columns then right columns (join keys
    from both sides kept, as in the logical Join's output contract)."""
    l_reps = left.key_reps([l for l, _ in on])
    r_reps = right.key_reps([r for _, r in on])
    # Null keys never match (SQL semantics): reps encode null as a sentinel
    # which would match null-to-null, so mask them out first.
    from hyperspace_tpu.io.columnar import NULL_KEY_REP

    l_ok = ~(l_reps == NULL_KEY_REP).any(axis=0)
    r_ok = ~(r_reps == NULL_KEY_REP).any(axis=0)
    l_map = np.nonzero(l_ok)[0]
    r_map = np.nonzero(r_ok)[0]
    li, ri = merge_join_indices(l_reps[:, l_ok], r_reps[:, r_ok])
    li, ri = l_map[li], r_map[ri]
    li, ri = _verify_string_keys(left, right, on, li, ri)
    out = {}
    for name, col in left.columns.items():
        out[name] = col.take(li)
    for name, col in right.columns.items():
        out[name] = col.take(ri)
    return ColumnarBatch(out)
