"""Serve-server mode: an in-memory cache of immutable index data.

The reference caches index *metadata* with a TTL
(``index/CachingIndexCollectionManager.scala:38-108``); the data itself is
re-read from the lake on every query because Spark executors are
stateless. A TPU serve process is not — host RAM (and HBM) can hold the
hot index buckets between queries, which converts the serve path from
parquet-read-bound to compute-bound. This module is that cache.

Correctness model: entries are keyed by a **fingerprint of the exact file
set** — (path, size, mtime_ns) per file. Index data files are immutable
once written (every refresh/optimize writes a new ``v__=N`` version
directory, ``metadata/data_manager.py``), so a stale entry's key simply
never matches again; no invalidation protocol is needed. Eviction is LRU
by byte size (``hyperspace.serve.cache.maxBytes``).

Opt-in via ``hyperspace.serve.cache.enabled`` (constants.py) — the cold
path behaves exactly as before. What gets cached (see
``execution/executor.py``):

* ``("scan", fp)`` — per-COLUMN decoded data of a clean index scan
  (columns accrue across projections) + lazily-computed sorted-segment
  state for the binary-search point-lookup fast path;
* ``("joinside", fps, cols, keys)`` — a ``PreparedJoinSide``
  (``execution/join_exec.py``): concat batch, key reps, combined keys,
  per-bucket offsets and sortedness. ``fps`` is a TUPLE of per-relation
  fingerprints: one for a clean index scan, two for the Hybrid-Scan
  append union (index files + appended source files), so a further
  append or refresh re-keys the entry;
* ``("bucketed", fp, cols)`` — per-bucket batches for hybrid-scan serves;
* ``("delta", fp, …)`` — the hybrid-scan appended-files compensation,
  pre-bucketed (``executor._prepare_delta``);
* ``("zonemap", fp)`` — assembled zone maps for range pruning
  (``indexes/zonemaps.py``);
* ``("fusedplan", fp, …)`` — compiled fused-pipeline lowerings
  (``execution/pipeline_compiler.FusedAggPlan``): the symbolic
  Filter→Aggregate lowering reused across serves of one index version;
* ``("aggstate", fp)`` — assembled aggregate-plane partials
  (``indexes/aggindex.AggData``): the decoded per-row-group partial-
  aggregate state the metadata lowering folds instead of reading rows
  (docs/agg-serve.md).
"""

from __future__ import annotations

import hashlib
import mmap as _mmap
import os
import struct
import threading
import weakref
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils


def file_fingerprint(files) -> Optional[Tuple]:
    """(path, size, mtime_ns) per file — the cache key component that makes
    stale entries unreachable. None when any file is missing (caller skips
    the cache and lets the normal read path raise its own error)."""
    out = []
    try:
        for f in files:
            st = os.stat(f)
            out.append((f, st.st_size, st.st_mtime_ns))
    except OSError:
        return None
    return tuple(out)


#: CPython small-object overhead charged per cached string (an empty
#: ``str`` is ~49 bytes resident)
_STR_OVERHEAD = 49

#: resident charge for a file-backed (memory-mapped) array or buffer:
#: the pages live in the kernel page cache and are reclaimable without
#: a write-back, so the governor charges only a bookkeeping token —
#: charging heap bytes would falsely exhaust the cache budget with
#: state the OS can drop for free (the mmap half of docs/out-of-core.md)
_MMAP_TOKEN_NBYTES = 64

#: registry of live memory-mapped regions (start address -> byte
#: length), fed by :func:`register_mapped_region` (spill restores,
#: ``io.parquet.open_mmap_table``). ``estimate_nbytes`` classifies a
#: buffer whose address falls inside a region as file-backed. Guarded
#: by ``_mmap_lock``; entries are removed by a weakref finalizer on the
#: mapping owner when the owner supports weakrefs.
_mmap_regions: Dict[int, int] = {}
_mmap_lock = threading.Lock()


def _unregister_mapped_region(address: int) -> None:
    with _mmap_lock:
        _mmap_regions.pop(address, None)


def register_mapped_region(address: int, length: int, owner=None) -> None:
    """Declare ``[address, address+length)`` as a file-backed mapping so
    the sizing primitive charges views into it as near-zero resident.
    ``owner`` (the mmap / pyarrow MemoryMappedFile keeping the mapping
    alive) gets a weakref finalizer that retires the entry when the
    mapping dies; owners that refuse weakrefs simply leave a stale
    entry, which is only ever consulted for addresses handed out by a
    live mapping."""
    if length <= 0:
        return
    with _mmap_lock:
        _mmap_regions[int(address)] = int(length)
    if owner is not None:
        try:
            weakref.finalize(owner, _unregister_mapped_region, int(address))
        except TypeError:
            pass


def _address_in_mapped_region(addr: int) -> bool:
    if not _mmap_regions:
        return False
    with _mmap_lock:
        for start, length in _mmap_regions.items():
            if start <= addr < start + length:
                return True
    return False


def _buffer_file_backed(base) -> bool:
    """Is this backing buffer (an ndarray ``base``) a file mapping? —
    direct mmap/memoryview-over-mmap detection plus the registered-
    region address check for pyarrow Buffers."""
    if isinstance(base, _mmap.mmap):
        return True
    if isinstance(base, memoryview):
        obj = base.obj
        if isinstance(obj, _mmap.mmap):
            return True
    addr = getattr(base, "address", None)  # pyarrow.Buffer
    if isinstance(addr, int):
        return _address_in_mapped_region(addr)
    return False


def _owned_nbytes(a: np.ndarray) -> int:
    """Resident bytes an ndarray actually pins. A zero-copy view (an
    arrow-buffer-backed decode, a slice of a larger cached array) keeps
    its WHOLE owner alive, so the owner's extent is what a byte governor
    must charge — ``a.nbytes`` alone reports the slice extent and
    undercounts exactly the pyarrow-backed entries. Walks the ``base``
    chain to the owning ndarray, then charges the backing buffer
    (``pyarrow.Buffer.size`` / ``memoryview.nbytes``) when it is larger
    still. File-backed arrays (``np.memmap``, views over an ``mmap``, a
    registered mapped region) charge only ``_MMAP_TOKEN_NBYTES`` — the
    kernel page cache owns those bytes, not the process heap."""
    owner = a
    if isinstance(owner, np.memmap):
        return _MMAP_TOKEN_NBYTES
    while isinstance(owner.base, np.ndarray):
        owner = owner.base
        if isinstance(owner, np.memmap):
            return _MMAP_TOKEN_NBYTES
    extent = max(int(a.nbytes), int(owner.nbytes))
    base = owner.base
    if base is None:
        if _mmap_regions:
            try:
                addr = owner.__array_interface__["data"][0]
            except (AttributeError, KeyError, TypeError):
                addr = None
            if isinstance(addr, int) and _address_in_mapped_region(addr):
                return _MMAP_TOKEN_NBYTES
        return extent
    if _buffer_file_backed(base):
        return _MMAP_TOKEN_NBYTES
    if _mmap_regions:
        try:
            addr = owner.__array_interface__["data"][0]
        except (AttributeError, KeyError, TypeError):
            addr = None
        if isinstance(addr, int) and _address_in_mapped_region(addr):
            return _MMAP_TOKEN_NBYTES
    for attr in ("size", "nbytes"):  # pyarrow.Buffer / memoryview
        n = getattr(base, attr, None)
        if isinstance(n, int) and n > extent:
            return n
    return extent


def _arrow_resident_nbytes(value) -> Optional[int]:
    """Resident bytes of a pyarrow container, charging buffers that live
    inside a registered memory-mapped region as tokens instead of heap
    bytes. None when the shape is not one we know how to walk (caller
    falls back to ``get_total_buffer_size``)."""
    try:
        if hasattr(value, "itercolumns"):  # Table
            chunks = [c for col in value.itercolumns() for c in col.chunks]
        elif hasattr(value, "chunks"):  # ChunkedArray
            chunks = list(value.chunks)
        elif hasattr(value, "buffers") and callable(value.buffers):
            chunks = [value]  # Array / RecordBatch-like
        else:
            return None
        seen = set()
        total = 0
        for ch in chunks:
            for buf in ch.buffers():
                if buf is None:
                    continue
                addr = buf.address
                if addr in seen:
                    continue
                seen.add(addr)
                if _address_in_mapped_region(addr):
                    total += _MMAP_TOKEN_NBYTES
                else:
                    total += buf.size
        return total
    except Exception:  # hslint: disable=HS402
        # any unexpected container shape degrades to the caller's
        # get_total_buffer_size fallback — sizing must never raise
        return None


def estimate_nbytes(value, _depth: int = 0) -> int:
    """Approximate resident bytes of an arbitrary cached value — THE
    sizing primitive shared by the cache governor (``batch_nbytes``,
    ``ScanCacheEntry.budget_nbytes``) and the residency witness
    (``testing/residency_witness.py``), so the runtime accounting and
    the HS10xx bound model measure with one ruler. View-aware: numpy
    views charge their owner's full extent (``_owned_nbytes``), pyarrow
    containers report their total buffer size, and composite values
    (Column / ColumnarBatch / dict / sequence) recurse."""
    if value is None:
        return 0
    if isinstance(value, np.ndarray):
        return _owned_nbytes(value)
    if isinstance(value, (bool, int, float)):
        return 28
    if isinstance(value, (str, bytes, bytearray)):
        return len(value) + _STR_OVERHEAD
    if isinstance(value, Column):
        total = 0
        for a in (value.values, value.codes, value.validity):
            if a is not None:
                total += _owned_nbytes(a)
        if value.dictionary:
            total += sum(len(s) + _STR_OVERHEAD for s in value.dictionary)
        return total
    if isinstance(value, ColumnarBatch):
        return sum(
            estimate_nbytes(c, _depth + 1) for c in value.columns.values()
        )
    gtbs = getattr(value, "get_total_buffer_size", None)
    if callable(gtbs):  # pyarrow Table / RecordBatch / (Chunked)Array
        if _mmap_regions:  # mapped buffers charge tokens, not heap bytes
            resident = _arrow_resident_nbytes(value)
            if resident is not None:
                return resident
        return int(gtbs())
    if type(value).__module__.partition(".")[0] == "pyarrow":
        n = getattr(value, "size", None)  # pyarrow.Buffer
        if isinstance(n, int):
            return n
    for attr in ("budget_nbytes", "nbytes"):
        n = getattr(value, attr, None)
        if isinstance(n, (int, float)):
            return int(n)
    if _depth >= 6:  # composite recursion guard; cached values are trees
        return 0
    if isinstance(value, dict):
        return 64 + sum(
            estimate_nbytes(k, _depth + 1) + estimate_nbytes(v, _depth + 1)
            for k, v in value.items()
        )
    if isinstance(value, (list, tuple, set, frozenset)):
        return 56 + sum(8 + estimate_nbytes(v, _depth + 1) for v in value)
    try:
        import sys

        return int(sys.getsizeof(value))
    except TypeError:
        return 0


def batch_nbytes(batch: ColumnarBatch) -> int:
    """Approximate resident bytes of a batch (arrays + dictionaries).
    Delegates to :func:`estimate_nbytes`, so view-backed columns charge
    the buffers they pin, not just their slice extent."""
    return estimate_nbytes(batch)


# -- spill tier wire format ---------------------------------------------------
# magic | u64 pickle_len | u64 nbuf | nbuf x (u64 offset, u64 length) |
# pickle bytes | 64-aligned out-of-band buffer segments. The pickle is
# protocol 5 with buffer_callback, so every contiguous numpy payload is
# written as a raw aligned segment the restore side can hand back to
# ``pickle.loads(buffers=...)`` as a memoryview slice of the mmap —
# restored arrays are zero-copy read-only views of the spill file, and
# the mmap-aware sizing above charges them as file-backed.
_SPILL_MAGIC = b"HSSP1\0"
_SPILL_ALIGN = 64
_SPILL_SUFFIX = ".spill"


def _spill_encode(value) -> bytes:
    import pickle

    bufs: list = []
    payload = pickle.dumps(value, protocol=5, buffer_callback=bufs.append)
    raws = [b.raw() for b in bufs]
    header_len = len(_SPILL_MAGIC) + 16 + 16 * len(raws)
    pos = header_len + len(payload)
    metas = []
    for mv in raws:
        off = (pos + _SPILL_ALIGN - 1) & ~(_SPILL_ALIGN - 1)
        metas.append((off, mv.nbytes))
        pos = off + mv.nbytes
    parts = [_SPILL_MAGIC, struct.pack("<QQ", len(payload), len(raws))]
    for off, length in metas:
        parts.append(struct.pack("<QQ", off, length))
    parts.append(payload)
    pos = header_len + len(payload)
    for (off, length), mv in zip(metas, raws):
        parts.append(b"\0" * (off - pos))
        parts.append(mv)
        pos = off + length
    return b"".join(parts)


def _spill_decode(path: str):
    """Restore a spilled value zero-copy: mmap the file, register the
    mapping as file-backed, and feed the out-of-band segments to
    ``pickle.loads`` as memoryview slices (the arrays keep the mapping
    alive through their base chain). Raises ``ValueError`` on a torn or
    foreign file — the caller reaps it and treats the key as a miss."""
    import pickle

    with open(path, "rb") as f:
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    view = memoryview(mm)
    total = len(view)
    hdr = len(_SPILL_MAGIC)
    if total < hdr + 16 or bytes(view[:hdr]) != _SPILL_MAGIC:
        raise ValueError("not a spill file: %s" % path)
    plen, nbuf = struct.unpack_from("<QQ", view, hdr)
    p = hdr + 16
    if total < p + 16 * nbuf + plen:
        raise ValueError("truncated spill file: %s" % path)
    metas = []
    for _ in range(nbuf):
        off, length = struct.unpack_from("<QQ", view, p)
        p += 16
        if off + length > total:
            raise ValueError("truncated spill file: %s" % path)
        metas.append((off, length))
    payload = view[p:p + plen]
    base_addr = np.frombuffer(mm, dtype=np.uint8).__array_interface__[
        "data"
    ][0]
    register_mapped_region(base_addr, total, owner=mm)
    buffers = [view[off:off + length] for off, length in metas]
    return pickle.loads(payload, buffers=buffers)


def _spill_filename(key) -> str:
    return hashlib.sha256(repr(key).encode()).hexdigest() + _SPILL_SUFFIX


#: entry kinds eligible for demotion to the spill tier: the decoded /
#: prepared data-plane state the ISSUE's out-of-core arc targets. The
#: metadata-ish kinds (zonemap/fusedplan/aggstate) stay evict-to-
#: oblivion — they are cheap to re-derive and may hold compiled
#: callables pickle cannot round-trip.
_SPILL_KINDS = frozenset(("scan", "bucketed", "joinside", "delta"))

#: every live ServeCache in this process — the spill reaper
#: (``metadata/recovery.reap_spill_orphans``) consults
#: :func:`live_spill_paths` so it never deletes a file a live cache
#: still indexes. Weak so a replaced cache (session reconfig) does not
#: pin its gigabytes.
_LIVE_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def live_spill_paths() -> set:
    """Spill file paths owned by live caches in this process — the
    reaper's do-not-delete set."""
    out: set = set()
    for cache in list(_LIVE_CACHES):
        out.update(cache.spill_paths())
    return out


def spill_root(conf) -> str:
    """``<hyperspace.system.path>/_hyperspace_spill`` — the lake-level
    spill tier directory (the bus/querylog sidecar-dir idiom)."""
    from hyperspace_tpu import constants as C

    system_path = conf.get_str(
        C.INDEX_SYSTEM_PATH, C.INDEX_SYSTEM_PATH_DEFAULT
    )
    return os.path.join(system_path, C.HYPERSPACE_SPILL_DIR)


class ServeCache:
    """Thread-safe LRU cache, byte-capped — the serve plane's memory
    governor. Values carry their own size (entries are (value, nbytes)
    internally).

    Lock discipline (audited for the concurrent serve frontend,
    ``serve/frontend.py``; covered by the two-thread race tests in
    ``tests/test_serve_cache.py``): ONE lock guards the entry map, the
    byte ledger and every counter, and every public method takes it for
    its whole critical section — so ``resident_bytes`` can never
    observe a half-applied put, an eviction can never interleave with a
    replace's pop/re-add, and ``evict_kind`` snapshots its victim list
    under the same lock that guards concurrent ``get``/``put``. No I/O
    and no user code runs under the lock (values are stored, never
    inspected), keeping it HS502-clean and O(1)-held. Values handed out
    by ``get`` may outlive their entry (a racing eviction drops the
    cache's reference, not the caller's) — safe because every cached
    value is immutable by the publication contracts documented above.

    The governor's accounting invariant — ``resident_bytes`` equals the
    exact sum of resident entry sizes and never exceeds ``max_bytes`` —
    is what the byte budget means under concurrency; the stress tests
    assert it while readers, writers and evictors race.
    """

    def __init__(
        self,
        max_bytes: int,
        spill_dir: Optional[str] = None,
        spill_max_bytes: int = 0,
    ):
        self.max_bytes = int(max_bytes)
        # on-disk demotion tier (docs/out-of-core.md): LRU-evicted
        # values of spillable kinds are pickled (protocol 5, out-of-band
        # buffers) to fsync'd files under spill_dir instead of being
        # dropped; a later miss restores them zero-copy via mmap. Off
        # when spill_dir is unset or the byte cap is 0.
        self.spill_dir = spill_dir
        self.spill_max_bytes = int(spill_max_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        # spill index: key -> (path, on-disk bytes), LRU by demotion
        # recency; guarded by the same one lock as the resident map so
        # a key is never simultaneously resident and spilled
        self._spill: OrderedDict = OrderedDict()
        self._spill_bytes = 0
        self.hits = 0
        self.misses = 0
        # resident-set telemetry (memory governor): high-water mark of
        # the byte ledger, cumulative LRU evictions, inserts dropped by
        # an armed cache_insert fault (testing/faults.py)
        self.high_water_bytes = 0
        self.evictions = 0
        self.evicted_bytes = 0
        self.insert_failures = 0
        # spill-tier telemetry: demotions written, restores served,
        # values dropped (unpicklable / oversized / torn file),
        # cumulative bytes written
        self.spill_demotes = 0
        self.spill_restores = 0
        self.spill_drops = 0
        self.spill_bytes_written = 0
        # live stats() view in the metrics registry (docs/observability.
        # md; last-registered instance wins, the process-global
        # telemetry doctrine) — weakly bound so the registry never
        # keeps a replaced cache (and its gigabytes) alive
        from hyperspace_tpu.obs import metrics as obs_metrics

        obs_metrics.registry.register_weak_view("serve_cache", self)
        _LIVE_CACHES.add(self)

    @property
    def spill_enabled(self) -> bool:
        return bool(self.spill_dir) and self.spill_max_bytes > 0

    def get(self, key):
        spilled = None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry[0]
            spilled = self._spill.pop(key, None)
            if spilled is None:
                self.misses += 1
                return None
            self._spill_bytes -= spilled[1]
        # restore OUTSIDE the lock (file I/O + unpickle): a torn or
        # vanished file degrades to a miss — the caller re-derives from
        # parquet, exactly as if the value had been evicted to oblivion
        value, nbytes = self._restore_from_spill(key, spilled[0])
        if value is None:
            with self._lock:
                self.misses += 1
            return None
        self.put(key, value, nbytes)
        with self._lock:
            self.spill_restores += 1
            self.hits += 1
        return value

    def peek(self, key):
        """Read without touching hit/miss counters or LRU order — for
        internal publication paths (re-reading the freshest entry before
        a merge-put must not skew the query-level statistics)."""
        with self._lock:
            entry = self._entries.get(key)
            return None if entry is None else entry[0]

    def put(self, key, value, nbytes: int) -> None:
        # fault-injection seam: a failing insert must never fail the
        # query — the value simply stays uncached (degrade-in-place),
        # counted so operators can see a sick cache backend. The detail
        # (the key's kind) is passed raw; it is stringified only when
        # the point is armed, like the parquet_read seam.
        if faults.degraded("cache_insert", key[:1] if key else ""):
            with self._lock:
                self.insert_failures += 1
            return
        if nbytes > self.max_bytes:
            return  # larger than the whole cache: not cacheable
        demote = []
        spill = self.spill_enabled
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            # evict BEFORE inserting: the ledger never overshoots the
            # budget even transiently, so an unsynchronized
            # ``resident_bytes`` probe (telemetry threads, the stress
            # tests' budget assertion) can never observe a value past
            # ``max_bytes``
            while self._bytes + nbytes > self.max_bytes and self._entries:
                vk, (vv, freed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1
                self.evicted_bytes += freed
                if (
                    spill
                    and isinstance(vk, tuple)
                    and vk
                    and vk[0] in _SPILL_KINDS
                ):
                    demote.append((vk, vv))
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            if self._bytes > self.high_water_bytes:
                self.high_water_bytes = self._bytes
        # demotions run OUTSIDE the lock (pickle + fsync'd write): the
        # victims are already out of the resident map, so a racing get
        # of a mid-demotion key simply misses and re-derives
        for vk, vv in demote:
            self._spill_demote(vk, vv)

    def _spill_demote(self, key, value) -> None:
        """Write one evicted value to the spill tier (called with NO
        cache lock held — pickling and the fsync'd atomic publish are
        I/O). Values that refuse to pickle or exceed the tier budget
        are dropped (counted); the tier itself is LRU by demotion
        recency, oldest files deleted when the byte cap overflows."""
        import time

        from hyperspace_tpu.obs import trace

        t0 = time.perf_counter()
        try:
            blob = _spill_encode(value)
        except Exception:  # hslint: disable=HS402
            # a value that refuses to pickle (compiled callables, exotic
            # buffers) is dropped to oblivion, counted — demotion is
            # best-effort and must never fail the query that evicted it
            with self._lock:
                self.spill_drops += 1
            return
        if len(blob) > self.spill_max_bytes:
            with self._lock:
                self.spill_drops += 1
            return
        path = os.path.join(self.spill_dir, _spill_filename(key))
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            # crash seam: dying here leaves at most a .tmp_spool_ temp
            # (atomic publish never exposes a torn final file) — the
            # recovery matrix (tests/test_crash_recovery.py) proves the
            # mid_spill_write wreckage is reaped and never served
            faults.crash("mid_spill_write", path)
            file_utils.atomic_overwrite_bytes(path, blob)
        except faults.SimulatedCrash:
            raise
        except OSError:
            with self._lock:
                self.spill_drops += 1
            return
        trace.stage("spill_write", t0=t0, attrs={"bytes": len(blob)})
        reap = []
        with self._lock:
            old = self._spill.pop(key, None)
            if old is not None:
                self._spill_bytes -= old[1]
            while (
                self._spill_bytes + len(blob) > self.spill_max_bytes
                and self._spill
            ):
                _, (opath, onbytes) = self._spill.popitem(last=False)
                self._spill_bytes -= onbytes
                reap.append(opath)
            self._spill[key] = (path, len(blob))
            self._spill_bytes += len(blob)
            self.spill_demotes += 1
            self.spill_bytes_written += len(blob)
        for p in reap:
            try:
                file_utils.delete(p)
            except OSError:
                pass

    def _restore_from_spill(self, key, path: str):
        """Restore one spilled value (NO cache lock held). Returns
        ``(value, resident_nbytes)`` or ``(None, 0)`` on a torn /
        vanished file (counted as a drop, wreckage deleted). The
        restored arrays are mmap views of the spill file, so the
        resident charge re-estimated here is near-zero — the pages
        belong to the kernel page cache. The file is unlinked after a
        successful restore; the live mapping keeps its pages readable
        (POSIX), and the disk space returns when the value is finally
        dropped."""
        import time

        from hyperspace_tpu.obs import trace

        t0 = time.perf_counter()
        try:
            value = _spill_decode(path)
        except Exception:  # hslint: disable=HS402
            # torn/foreign/vanished spill file degrades to a cache miss
            # (caller re-derives from parquet) — restore must never
            # surface a spill-tier defect as a query failure
            with self._lock:
                self.spill_drops += 1
            try:
                file_utils.delete(path)
            except OSError:
                pass
            return None, 0
        nbytes = estimate_nbytes(value)
        trace.stage("spill_restore", t0=t0, attrs={"resident_bytes": nbytes})
        try:
            file_utils.delete(path)
        except OSError:
            pass
        return value, nbytes

    def spill_paths(self) -> set:
        """Paths the spill index currently claims (one consistent
        snapshot) — consulted by the orphan reaper's do-not-delete set."""
        with self._lock:
            return {path for path, _ in self._spill.values()}

    def clear(self) -> None:
        """Empty the cache and start a fresh telemetry epoch: the
        high-water mark resets with the contents (cumulative counters —
        hits/misses/evictions — keep counting), so per-phase probes
        (bench rungs) report their own peak, not an earlier phase's.
        The spill tier empties too (files deleted outside the lock) —
        clear means clear."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.high_water_bytes = 0
            reap = [path for path, _ in self._spill.values()]
            self._spill.clear()
            self._spill_bytes = 0
        for p in reap:
            try:
                file_utils.delete(p)
            except OSError:
                pass

    def evict_kind(self, kind: str) -> int:
        """Drop every entry of one kind (keys are ``(kind, …)`` tuples:
        "scan" / "bucketed" / "joinside" / "delta" / "zonemap" /
        "fusedplan" / "aggstate"). Returns the number evicted. Operational tooling:
        lets a serve process (or bench) shed one class of state — e.g.
        keep the prepared hybrid delta but force joinside
        re-preparation, or drop compiled fused-pipeline plans after a
        config change — without a full clear. The victim list is built
        AND drained under the one cache lock, so a racing ``put`` of
        the same kind either lands before the snapshot (and is evicted)
        or after the drain (and survives) — never half-accounted."""
        with self._lock:
            victims = [
                k
                for k in self._entries
                if isinstance(k, tuple) and k and k[0] == kind
            ]
            for k in victims:
                _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes
            reap = []
            for k in [
                k
                for k in self._spill
                if isinstance(k, tuple) and k and k[0] == kind
            ]:
                path, nbytes = self._spill.pop(k)
                self._spill_bytes -= nbytes
                reap.append(path)
        for p in reap:
            try:
                file_utils.delete(p)
            except OSError:
                pass
        return len(victims)

    def evict_paths_under(self, root: str) -> int:
        """Drop every entry whose fingerprint names a file under
        ``root`` (an index directory). The fleet fanout's invalidation
        primitive (``serve/bus.py``): a refresh/optimize/vacuum in a
        PEER process re-keys or kills this index's entries — eviction
        frees the dead versions' bytes proactively instead of letting
        them age out of the LRU while fresher state fights for budget.
        Keys are tuples nesting fingerprint tuples of (path, size,
        mtime_ns) triples; the walk finds every string in the key, so
        every current and future key shape is covered. Victim list built
        and drained under the one cache lock, like ``evict_kind``."""
        prefix = root.replace("\\", "/").rstrip("/") + "/"

        def _mentions(obj) -> bool:
            if isinstance(obj, str):
                return obj.replace("\\", "/").startswith(prefix)
            if isinstance(obj, tuple):
                return any(_mentions(x) for x in obj)
            return False

        with self._lock:
            victims = [k for k in self._entries if _mentions(k)]
            for k in victims:
                _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes
            reap = []
            for k in [k for k in self._spill if _mentions(k)]:
                path, nbytes = self._spill.pop(k)
                self._spill_bytes -= nbytes
                reap.append(path)
        for p in reap:
            try:
                file_utils.delete(p)
            except OSError:
                pass
        return len(victims)

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def bytes_by_kind(self) -> dict:
        """Resident bytes per entry kind — the governor's breakdown
        telemetry (which class of state owns the budget)."""
        with self._lock:
            out: dict = {}
            for k, (_v, nbytes) in self._entries.items():
                kind = k[0] if isinstance(k, tuple) and k else "other"
                out[kind] = out.get(kind, 0) + nbytes
            return out

    def stats(self) -> dict:
        """One consistent snapshot of the governor's counters (taken
        under the lock, so bytes/entries/high-water agree).
        ``snapshot_at_ms`` stamps WHEN — merge several frontends'/
        processes' snapshots with ``obs.merge_snapshots``, never by
        hand."""
        import time as _t

        with self._lock:
            return {
                "snapshot_at_ms": int(_t.time() * 1000),
                "resident_bytes": self._bytes,
                "high_water_bytes": self.high_water_bytes,
                "max_bytes": self.max_bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "insert_failures": self.insert_failures,
                "spill_entries": len(self._spill),
                "spill_resident_bytes": self._spill_bytes,
                "spill_max_bytes": self.spill_max_bytes,
                "spill_demotes": self.spill_demotes,
                "spill_restores": self.spill_restores,
                "spill_drops": self.spill_drops,
                "spill_bytes": self.spill_bytes_written,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ScanCacheEntry:
    """Per-COLUMN cached decode of one index scan, with lazily-computed
    sorted-segment state.

    One entry per file set (key = ("scan", fp)); columns are added on
    demand as queries need them, so overlapping projections share one
    decoded copy per column instead of pinning a full batch per distinct
    column set. Index bucket files are key-sorted on disk; after an
    incremental refresh a bucket holds several files, each sorted but not
    globally merged — the entry keeps per-file segment boundaries and,
    per column, whether every segment is monotonic in key-rep order,
    detected from the data (never trusted from metadata), the same
    doctrine as the join's presorted fast path.

    Concurrency contract: a PUBLISHED entry (one that has been ``put``
    into the cache) is never structurally mutated — column additions go
    through :meth:`with_new_columns`, which builds a copy sharing the
    existing Column objects and is published by replacing the cache
    entry (racing writers waste a decode; readers never see a torn
    entry). ``column_state`` memoization is the one in-place write and
    is safe: racing threads compute identical values and dict assignment
    is atomic."""

    def __init__(self, segments):
        self.segments = tuple(segments)  # ((start, end), ...)
        self.columns: dict = {}  # name -> Column
        self._reps: dict = {}  # name -> (key_rep, all_segments_sorted)

    def with_new_columns(self, new_columns: dict) -> "ScanCacheEntry":
        """A copy of this entry with ``new_columns`` added (copy-on-write
        publication — see the concurrency contract above)."""
        out = ScanCacheEntry(self.segments)
        out.columns.update(self.columns)
        out.columns.update(new_columns)
        out._reps.update(self._reps)
        return out

    @property
    def num_rows(self) -> int:
        return self.segments[-1][1] if self.segments else 0

    def batch_for(self, cols) -> Optional[ColumnarBatch]:
        """A batch over ``cols``, or None when some column is not cached
        yet (caller reads the missing ones and publishes a copy via
        :meth:`with_new_columns`)."""
        if any(c not in self.columns for c in cols):
            return None
        return ColumnarBatch({c: self.columns[c] for c in cols})

    def column_state(self, name: str):
        """(key_rep, all_segments_sorted) for a column, memoized."""
        import numpy as np

        st = self._reps.get(name)
        if st is not None:
            return st
        rep = self.columns[name].key_rep()
        ok = True
        for s, e in self.segments:
            seg = rep[s:e]
            if len(seg) > 1 and not bool(np.all(seg[1:] >= seg[:-1])):
                ok = False
                break
        st = (rep, ok)
        self._reps[name] = st
        return st

    @property
    def budget_nbytes(self) -> int:
        """What the LRU accounting charges: every cached column PLUS its
        worst-case memoized key-rep (8 bytes/row, ``column_state``) —
        sizes are fixed at put() time, so growth must be pre-charged or
        the byte cap stops bounding real memory. Publishers re-put the
        ``with_new_columns`` copy with its new charge."""
        total = 0
        rows = self.num_rows
        for c in self.columns.values():
            total += estimate_nbytes(c)
            total += 8 * rows
        return total
