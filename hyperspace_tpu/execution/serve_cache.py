"""Serve-server mode: an in-memory cache of immutable index data.

The reference caches index *metadata* with a TTL
(``index/CachingIndexCollectionManager.scala:38-108``); the data itself is
re-read from the lake on every query because Spark executors are
stateless. A TPU serve process is not — host RAM (and HBM) can hold the
hot index buckets between queries, which converts the serve path from
parquet-read-bound to compute-bound. This module is that cache.

Correctness model: entries are keyed by a **fingerprint of the exact file
set** — (path, size, mtime_ns) per file. Index data files are immutable
once written (every refresh/optimize writes a new ``v__=N`` version
directory, ``metadata/data_manager.py``), so a stale entry's key simply
never matches again; no invalidation protocol is needed. Eviction is LRU
by byte size (``hyperspace.serve.cache.maxBytes``).

Opt-in via ``hyperspace.serve.cache.enabled`` (constants.py) — the cold
path behaves exactly as before. What gets cached (see
``execution/executor.py``):

* ``("scan", fp)`` — per-COLUMN decoded data of a clean index scan
  (columns accrue across projections) + lazily-computed sorted-segment
  state for the binary-search point-lookup fast path;
* ``("joinside", fps, cols, keys)`` — a ``PreparedJoinSide``
  (``execution/join_exec.py``): concat batch, key reps, combined keys,
  per-bucket offsets and sortedness. ``fps`` is a TUPLE of per-relation
  fingerprints: one for a clean index scan, two for the Hybrid-Scan
  append union (index files + appended source files), so a further
  append or refresh re-keys the entry;
* ``("bucketed", fp, cols)`` — per-bucket batches for hybrid-scan serves;
* ``("delta", fp, …)`` — the hybrid-scan appended-files compensation,
  pre-bucketed (``executor._prepare_delta``);
* ``("zonemap", fp)`` — assembled zone maps for range pruning
  (``indexes/zonemaps.py``);
* ``("fusedplan", fp, …)`` — compiled fused-pipeline lowerings
  (``execution/pipeline_compiler.FusedAggPlan``): the symbolic
  Filter→Aggregate lowering reused across serves of one index version;
* ``("aggstate", fp)`` — assembled aggregate-plane partials
  (``indexes/aggindex.AggData``): the decoded per-row-group partial-
  aggregate state the metadata lowering folds instead of reading rows
  (docs/agg-serve.md).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.testing import faults


def file_fingerprint(files) -> Optional[Tuple]:
    """(path, size, mtime_ns) per file — the cache key component that makes
    stale entries unreachable. None when any file is missing (caller skips
    the cache and lets the normal read path raise its own error)."""
    out = []
    try:
        for f in files:
            st = os.stat(f)
            out.append((f, st.st_size, st.st_mtime_ns))
    except OSError:
        return None
    return tuple(out)


#: CPython small-object overhead charged per cached string (an empty
#: ``str`` is ~49 bytes resident)
_STR_OVERHEAD = 49


def _owned_nbytes(a: np.ndarray) -> int:
    """Resident bytes an ndarray actually pins. A zero-copy view (an
    arrow-buffer-backed decode, a slice of a larger cached array) keeps
    its WHOLE owner alive, so the owner's extent is what a byte governor
    must charge — ``a.nbytes`` alone reports the slice extent and
    undercounts exactly the pyarrow-backed entries. Walks the ``base``
    chain to the owning ndarray, then charges the backing buffer
    (``pyarrow.Buffer.size`` / ``memoryview.nbytes``) when it is larger
    still."""
    owner = a
    while isinstance(owner.base, np.ndarray):
        owner = owner.base
    extent = max(int(a.nbytes), int(owner.nbytes))
    base = owner.base
    if base is None:
        return extent
    for attr in ("size", "nbytes"):  # pyarrow.Buffer / memoryview
        n = getattr(base, attr, None)
        if isinstance(n, int) and n > extent:
            return n
    return extent


def estimate_nbytes(value, _depth: int = 0) -> int:
    """Approximate resident bytes of an arbitrary cached value — THE
    sizing primitive shared by the cache governor (``batch_nbytes``,
    ``ScanCacheEntry.budget_nbytes``) and the residency witness
    (``testing/residency_witness.py``), so the runtime accounting and
    the HS10xx bound model measure with one ruler. View-aware: numpy
    views charge their owner's full extent (``_owned_nbytes``), pyarrow
    containers report their total buffer size, and composite values
    (Column / ColumnarBatch / dict / sequence) recurse."""
    if value is None:
        return 0
    if isinstance(value, np.ndarray):
        return _owned_nbytes(value)
    if isinstance(value, (bool, int, float)):
        return 28
    if isinstance(value, (str, bytes, bytearray)):
        return len(value) + _STR_OVERHEAD
    if isinstance(value, Column):
        total = 0
        for a in (value.values, value.codes, value.validity):
            if a is not None:
                total += _owned_nbytes(a)
        if value.dictionary:
            total += sum(len(s) + _STR_OVERHEAD for s in value.dictionary)
        return total
    if isinstance(value, ColumnarBatch):
        return sum(
            estimate_nbytes(c, _depth + 1) for c in value.columns.values()
        )
    gtbs = getattr(value, "get_total_buffer_size", None)
    if callable(gtbs):  # pyarrow Table / RecordBatch / (Chunked)Array
        return int(gtbs())
    if type(value).__module__.partition(".")[0] == "pyarrow":
        n = getattr(value, "size", None)  # pyarrow.Buffer
        if isinstance(n, int):
            return n
    for attr in ("budget_nbytes", "nbytes"):
        n = getattr(value, attr, None)
        if isinstance(n, (int, float)):
            return int(n)
    if _depth >= 6:  # composite recursion guard; cached values are trees
        return 0
    if isinstance(value, dict):
        return 64 + sum(
            estimate_nbytes(k, _depth + 1) + estimate_nbytes(v, _depth + 1)
            for k, v in value.items()
        )
    if isinstance(value, (list, tuple, set, frozenset)):
        return 56 + sum(8 + estimate_nbytes(v, _depth + 1) for v in value)
    try:
        import sys

        return int(sys.getsizeof(value))
    except TypeError:
        return 0


def batch_nbytes(batch: ColumnarBatch) -> int:
    """Approximate resident bytes of a batch (arrays + dictionaries).
    Delegates to :func:`estimate_nbytes`, so view-backed columns charge
    the buffers they pin, not just their slice extent."""
    return estimate_nbytes(batch)


class ServeCache:
    """Thread-safe LRU cache, byte-capped — the serve plane's memory
    governor. Values carry their own size (entries are (value, nbytes)
    internally).

    Lock discipline (audited for the concurrent serve frontend,
    ``serve/frontend.py``; covered by the two-thread race tests in
    ``tests/test_serve_cache.py``): ONE lock guards the entry map, the
    byte ledger and every counter, and every public method takes it for
    its whole critical section — so ``resident_bytes`` can never
    observe a half-applied put, an eviction can never interleave with a
    replace's pop/re-add, and ``evict_kind`` snapshots its victim list
    under the same lock that guards concurrent ``get``/``put``. No I/O
    and no user code runs under the lock (values are stored, never
    inspected), keeping it HS502-clean and O(1)-held. Values handed out
    by ``get`` may outlive their entry (a racing eviction drops the
    cache's reference, not the caller's) — safe because every cached
    value is immutable by the publication contracts documented above.

    The governor's accounting invariant — ``resident_bytes`` equals the
    exact sum of resident entry sizes and never exceeds ``max_bytes`` —
    is what the byte budget means under concurrency; the stress tests
    assert it while readers, writers and evictors race.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        # resident-set telemetry (memory governor): high-water mark of
        # the byte ledger, cumulative LRU evictions, inserts dropped by
        # an armed cache_insert fault (testing/faults.py)
        self.high_water_bytes = 0
        self.evictions = 0
        self.evicted_bytes = 0
        self.insert_failures = 0
        # live stats() view in the metrics registry (docs/observability.
        # md; last-registered instance wins, the process-global
        # telemetry doctrine) — weakly bound so the registry never
        # keeps a replaced cache (and its gigabytes) alive
        from hyperspace_tpu.obs import metrics as obs_metrics

        obs_metrics.registry.register_weak_view("serve_cache", self)

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def peek(self, key):
        """Read without touching hit/miss counters or LRU order — for
        internal publication paths (re-reading the freshest entry before
        a merge-put must not skew the query-level statistics)."""
        with self._lock:
            entry = self._entries.get(key)
            return None if entry is None else entry[0]

    def put(self, key, value, nbytes: int) -> None:
        # fault-injection seam: a failing insert must never fail the
        # query — the value simply stays uncached (degrade-in-place),
        # counted so operators can see a sick cache backend. The detail
        # (the key's kind) is passed raw; it is stringified only when
        # the point is armed, like the parquet_read seam.
        if faults.degraded("cache_insert", key[:1] if key else ""):
            with self._lock:
                self.insert_failures += 1
            return
        if nbytes > self.max_bytes:
            return  # larger than the whole cache: not cacheable
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            # evict BEFORE inserting: the ledger never overshoots the
            # budget even transiently, so an unsynchronized
            # ``resident_bytes`` probe (telemetry threads, the stress
            # tests' budget assertion) can never observe a value past
            # ``max_bytes``
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, (_, freed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1
                self.evicted_bytes += freed
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            if self._bytes > self.high_water_bytes:
                self.high_water_bytes = self._bytes

    def clear(self) -> None:
        """Empty the cache and start a fresh telemetry epoch: the
        high-water mark resets with the contents (cumulative counters —
        hits/misses/evictions — keep counting), so per-phase probes
        (bench rungs) report their own peak, not an earlier phase's."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.high_water_bytes = 0

    def evict_kind(self, kind: str) -> int:
        """Drop every entry of one kind (keys are ``(kind, …)`` tuples:
        "scan" / "bucketed" / "joinside" / "delta" / "zonemap" /
        "fusedplan" / "aggstate"). Returns the number evicted. Operational tooling:
        lets a serve process (or bench) shed one class of state — e.g.
        keep the prepared hybrid delta but force joinside
        re-preparation, or drop compiled fused-pipeline plans after a
        config change — without a full clear. The victim list is built
        AND drained under the one cache lock, so a racing ``put`` of
        the same kind either lands before the snapshot (and is evicted)
        or after the drain (and survives) — never half-accounted."""
        with self._lock:
            victims = [
                k
                for k in self._entries
                if isinstance(k, tuple) and k and k[0] == kind
            ]
            for k in victims:
                _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes
            return len(victims)

    def evict_paths_under(self, root: str) -> int:
        """Drop every entry whose fingerprint names a file under
        ``root`` (an index directory). The fleet fanout's invalidation
        primitive (``serve/bus.py``): a refresh/optimize/vacuum in a
        PEER process re-keys or kills this index's entries — eviction
        frees the dead versions' bytes proactively instead of letting
        them age out of the LRU while fresher state fights for budget.
        Keys are tuples nesting fingerprint tuples of (path, size,
        mtime_ns) triples; the walk finds every string in the key, so
        every current and future key shape is covered. Victim list built
        and drained under the one cache lock, like ``evict_kind``."""
        prefix = root.replace("\\", "/").rstrip("/") + "/"

        def _mentions(obj) -> bool:
            if isinstance(obj, str):
                return obj.replace("\\", "/").startswith(prefix)
            if isinstance(obj, tuple):
                return any(_mentions(x) for x in obj)
            return False

        with self._lock:
            victims = [k for k in self._entries if _mentions(k)]
            for k in victims:
                _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes
            return len(victims)

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def bytes_by_kind(self) -> dict:
        """Resident bytes per entry kind — the governor's breakdown
        telemetry (which class of state owns the budget)."""
        with self._lock:
            out: dict = {}
            for k, (_v, nbytes) in self._entries.items():
                kind = k[0] if isinstance(k, tuple) and k else "other"
                out[kind] = out.get(kind, 0) + nbytes
            return out

    def stats(self) -> dict:
        """One consistent snapshot of the governor's counters (taken
        under the lock, so bytes/entries/high-water agree).
        ``snapshot_at_ms`` stamps WHEN — merge several frontends'/
        processes' snapshots with ``obs.merge_snapshots``, never by
        hand."""
        import time as _t

        with self._lock:
            return {
                "snapshot_at_ms": int(_t.time() * 1000),
                "resident_bytes": self._bytes,
                "high_water_bytes": self.high_water_bytes,
                "max_bytes": self.max_bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "insert_failures": self.insert_failures,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ScanCacheEntry:
    """Per-COLUMN cached decode of one index scan, with lazily-computed
    sorted-segment state.

    One entry per file set (key = ("scan", fp)); columns are added on
    demand as queries need them, so overlapping projections share one
    decoded copy per column instead of pinning a full batch per distinct
    column set. Index bucket files are key-sorted on disk; after an
    incremental refresh a bucket holds several files, each sorted but not
    globally merged — the entry keeps per-file segment boundaries and,
    per column, whether every segment is monotonic in key-rep order,
    detected from the data (never trusted from metadata), the same
    doctrine as the join's presorted fast path.

    Concurrency contract: a PUBLISHED entry (one that has been ``put``
    into the cache) is never structurally mutated — column additions go
    through :meth:`with_new_columns`, which builds a copy sharing the
    existing Column objects and is published by replacing the cache
    entry (racing writers waste a decode; readers never see a torn
    entry). ``column_state`` memoization is the one in-place write and
    is safe: racing threads compute identical values and dict assignment
    is atomic."""

    def __init__(self, segments):
        self.segments = tuple(segments)  # ((start, end), ...)
        self.columns: dict = {}  # name -> Column
        self._reps: dict = {}  # name -> (key_rep, all_segments_sorted)

    def with_new_columns(self, new_columns: dict) -> "ScanCacheEntry":
        """A copy of this entry with ``new_columns`` added (copy-on-write
        publication — see the concurrency contract above)."""
        out = ScanCacheEntry(self.segments)
        out.columns.update(self.columns)
        out.columns.update(new_columns)
        out._reps.update(self._reps)
        return out

    @property
    def num_rows(self) -> int:
        return self.segments[-1][1] if self.segments else 0

    def batch_for(self, cols) -> Optional[ColumnarBatch]:
        """A batch over ``cols``, or None when some column is not cached
        yet (caller reads the missing ones and publishes a copy via
        :meth:`with_new_columns`)."""
        if any(c not in self.columns for c in cols):
            return None
        return ColumnarBatch({c: self.columns[c] for c in cols})

    def column_state(self, name: str):
        """(key_rep, all_segments_sorted) for a column, memoized."""
        import numpy as np

        st = self._reps.get(name)
        if st is not None:
            return st
        rep = self.columns[name].key_rep()
        ok = True
        for s, e in self.segments:
            seg = rep[s:e]
            if len(seg) > 1 and not bool(np.all(seg[1:] >= seg[:-1])):
                ok = False
                break
        st = (rep, ok)
        self._reps[name] = st
        return st

    @property
    def budget_nbytes(self) -> int:
        """What the LRU accounting charges: every cached column PLUS its
        worst-case memoized key-rep (8 bytes/row, ``column_state``) —
        sizes are fixed at put() time, so growth must be pre-charged or
        the byte cap stops bounding real memory. Publishers re-put the
        ``with_new_columns`` copy with its new charge."""
        total = 0
        rows = self.num_rows
        for c in self.columns.values():
            total += estimate_nbytes(c)
            total += 8 * rows
        return total
