"""Approximate serve plane: sample-based COUNT/SUM with error bounds.

Approximate Distributed Joins in Apache Spark (PAPERS.md) argues
interactive traffic will happily trade exactness for latency — IF the
error is bounded and reported. This module serves ungrouped COUNT /
COUNT(col) / SUM estimates from the stratified per-row-group row sample
the aggregate index plane captures (``indexes/aggindex.py``,
``_aggsample.parquet``), with 95% confidence intervals from classical
stratified-sampling theory:

* strata are (file, row group); within stratum ``h`` of ``N_h`` rows,
  ``n_h`` rows were sampled uniformly without replacement;
* a COUNT estimate is ``Σ_h N_h·p_h`` with variance
  ``Σ_h N_h²·p_h(1-p_h)/n_h·(1-n_h/N_h)`` (finite-population
  correction: a fully-sampled stratum contributes zero variance);
* a SUM estimate uses ``y_i = v_i·1{row passes}`` (nulls contribute 0)
  with the stratified mean estimator ``Σ_h N_h·ȳ_h`` and variance
  ``Σ_h N_h²·s²_h/n_h·(1-n_h/N_h)``.

Contract (docs/agg-serve.md): approximate answers are produced ONLY
through the explicit ``DataFrame.collect_approx()`` opt-in behind
``hyperspace.serve.approx.enabled`` — the exact serve path never touches
samples — and an estimate whose interval blows the per-query error
budget (``hyperspace.serve.approx.maxRelativeError`` or the
``max_rel_error=`` override) raises a typed
:class:`~hyperspace_tpu.exceptions.ApproximationError` instead of
returning a number the caller would over-trust.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import ApproximationError
from hyperspace_tpu.plan.nodes import Aggregate, Filter, Project, Scan

#: 97.5th percentile of the standard normal — two-sided 95% interval
_Z95 = 1.959963984540054

# Telemetry of the LAST approximate serve (rebind-only, like the fused
# stats): strata counts, sample size, per-agg relative half-widths.
last_approx_stats: Dict[str, Any] = {}


def _match_plan(plan):
    """(cond | None, scan) when the optimized plan is an ungrouped
    Aggregate over [Project] [Filter] Scan, else None."""
    if not isinstance(plan, Aggregate) or plan.group_by:
        return None
    node = plan.child
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, Filter) and isinstance(node.child, Scan):
        return node.condition, node.child
    if isinstance(node, Scan):
        return None, node
    return None


def approx_aggregate(
    session, plan, max_rel_error: Optional[float] = None
) -> pa.Table:
    """Estimate an ungrouped COUNT/SUM aggregate from the stratified
    index sample. Returns one row with, per aggregate ``x``, columns
    ``x`` (the estimate), ``x_lo`` and ``x_hi`` (the 95% CI) — all
    float64, so an approximate answer can never be mistaken for the
    exact integer result. Raises :class:`ApproximationError` whenever an
    honest bounded estimate is impossible."""
    global last_approx_stats
    if session is None or not session.conf.serve_approx_enabled:
        raise ApproximationError(
            "approximate serving is disabled; set "
            "hyperspace.serve.approx.enabled=true to opt in"
        )
    budget = (
        session.conf.serve_approx_max_rel_error
        if max_rel_error is None
        else float(max_rel_error)
    )
    t0 = time.perf_counter()
    optimized = session.optimize(plan)
    m = _match_plan(optimized)
    if m is None:
        raise ApproximationError(
            "only ungrouped Filter→Aggregate plans are approximable"
        )
    cond, scan = m
    rel = scan.relation
    from hyperspace_tpu.execution import executor as X

    if rel.index_info is None or not X._cacheable_scan(rel):
        raise ApproximationError(
            "the plan is not served by a clean covering-index scan "
            "(no index, or query-shaped compensation is in play) — "
            "run exact instead"
        )
    for spec in plan.aggs:
        if spec.func not in ("count", "sum"):
            raise ApproximationError(
                f"{spec.func}() is not estimable from a sample; "
                "approximable aggregates are COUNT and SUM"
            )
    from hyperspace_tpu.indexes import aggindex

    sample = aggindex.sample_data_for(rel, session.conf)
    if sample is None:
        raise ApproximationError(
            "no stratified sample is available for this index "
            "(capture disabled, or a file is unreadable)"
        )
    from hyperspace_tpu.io.columnar import ColumnarBatch

    batch = ColumnarBatch.from_arrow(sample["table"])
    ns = batch.num_rows
    if cond is not None:
        passing = X._filter_mask(cond, batch, session).astype(bool)
    else:
        passing = np.ones(ns, dtype=bool)
    if not bool(passing.any()):
        # zero passing sample rows: the sample carries no information
        # about the selection's values and the normal interval collapses
        # to [0, 0] — refusing is the only honest answer
        raise ApproximationError(
            "no sampled row satisfies the predicate — the selection is "
            "too rare to estimate from the sample; run exact"
        )
    stratum = sample["stratum"]
    N = sample["N"].astype(np.float64)
    n = sample["n"].astype(np.float64)
    if bool(np.any((n < 2) & (n < N))) :
        # a partially-sampled stratum with one sample row has no
        # estimable variance (ddof=1 is undefined) — a zero-width
        # "interval" from it would be categorically false, so refuse
        # (a fully-sampled singleton stratum is exact and fine)
        raise ApproximationError(
            "a stratum has a single sampled row but more than one "
            "population row — variance is not estimable; enlarge "
            "hyperspace.index.agg.sampleRowsPerGroup or run exact"
        )
    H = len(N)
    fpc = np.clip(1.0 - n / N, 0.0, 1.0)
    out: Dict[str, Any] = {}
    rel_errs = []
    for spec in plan.aggs:
        if spec.func == "count":
            if spec.column is None:
                y = passing.astype(np.float64)
            else:
                col = batch.column(spec.column)
                nm = col.null_mask
                valid = (
                    np.ones(ns, dtype=bool) if nm is None else ~nm
                )
                y = (passing & valid).astype(np.float64)
        else:  # sum
            col = batch.column(spec.column)
            if col.kind != "numeric":
                raise ApproximationError(
                    f"sum() over non-numeric column {spec.column!r}"
                )
            v = col.values.astype(np.float64, copy=False)
            nm = col.null_mask
            if nm is not None:
                v = np.where(nm, 0.0, v)
            y = np.where(passing, v, 0.0)
        # per-stratum mean and (ddof=1) variance of y
        sums = np.bincount(stratum, weights=y, minlength=H)
        sq = np.bincount(stratum, weights=y * y, minlength=H)
        mean = sums / n
        with np.errstate(invalid="ignore", divide="ignore"):
            var_h = np.where(
                n > 1, (sq - n * mean * mean) / (n - 1), 0.0
            )
        var_h = np.maximum(var_h, 0.0)
        est = float(np.sum(N * mean))
        var = float(np.sum(N * N * var_h / n * fpc))
        hw = _Z95 * np.sqrt(max(var, 0.0))
        out[spec.name] = est
        out[spec.name + "_lo"] = est - hw
        out[spec.name + "_hi"] = est + hw
        rel_err = hw / abs(est) if est != 0.0 else (0.0 if hw == 0.0 else np.inf)
        rel_errs.append((spec.name, rel_err))
        if rel_err > budget:
            raise ApproximationError(
                f"estimate for {spec.name!r} has relative 95%-CI "
                f"half-width {rel_err:.4f} > budget {budget:.4f} — "
                "run exact, or widen the budget / enlarge "
                "hyperspace.index.agg.sampleRowsPerGroup"
            )
    last_approx_stats = {
        "mode": "agg_approx",
        "strata": H,
        "sample_rows": int(ns),
        "population_rows": int(sample["N"].sum()),
        "rel_half_widths": {k: float(v) for k, v in rel_errs},
        "wall_s": time.perf_counter() - t0,
    }
    return pa.table(
        {k: pa.array([v], type=pa.float64()) for k, v in out.items()}
    )
