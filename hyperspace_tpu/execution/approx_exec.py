"""Approximate serve plane: sample-based COUNT/SUM with error bounds.

Approximate Distributed Joins in Apache Spark (PAPERS.md) argues
interactive traffic will happily trade exactness for latency — IF the
error is bounded and reported. This module serves ungrouped COUNT /
COUNT(col) / SUM estimates from the stratified per-row-group row sample
the aggregate index plane captures (``indexes/aggindex.py``,
``_aggsample.parquet``), with 95% confidence intervals from classical
stratified-sampling theory:

* strata are (file, row group); within stratum ``h`` of ``N_h`` rows,
  ``n_h`` rows were sampled uniformly without replacement;
* a COUNT estimate is ``Σ_h N_h·p_h`` with variance
  ``Σ_h N_h²·p_h(1-p_h)/n_h·(1-n_h/N_h)`` (finite-population
  correction: a fully-sampled stratum contributes zero variance);
* a SUM estimate uses ``y_i = v_i·1{row passes}`` (nulls contribute 0)
  with the stratified mean estimator ``Σ_h N_h·ȳ_h`` and variance
  ``Σ_h N_h²·s²_h/n_h·(1-n_h/N_h)``.

Contract (docs/agg-serve.md): approximate answers are produced ONLY
through the explicit ``DataFrame.collect_approx()`` opt-in behind
``hyperspace.serve.approx.enabled`` — the exact serve path never touches
samples — and an estimate whose interval blows the per-query error
budget (``hyperspace.serve.approx.maxRelativeError`` or the
``max_rel_error=`` override) raises a typed
:class:`~hyperspace_tpu.exceptions.ApproximationError` instead of
returning a number the caller would over-trust.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np
import pyarrow as pa

from hyperspace_tpu.exceptions import ApproximationError
from hyperspace_tpu.plan.nodes import Aggregate, Filter, Project, Scan

#: 97.5th percentile of the standard normal — two-sided 95% interval
_Z95 = 1.959963984540054

# Telemetry of the LAST approximate serve (rebind-only, like the fused
# stats): strata counts, sample size, per-agg relative half-widths.
last_approx_stats: Dict[str, Any] = {}


def _match_plan(plan):
    """(cond | None, scan, group key | None) when the optimized plan is
    an ungrouped or SINGLE-KEY grouped Aggregate over [Project] [Filter]
    Scan, else None."""
    if not isinstance(plan, Aggregate) or len(plan.group_by) > 1:
        return None
    key = plan.group_by[0] if plan.group_by else None
    node = plan.child
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, Filter) and isinstance(node.child, Scan):
        return node.condition, node.child, key
    if isinstance(node, Scan):
        return None, node, key
    return None


def approx_aggregate(
    session, plan, max_rel_error: Optional[float] = None
) -> pa.Table:
    """Estimate an ungrouped — or single-key GROUPED — COUNT/SUM
    aggregate from the stratified index sample. Ungrouped: one row
    with, per aggregate ``x``, columns ``x`` (the estimate), ``x_lo``
    and ``x_hi`` (the 95% CI). Grouped: one row per group OBSERVED in
    the passing sample (key-sorted, nulls last), the key column first,
    then the same ``x``/``x_lo``/``x_hi`` triple per aggregate — each
    group gets its own interval from the same stratified estimator
    (``y`` restricted to the group's rows; zeros elsewhere count toward
    the variance, exactly the theory asks). Estimates are float64, so
    an approximate answer can never be mistaken for the exact integer
    result; groups too rare for the sample to see are absent (the
    per-group budget check bounds what CAN be returned — a group whose
    interval blows the budget raises instead). Raises
    :class:`ApproximationError` whenever an honest bounded estimate is
    impossible."""
    global last_approx_stats
    if session is None or not session.conf.serve_approx_enabled:
        raise ApproximationError(
            "approximate serving is disabled; set "
            "hyperspace.serve.approx.enabled=true to opt in"
        )
    budget = (
        session.conf.serve_approx_max_rel_error
        if max_rel_error is None
        else float(max_rel_error)
    )
    t0 = time.perf_counter()
    optimized = session.optimize(plan)
    m = _match_plan(optimized)
    if m is None:
        raise ApproximationError(
            "only ungrouped or single-key grouped Filter→Aggregate "
            "plans are approximable"
        )
    cond, scan, group_key = m
    rel = scan.relation
    from hyperspace_tpu.execution import executor as X

    if rel.index_info is None or not X._cacheable_scan(rel):
        raise ApproximationError(
            "the plan is not served by a clean covering-index scan "
            "(no index, or query-shaped compensation is in play) — "
            "run exact instead"
        )
    for spec in plan.aggs:
        if spec.func not in ("count", "sum"):
            raise ApproximationError(
                f"{spec.func}() is not estimable from a sample; "
                "approximable aggregates are COUNT and SUM"
            )
    from hyperspace_tpu.indexes import aggindex

    sample = aggindex.sample_data_for(rel, session.conf)
    if sample is None:
        raise ApproximationError(
            "no stratified sample is available for this index "
            "(capture disabled, or a file is unreadable)"
        )
    from hyperspace_tpu.io.columnar import ColumnarBatch

    batch = ColumnarBatch.from_arrow(sample["table"])
    ns = batch.num_rows
    if cond is not None:
        passing = X._filter_mask(cond, batch, session).astype(bool)
    else:
        passing = np.ones(ns, dtype=bool)
    if not bool(passing.any()):
        # zero passing sample rows: the sample carries no information
        # about the selection's values and the normal interval collapses
        # to [0, 0] — refusing is the only honest answer
        raise ApproximationError(
            "no sampled row satisfies the predicate — the selection is "
            "too rare to estimate from the sample; run exact"
        )
    stratum = sample["stratum"]
    N = sample["N"].astype(np.float64)
    n = sample["n"].astype(np.float64)
    if bool(np.any((n < 2) & (n < N))) :
        # a partially-sampled stratum with one sample row has no
        # estimable variance (ddof=1 is undefined) — a zero-width
        # "interval" from it would be categorically false, so refuse
        # (a fully-sampled singleton stratum is exact and fine)
        raise ApproximationError(
            "a stratum has a single sampled row but more than one "
            "population row — variance is not estimable; enlarge "
            "hyperspace.index.agg.sampleRowsPerGroup or run exact"
        )
    H = len(N)
    fpc = np.clip(1.0 - n / N, 0.0, 1.0)

    # -- group factorization over the PASSING sample rows --------------------
    # One virtual group for the ungrouped shape keeps the estimator a
    # single [H, G] computation either way: y restricted to a group is
    # zero on every other row, and those zeros belong in the stratum
    # mean/variance — that is what makes the per-group interval honest.
    if group_key is None:
        G = 1
        codes = np.zeros(ns, dtype=np.int64)
        grouped_rows = passing
        key_values = None
    else:
        if group_key not in batch.column_names:
            raise ApproximationError(
                f"group key {group_key!r} is not in the index sample — "
                "only indexed columns are estimable"
            )
        kcol = batch.column(group_key)
        rep = kcol.key_rep()
        nm = kcol.null_mask
        valid = np.ones(ns, dtype=bool) if nm is None else ~nm
        # null keys form their own group, like the exact engine's
        # factorize; an out-of-range rep stands in for them
        grouped_rows = passing
        pass_valid = passing & valid
        uniq = np.unique(rep[pass_valid])
        has_null_group = bool(np.any(passing & ~valid))
        G = len(uniq) + int(has_null_group)
        codes = np.searchsorted(uniq, rep)
        codes = np.clip(codes, 0, max(len(uniq) - 1, 0))
        # rows whose rep is not actually in uniq (non-passing values)
        # only matter where grouped_rows is True, and there membership
        # is exact; null rows get the trailing group id
        if has_null_group:
            codes = np.where(valid, codes, len(uniq))
        # group key values for the output: first passing occurrence
        order = np.argsort(codes[pass_valid], kind="stable")
        first_idx = np.nonzero(pass_valid)[0][order]
        _codes_sorted = codes[pass_valid][order]
        firsts = first_idx[
            np.searchsorted(_codes_sorted, np.arange(len(uniq)))
        ]
        arrow_key = sample["table"].column(group_key)
        key_values = arrow_key.take(pa.array(firsts, type=pa.int64()))
        if has_null_group:
            key_values = pa.concat_arrays(
                [
                    key_values.combine_chunks()
                    if isinstance(key_values, pa.ChunkedArray)
                    else key_values,
                    pa.nulls(1, type=arrow_key.type),
                ]
            )

    def _estimate(y: np.ndarray):
        """[G] estimates + half-widths from the stratified estimator
        applied per group (y already zeroed outside its rows)."""
        member = grouped_rows
        idx = stratum * G + codes
        sums = np.bincount(
            idx[member], weights=y[member], minlength=H * G
        ).reshape(H, G)
        sq = np.bincount(
            idx[member], weights=(y * y)[member], minlength=H * G
        ).reshape(H, G)
        n_col = n[:, None]
        mean = sums / n_col
        with np.errstate(invalid="ignore", divide="ignore"):
            var_h = np.where(
                n_col > 1, (sq - n_col * mean * mean) / (n_col - 1), 0.0
            )
        var_h = np.maximum(var_h, 0.0)
        est = np.sum(N[:, None] * mean, axis=0)
        var = np.sum(
            N[:, None] * N[:, None] * var_h / n_col * fpc[:, None], axis=0
        )
        return est, _Z95 * np.sqrt(np.maximum(var, 0.0))

    out: Dict[str, Any] = {}
    rel_errs = []
    for spec in plan.aggs:
        if spec.func == "count":
            if spec.column is None:
                y = passing.astype(np.float64)
            else:
                col = batch.column(spec.column)
                nm = col.null_mask
                valid_c = (
                    np.ones(ns, dtype=bool) if nm is None else ~nm
                )
                y = (passing & valid_c).astype(np.float64)
        else:  # sum
            col = batch.column(spec.column)
            if col.kind != "numeric":
                raise ApproximationError(
                    f"sum() over non-numeric column {spec.column!r}"
                )
            v = col.values.astype(np.float64, copy=False)
            nm = col.null_mask
            if nm is not None:
                v = np.where(nm, 0.0, v)
            y = np.where(passing, v, 0.0)
        est, hw = _estimate(y)
        out[spec.name] = est
        out[spec.name + "_lo"] = est - hw
        out[spec.name + "_hi"] = est + hw
        with np.errstate(invalid="ignore", divide="ignore"):
            rel = np.where(
                est != 0.0,
                hw / np.abs(est),
                np.where(hw == 0.0, 0.0, np.inf),
            )
        worst = float(np.max(rel)) if len(rel) else 0.0
        rel_errs.append((spec.name, worst))
        if worst > budget:
            raise ApproximationError(
                f"estimate for {spec.name!r} has relative 95%-CI "
                f"half-width {worst:.4f} > budget {budget:.4f}"
                + (
                    " in at least one group"
                    if group_key is not None
                    else ""
                )
                + " — run exact, or widen the budget / enlarge "
                "hyperspace.index.agg.sampleRowsPerGroup"
            )
    last_approx_stats = {
        "mode": "agg_approx",
        "strata": H,
        "groups": G if group_key is not None else 0,
        "sample_rows": int(ns),
        "population_rows": int(sample["N"].sum()),
        "rel_half_widths": {k: float(v) for k, v in rel_errs},
        "wall_s": time.perf_counter() - t0,
    }
    cols: Dict[str, Any] = {}
    if key_values is not None:
        cols[group_key] = key_values
    for k, v in out.items():
        cols[k] = pa.array(np.asarray(v, dtype=np.float64), type=pa.float64())
    table = pa.table(cols)
    if key_values is not None:
        table = table.sort_by([(group_key, "ascending")])
    return table
