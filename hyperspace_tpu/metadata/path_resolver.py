"""Resolve index names to index root directories.

Reference: ``index/PathResolver.scala:30-70`` — root is the
``hyperspace.system.path`` conf (default ``<warehouse>/indexes``); lookup
is case-insensitive against existing directories.
"""

from __future__ import annotations

import os
from typing import List

from hyperspace_tpu import constants as C


# Kept as an alias: the default itself lives in constants.py with every
# other key default (hslint HS701).
DEFAULT_SYSTEM_PATH = C.INDEX_SYSTEM_PATH_DEFAULT


class PathResolver:
    def __init__(self, conf):
        self._conf = conf

    @property
    def system_path(self) -> str:
        return self._conf.get_str(C.INDEX_SYSTEM_PATH, DEFAULT_SYSTEM_PATH)

    def get_index_path(self, name: str) -> str:
        """Existing dir matching case-insensitively, else ``<root>/<name>``
        (getIndexPath:39-58)."""
        root = self.system_path
        if os.path.isdir(root):
            for existing in os.listdir(root):
                if existing.lower() == name.lower():
                    return os.path.join(root, existing)
        return os.path.join(root, name)

    def all_index_paths(self) -> List[str]:
        root = self.system_path
        if not os.path.isdir(root):
            return []
        return [
            os.path.join(root, n)
            for n in sorted(os.listdir(root))
            if os.path.isdir(os.path.join(root, n))
            # lake-level service dirs (the spill tier, and any future
            # _hyperspace_* sidecar) are not indexes
            and not n.startswith("_hyperspace")
        ]
