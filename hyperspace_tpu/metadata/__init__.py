"""Metadata plane (L1): operation log, versioned index data, path layout.

Reference: ``src/main/scala/com/microsoft/hyperspace/index/`` —
``IndexLogEntry.scala``, ``IndexLogManager.scala``, ``IndexDataManager.scala``,
``PathResolver.scala``. Entirely host-side; no Spark/JVM dependence in the
reference either, which is why this layer ports semantically 1:1 while the
data plane below it is re-designed for TPU.
"""

from hyperspace_tpu.metadata.entry import (
    Content,
    Directory,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlan,
    Update,
)
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.path_resolver import PathResolver

__all__ = [
    "Content",
    "Directory",
    "FileIdTracker",
    "FileInfo",
    "IndexLogEntry",
    "LogEntry",
    "LogicalPlanFingerprint",
    "Relation",
    "Signature",
    "Source",
    "SourcePlan",
    "Update",
    "IndexLogManager",
    "IndexDataManager",
    "PathResolver",
]
