"""Versioned index-data directories ``<index>/v__=N/``.

Reference: ``index/IndexDataManager.scala`` (layout doc :24-37). Index data
for log version N lives under ``v__=N``; versions are immutable once
written, which is what makes quick/incremental refresh, restore and
time-travel cheap.
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from hyperspace_tpu.constants import INDEX_VERSION_DIR_PREFIX
from hyperspace_tpu.utils import files as file_utils

_VERSION_RE = re.compile(
    rf"{re.escape(INDEX_VERSION_DIR_PREFIX)}=(\d+)(?:/|$)"
)


def version_from_path(path: str) -> Optional[int]:
    m = _VERSION_RE.search(path.replace("\\", "/"))
    return int(m.group(1)) if m else None


class IndexDataManager:
    def __init__(self, index_path: str):
        self.index_path = index_path

    def _version_dir_name(self, version: int) -> str:
        return f"{INDEX_VERSION_DIR_PREFIX}={version}"

    def get_path(self, version: int) -> str:
        return os.path.join(self.index_path, self._version_dir_name(version))

    def get_all_versions(self) -> List[int]:
        if not os.path.isdir(self.index_path):
            return []
        out = []
        for name in os.listdir(self.index_path):
            if name.startswith(INDEX_VERSION_DIR_PREFIX + "="):
                try:
                    out.append(int(name.split("=", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def get_latest_version_id(self) -> Optional[int]:
        versions = self.get_all_versions()
        return versions[-1] if versions else None

    def delete(self, version: int) -> None:
        file_utils.delete(self.get_path(version))
