"""Operation-log manager with optimistic concurrency.

Reference: ``index/IndexLogManager.scala:57-195``. Layout under the index
root::

    <index>/_hyperspace_log/0, 1, 2, ...   numbered JSON log entries
    <index>/_hyperspace_log/latestStable   pointer file (copy of the entry)

Concurrency contract (writeLog:178-194): writing id N succeeds iff no file
named N exists — temp file + atomic link (create-if-absent). Two concurrent
actions conflict at their ``begin()`` write and exactly one proceeds.
"""

from __future__ import annotations

import os
from typing import List, Optional

from hyperspace_tpu.constants import (
    HYPERSPACE_LOG_DIR,
    LATEST_STABLE_LOG_NAME,
    States,
)
from hyperspace_tpu.exceptions import LogCorruptedError
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils
from hyperspace_tpu.utils import json_utils


def _parse_entry(path: str) -> IndexLogEntry:
    """Parse one on-disk log entry; typed LogCorruptedError on torn or
    unparseable JSON (a crash artifact, not a caller bug — the recovery
    plane treats it as a stranded entry)."""
    text = file_utils.read_text(path)
    try:
        return IndexLogEntry.from_dict(json_utils.from_json(text))
    except (ValueError, KeyError, TypeError) as exc:
        raise LogCorruptedError(path, f"{type(exc).__name__}: {exc}") from exc


class IndexLogManager:
    """IndexLogManagerImpl equivalent."""

    def __init__(self, index_path: str):
        self.index_path = index_path
        self.log_dir = os.path.join(index_path, HYPERSPACE_LOG_DIR)

    # -- paths --------------------------------------------------------------
    def _path_for(self, log_id: int) -> str:
        return os.path.join(self.log_dir, str(log_id))

    @property
    def _latest_stable_path(self) -> str:
        return os.path.join(self.log_dir, LATEST_STABLE_LOG_NAME)

    # -- reads --------------------------------------------------------------
    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        p = self._path_for(log_id)
        # fault-injection seam (testing/faults.py, "log_read"): the serve
        # frontend's snapshot pinning reads logs through here; an armed
        # point exercises its retry (transient) and serve-without-indexes
        # degrade (persistent) paths
        faults.check("log_read", p)
        if not os.path.isfile(p):
            return None
        return _parse_entry(p)

    def get_latest_id(self) -> Optional[int]:
        """Highest numeric log file present (getLatestId)."""
        if not os.path.isdir(self.log_dir):
            return None
        ids = [int(n) for n in os.listdir(self.log_dir) if n.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """latestStable pointer, else scan ids backwards for a stable state
        (getLatestStableLog:102-127)."""
        p = self._latest_stable_path
        faults.check("log_read", p)
        if os.path.isfile(p):
            try:
                entry = _parse_entry(p)
            except LogCorruptedError:
                # torn pointer (crash mid-publish on a no-atomic-rename
                # mount): fall through to the backward scan — the
                # numbered entries are the source of truth
                entry = None
            if entry is not None and entry.state in States.STABLE_STATES:
                return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            try:
                entry = self.get_log(log_id)
            except LogCorruptedError:
                # a torn entry is a stranded WRITE, not a reason the
                # index has no stable history: keep scanning past it
                continue
            if entry is not None and entry.state in States.STABLE_STATES:
                return entry
        return None

    def get_index_versions(self, states: List[str]) -> List[int]:
        """Log ids whose entry state is in ``states``
        (getIndexVersions:129-142), newest first."""
        latest = self.get_latest_id()
        if latest is None:
            return []
        out = []
        for log_id in range(latest, -1, -1):
            try:
                entry = self.get_log(log_id)
            except LogCorruptedError:
                continue
            if entry is not None and entry.state in states:
                out.append(log_id)
        return out

    def get_latest_stable_pointer_id(self) -> Optional[int]:
        """The id the latestStable POINTER file records — without the
        backward-scan fallback. None when the pointer is missing, torn,
        or names a non-stable entry. The recovery plane compares this
        against the latest stable entry to heal a crash that landed
        between end-log commit and pointer publish."""
        p = self._latest_stable_path
        if not os.path.isfile(p):
            return None
        try:
            entry = _parse_entry(p)
        except LogCorruptedError:
            return None
        return entry.id if entry.state in States.STABLE_STATES else None

    # -- writes -------------------------------------------------------------
    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Create log file ``log_id``; False on OCC conflict (writeLog:178-194).

        ``entry.id`` is only stamped after the write wins the race, so a
        losing writer's in-memory entry is left untouched.
        """
        payload = entry.to_dict()
        payload["id"] = log_id
        ok = file_utils.atomic_write_if_absent(
            self._path_for(log_id), json_utils.to_json(payload, indent=2)
        )
        if ok:
            entry.id = log_id
        return ok

    def overwrite_log(self, log_id: int, entry: IndexLogEntry) -> None:
        """Atomically REPLACE log file ``log_id`` — outside the OCC
        create-if-absent protocol on purpose. The single legitimate use
        is a live writer's lease heartbeat re-stamping its own TRANSIENT
        entry (``metadata/recovery.py``); final entries are immutable
        and only ever created through :meth:`write_log`."""
        payload = entry.to_dict()
        payload["id"] = log_id
        file_utils.atomic_overwrite(
            self._path_for(log_id), json_utils.to_json(payload, indent=2)
        )

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Copy entry ``log_id`` onto the latestStable pointer
        (createLatestStableLog:144-162)."""
        entry = self.get_log(log_id)
        if entry is None or entry.state not in States.STABLE_STATES:
            return False
        file_utils.atomic_overwrite(
            self._latest_stable_path, json_utils.to_json(entry.to_dict(), indent=2)
        )
        return True

    def delete_latest_stable_log(self) -> None:
        file_utils.delete(self._latest_stable_path)

    def delete_log(self) -> None:
        """Remove the whole log dir (vacuum)."""
        file_utils.delete(self.log_dir)
