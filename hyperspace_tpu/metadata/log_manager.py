"""Operation-log manager with optimistic concurrency.

Reference: ``index/IndexLogManager.scala:57-195``. Layout under the index
root::

    <index>/_hyperspace_log/0, 1, 2, ...   numbered JSON log entries
    <index>/_hyperspace_log/latestStable   pointer file (copy of the entry)

Concurrency contract (writeLog:178-194): writing id N succeeds iff no file
named N exists — temp file + atomic link (create-if-absent). Two concurrent
actions conflict at their ``begin()`` write and exactly one proceeds.
"""

from __future__ import annotations

import os
from typing import List, Optional

from hyperspace_tpu.constants import (
    HYPERSPACE_LOG_DIR,
    LATEST_STABLE_LOG_NAME,
    States,
)
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils
from hyperspace_tpu.utils import json_utils


class IndexLogManager:
    """IndexLogManagerImpl equivalent."""

    def __init__(self, index_path: str):
        self.index_path = index_path
        self.log_dir = os.path.join(index_path, HYPERSPACE_LOG_DIR)

    # -- paths --------------------------------------------------------------
    def _path_for(self, log_id: int) -> str:
        return os.path.join(self.log_dir, str(log_id))

    @property
    def _latest_stable_path(self) -> str:
        return os.path.join(self.log_dir, LATEST_STABLE_LOG_NAME)

    # -- reads --------------------------------------------------------------
    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        p = self._path_for(log_id)
        # fault-injection seam (testing/faults.py, "log_read"): the serve
        # frontend's snapshot pinning reads logs through here; an armed
        # point exercises its retry (transient) and serve-without-indexes
        # degrade (persistent) paths
        faults.check("log_read", p)
        if not os.path.isfile(p):
            return None
        return IndexLogEntry.from_dict(json_utils.from_json(file_utils.read_text(p)))

    def get_latest_id(self) -> Optional[int]:
        """Highest numeric log file present (getLatestId)."""
        if not os.path.isdir(self.log_dir):
            return None
        ids = [int(n) for n in os.listdir(self.log_dir) if n.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """latestStable pointer, else scan ids backwards for a stable state
        (getLatestStableLog:102-127)."""
        p = self._latest_stable_path
        faults.check("log_read", p)
        if os.path.isfile(p):
            entry = IndexLogEntry.from_dict(
                json_utils.from_json(file_utils.read_text(p))
            )
            if entry.state in States.STABLE_STATES:
                return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in States.STABLE_STATES:
                return entry
        return None

    def get_index_versions(self, states: List[str]) -> List[int]:
        """Log ids whose entry state is in ``states``
        (getIndexVersions:129-142), newest first."""
        latest = self.get_latest_id()
        if latest is None:
            return []
        out = []
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in states:
                out.append(log_id)
        return out

    # -- writes -------------------------------------------------------------
    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Create log file ``log_id``; False on OCC conflict (writeLog:178-194).

        ``entry.id`` is only stamped after the write wins the race, so a
        losing writer's in-memory entry is left untouched.
        """
        payload = entry.to_dict()
        payload["id"] = log_id
        ok = file_utils.atomic_write_if_absent(
            self._path_for(log_id), json_utils.to_json(payload, indent=2)
        )
        if ok:
            entry.id = log_id
        return ok

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Copy entry ``log_id`` onto the latestStable pointer
        (createLatestStableLog:144-162)."""
        entry = self.get_log(log_id)
        if entry is None or entry.state not in States.STABLE_STATES:
            return False
        file_utils.atomic_overwrite(
            self._latest_stable_path, json_utils.to_json(entry.to_dict(), indent=2)
        )
        return True

    def delete_latest_stable_log(self) -> None:
        file_utils.delete(self._latest_stable_path)

    def delete_log(self) -> None:
        """Remove the whole log dir (vacuum)."""
        file_utils.delete(self.log_dir)
