"""Crash-safe lifecycle recovery: leases, rollback, orphan GC.

The operation log's OCC protocol (``actions/base.py``,
``metadata/log_manager.py``) is correct for writers that FINISH — a
writer that dies mid-``op()`` strands a transient entry
(CREATING/REFRESHING/…) and the data files it half-wrote, forever.
Exoshuffle (PAPERS.md) argues fault tolerance belongs in the data-plane
framework itself; this module is that plane for the index lifecycle:

* **Writer lease / heartbeat.** ``Action.run`` stamps an owner id and a
  lease expiry into the transient begin entry and re-stamps it every
  ``leaseMs/3`` while the op runs (:class:`LeaseHeartbeat`, via
  ``IndexLogManager.overwrite_log`` — the one sanctioned mutation of a
  log entry, legal only for the owner of a TRANSIENT entry). A slow
  writer keeps its lease fresh; a dead writer's lease expires. That
  expiry is the dead/slow discriminator every other piece keys on.

* **Stranded-entry detection + rollback.** :func:`ensure_recovered`
  runs at action start (``Action.run``) and session attach
  (``manager.IndexCollectionManager``). A latest entry that is
  transient with an expired lease — or torn
  (:class:`~hyperspace_tpu.exceptions.LogCorruptedError`) — is rolled
  back along the ``constants.States.ROLLBACK`` edge by appending a copy
  of the last stable entry at the next id (exactly ``cancel()``'s
  write, shared here). The write is the standard OCC create-if-absent
  with fsync-before-publish, so two concurrent recoverers cannot
  double-roll: one wins the id, the other observes the new entry. A
  crash BETWEEN end-log commit and latestStable publish needs no
  rollback, only healing: the pointer is re-published from the newest
  stable entry.

* **Orphan data GC.** :func:`gc_orphans` quarantines index data files
  referenced by no stable log entry into
  ``<index>/_hyperspace_quarantine/<stamp>/`` and deletes quarantine
  stamps older than ``hyperspace.recovery.orphanGraceMs``. Files pinned
  by an in-process serve snapshot (``serve/frontend.py`` registers its
  pins here) are never quarantined, so a live query cannot lose its
  files mid-flight; the grace TTL covers readers in other processes.

Everything is idempotent and OCC-safe by construction: rollback loses
races gracefully, GC re-run finds nothing, pointer healing rewrites the
same bytes.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hyperspace_tpu.constants import (
    FLEET_PIN_LEASE_MS_DEFAULT,
    HYPERSPACE_LOG_DIR,
    HYPERSPACE_PINS_DIR,
    HYPERSPACE_QUARANTINE_DIR,
    HYPERSPACE_SPILL_DIR,
    SERVE_SPILL_ORPHAN_TTL_MS_DEFAULT,
    INDEX_VERSION_DIR_PREFIX,
    RECOVERY_LEASE_MS_DEFAULT,
    RECOVERY_ORPHAN_GRACE_MS_DEFAULT,
    States,
)
from hyperspace_tpu.exceptions import LogCorruptedError
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.utils import files as file_utils
from hyperspace_tpu.utils import paths as path_utils

# Lease bookkeeping lives in the entry's free-form ``properties`` dict —
# round-trips through the existing JSON schema untouched, and pre-lease
# entries simply lack the keys (timestamp fallback below).
LEASE_OWNER_PROP = "recovery.leaseOwner"
LEASE_EXPIRES_PROP = "recovery.leaseExpiresAtMs"


def now_ms() -> int:
    return int(time.time() * 1000)


def new_owner_id() -> str:
    return uuid.uuid4().hex


# ---------------------------------------------------------------------------
# Leases
# ---------------------------------------------------------------------------


def stamp_lease(
    entry: IndexLogEntry, owner: str, lease_ms: int, now: Optional[int] = None
) -> None:
    """Stamp (or renew) the writer lease on a transient entry."""
    now = now_ms() if now is None else now
    entry.properties[LEASE_OWNER_PROP] = owner
    entry.properties[LEASE_EXPIRES_PROP] = str(now + lease_ms)


def clear_lease(entry: IndexLogEntry) -> None:
    entry.properties.pop(LEASE_OWNER_PROP, None)
    entry.properties.pop(LEASE_EXPIRES_PROP, None)


def lease_expires_at(entry: IndexLogEntry, lease_ms: int) -> int:
    """When this entry's writer must be presumed dead (ms epoch).

    Entries from before the lease era (or written with recovery off)
    have no lease properties; their write timestamp plus one lease
    period is the conservative stand-in."""
    raw = entry.properties.get(LEASE_EXPIRES_PROP)
    if raw is not None:
        try:
            return int(raw)
        except (TypeError, ValueError):
            pass
    return int(entry.timestamp) + lease_ms


def is_stranded(
    entry: Optional[IndexLogEntry],
    lease_ms: int = RECOVERY_LEASE_MS_DEFAULT,
    now: Optional[int] = None,
) -> bool:
    """True when ``entry`` is a dead writer's leavings: a transient
    state whose lease has expired. A torn entry (``entry is None`` from
    a caught LogCorruptedError) is always stranded — a live writer's
    entry parses, its publish is fsynced before the name exists."""
    if entry is None:
        return True
    if entry.state in States.STABLE_STATES:
        return False
    now = now_ms() if now is None else now
    return lease_expires_at(entry, lease_ms) <= now


class LeaseHeartbeat:
    """Renews the writer lease on a transient entry every ``lease/3``
    until stopped. Owned by ``Action.run``: started right after the
    begin entry wins its OCC write, stopped in the commit/abort path.
    An ``os._exit`` crash (or SIGKILL) never stops it — the thread dies
    with the process and the lease expires, which is the signal."""

    def __init__(
        self,
        log_manager: IndexLogManager,
        log_id: int,
        entry: IndexLogEntry,
        owner: str,
        lease_ms: int,
    ):
        self._log_manager = log_manager
        self._log_id = log_id
        self._entry = entry
        self._owner = owner
        self._lease_ms = lease_ms
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"hs-lease-{log_id}", daemon=True
        )

    def start(self) -> "LeaseHeartbeat":
        self._thread.start()
        return self

    def _run(self) -> None:
        interval = max(self._lease_ms / 3000.0, 0.005)
        while not self._stop.wait(interval):
            stamp_lease(self._entry, self._owner, self._lease_ms)
            try:
                self._log_manager.overwrite_log(self._log_id, self._entry)
            except OSError:
                # best-effort: a failed renewal only ages the lease; the
                # next tick retries, and a recovery triggered by a
                # genuinely unreachable log dir is the correct outcome
                pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Rollback + pointer healing
# ---------------------------------------------------------------------------


def _latest_stable_by_scan(
    log_manager: IndexLogManager, below_id: int
) -> Optional[IndexLogEntry]:
    """Newest parseable stable entry with id < ``below_id`` — the
    rollback source. Scans the numbered entries, never the pointer (the
    pointer may itself be stale or torn after a crash)."""
    for log_id in range(below_id - 1, -1, -1):
        try:
            entry = log_manager.get_log(log_id)
        except LogCorruptedError:
            continue
        if entry is not None and entry.state in States.STABLE_STATES:
            return entry
    return None


def rollback(
    log_manager: IndexLogManager, latest_id: Optional[int] = None
) -> Tuple[Optional[IndexLogEntry], bool]:
    """Roll the log back from a transient/torn latest entry to its
    stable predecessor along the ``States.ROLLBACK`` edge.

    Appends a copy of the last stable entry (or the transient entry
    restamped with its rollback state when nothing stable ever existed
    — the failed-create case) at ``latest_id + 1`` and republishes
    latestStable. OCC-safe: the append is create-if-absent, so of two
    concurrent recoverers exactly one writes; the loser re-reads and
    returns whatever won. Shared by ``actions/cancel.py`` (the manual
    override, which does not check leases) and
    :func:`ensure_recovered` (which does).

    Returns ``(tip_entry, we_wrote)``: the entry now at the log tip
    (None when the log ended up empty) and whether THIS call performed
    the recovery. ``we_wrote=False`` means a competitor's write — a
    concurrent recoverer's rollback, or the not-dead-after-all writer's
    own end-commit — won the id; the caller decides whether the
    survivor satisfies it (auto-recovery: yes, any stable tip does;
    cancel: no, a commit is the opposite of a cancel)."""
    if latest_id is None:
        latest_id = log_manager.get_latest_id()
    if latest_id is None:
        return None, False
    try:
        latest = log_manager.get_log(latest_id)
    except LogCorruptedError:
        latest = None
    if latest is not None and latest.state in States.STABLE_STATES:
        return latest, False  # nothing to roll back (someone already did)
    stable = _latest_stable_by_scan(log_manager, latest_id)
    if stable is not None:
        entry = stable.copy()
    elif latest is not None:
        # no stable history (a crashed first create): the ROLLBACK edge
        # names the target — DOESNOTEXIST for CREATING
        target = States.ROLLBACK.get(latest.state, States.DOESNOTEXIST)
        entry = latest.with_state(target)
    else:
        # single torn entry and no stable history: the index never
        # reached a publishable state — clear the wreckage so the name
        # is reusable (get_latest_id -> None == DOESNOTEXIST)
        file_utils.delete(log_manager._path_for(latest_id))
        log_manager.delete_latest_stable_log()
        return None, True
    clear_lease(entry)
    if not log_manager.write_log(latest_id + 1, entry):
        # another recoverer (or the not-dead-after-all writer's commit)
        # won the id: their write is the truth now
        try:
            return log_manager.get_log(log_manager.get_latest_id()), False
        except LogCorruptedError:
            return None, False
    log_manager.create_latest_stable_log(latest_id + 1)
    return entry, True


def ensure_recovered(
    log_manager: IndexLogManager,
    lease_ms: int = RECOVERY_LEASE_MS_DEFAULT,
    now: Optional[int] = None,
) -> Dict[str, object]:
    """Detect and repair a dead writer's leavings at the log tip.

    Three cases, all idempotent:

    * latest entry stable but the latestStable pointer behind/missing
      (crash between end-log and publish) → re-publish the pointer;
    * latest entry transient/torn with an EXPIRED lease → rollback;
    * latest entry transient with a LIVE lease → leave it alone (a slow
      writer is not a dead one) and report it.

    Returns a report dict: ``rolled_back``, ``healed_pointer``,
    ``live_writer`` (bool each) + ``latest_state``.
    """
    report: Dict[str, object] = {
        "rolled_back": False,
        "healed_pointer": False,
        "live_writer": False,
        "latest_state": None,
    }
    latest_id = log_manager.get_latest_id()
    if latest_id is None:
        return report
    try:
        latest = log_manager.get_log(latest_id)
    except LogCorruptedError:
        latest = None
    if latest is not None and latest.state in States.STABLE_STATES:
        report["latest_state"] = latest.state
        if log_manager.get_latest_stable_pointer_id() != latest_id:
            log_manager.create_latest_stable_log(latest_id)
            report["healed_pointer"] = True
        return report
    if not is_stranded(latest, lease_ms, now):
        report["latest_state"] = latest.state
        report["live_writer"] = True
        return report
    rolled, _we_wrote = rollback(log_manager, latest_id)
    # either way the tip is repaired — by us or by the competitor whose
    # write beat ours; auto-recovery only cares that it IS repaired
    report["rolled_back"] = True
    report["latest_state"] = rolled.state if rolled is not None else None
    return report


# ---------------------------------------------------------------------------
# Serve snapshot pins (GC coordination)
# ---------------------------------------------------------------------------

_pins_lock = threading.Lock()
_active_pins: Dict[int, frozenset] = {}
_pin_seq = 0

#: this process's durable-pin identity (immutable; pin files are named
#: ``<owner>.<token>.json`` so two frontends in two processes can never
#: collide, and a restarted process never renews its predecessor's pins)
_pin_owner = uuid.uuid4().hex[:16]

# token -> {"lease_ms": int, "paths": {pin file path: [files]}} for the
# heartbeat's renewal sweep (SHARED_STATE: guarded by _pins_lock)
_durable_pins: Dict[int, Dict[str, object]] = {}
_pin_heartbeat = None  # the renewal thread, started on first durable pin


def _index_root_of(path: str) -> Optional[str]:
    """The index root a data file lives under — the parent of its
    ``v__=N`` version-dir component — or None for a path outside any
    version dir (not durably pinnable; the in-memory pin still holds)."""
    norm = path.replace("\\", "/")
    parts = norm.split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i].startswith(INDEX_VERSION_DIR_PREFIX + "="):
            return "/".join(parts[:i])
    return None


def _pin_file_payload(token: int, files: List[str], lease_ms: int) -> str:
    return json.dumps(
        {
            "owner": _pin_owner,
            "pid": os.getpid(),
            "token": token,
            "leaseMs": int(lease_ms),
            "expiresAtMs": now_ms() + int(lease_ms),
            "files": sorted(files),
        }
    )


def _write_pin_files(
    token: int, by_root: Dict[str, List[str]], lease_ms: int
) -> Dict[str, List[str]]:
    """Publish one pin file per index root (fsync-before-replace);
    returns {pin file path: files}. Best-effort per root: an unwritable
    pins dir costs the durable protection for that index only — the
    in-memory pin still guards same-process GC, and failing the QUERY
    over a bookkeeping write would invert the priorities."""
    out: Dict[str, List[str]] = {}
    for root, files in by_root.items():
        pin_path = os.path.join(
            root, HYPERSPACE_PINS_DIR, f"{_pin_owner}.{token}.json"
        )
        try:
            file_utils.atomic_overwrite(
                pin_path, _pin_file_payload(token, files, lease_ms)
            )
        except OSError:
            continue
        out[pin_path] = files
    return out


class _PinHeartbeat:
    """Renews every live durable pin file each ``min(lease)/3`` until the
    process exits — the reader-side twin of :class:`LeaseHeartbeat`. A
    SIGKILL never stops it; the leases expire and the next GC/vacuum in
    any process reaps the pins, which is the signal."""

    def __init__(self):
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="hs-pin-heartbeat", daemon=True
        )
        self._thread.start()

    def wake(self) -> None:
        """Cut the current wait short — a newly registered pin may carry
        a much shorter lease than the interval the thread is sleeping
        on."""
        self._wake.set()

    def _run(self) -> None:
        while True:
            # clear BEFORE snapshotting: a pin registered after the
            # snapshot sets the event and cuts the wait short; one
            # registered before it is in the snapshot — either way no
            # short-lease pin waits out a stale interval
            self._wake.clear()
            with _pins_lock:
                snapshot = [
                    (t, int(info["lease_ms"]), dict(info["paths"]))
                    for t, info in _durable_pins.items()
                ]
            interval = (
                min((lease for _t, lease, _p in snapshot), default=1000)
                / 3000.0
            )
            self._wake.wait(max(interval, 0.005))
            if self._stop.is_set():
                return
            for token, lease_ms, paths in snapshot:
                with _pins_lock:
                    live = token in _durable_pins
                if not live:
                    continue
                for pin_path, files in paths.items():
                    try:
                        file_utils.atomic_overwrite(
                            pin_path,
                            _pin_file_payload(token, files, lease_ms),
                        )
                    except OSError:
                        # best-effort, like the writer lease: a failed
                        # renewal only ages the pin; the next tick
                        # retries, and expiry under a truly dead store
                        # is the designed outcome
                        continue
                    # write-then-verify: release_pins may have deleted
                    # the file between the liveness check above and our
                    # rewrite — a resurrected pin would block GC/vacuum
                    # for a full lease, so re-check and undo
                    with _pins_lock:
                        live = token in _durable_pins
                    if not live:
                        file_utils.delete(pin_path)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()


def register_pins(
    entries: Optional[Iterable[IndexLogEntry]],
    durable: bool = False,
    lease_ms: int = FLEET_PIN_LEASE_MS_DEFAULT,
    heartbeat: bool = True,
) -> int:
    """Record the index files a serve snapshot depends on; returns a
    token for :func:`release_pins`. GC never quarantines a pinned file,
    so a query that pinned its snapshot before a version went
    unreferenced still finds every byte.

    With ``durable=True`` (fleet mode, docs/fleet-serve.md) the pin is
    ALSO published as a lease-expiring file per index root —
    ``<index>/_hyperspace_pins/<proc>.<seq>.json``, fsync-before-replace
    — so an orphan GC or vacuum running in ANOTHER process sees it too.
    A heartbeat renews the lease every ``lease_ms/3``; a frontend that
    dies (kill -9) stops renewing and the pin is reaped at expiry
    (``heartbeat=False`` exists for the tests that simulate exactly
    that death)."""
    files: Set[str] = set()
    for e in entries or ():
        files.update(p.replace("\\", "/") for p in e.content.files)
    global _pin_seq, _pin_heartbeat
    with _pins_lock:
        _pin_seq += 1
        token = _pin_seq
        _active_pins[token] = frozenset(files)
    if not durable or not files:
        return token
    by_root: Dict[str, List[str]] = {}
    for f in files:
        root = _index_root_of(f)
        if root is not None:
            by_root.setdefault(root, []).append(f)
    # file I/O stays OUTSIDE the pins lock (HS5xx: no I/O under a lock
    # serve threads contend on)
    written = _write_pin_files(token, by_root, lease_ms)
    if written:
        with _pins_lock:
            if token in _active_pins:
                _durable_pins[token] = {
                    "lease_ms": int(lease_ms),
                    "paths": written,
                }
                if heartbeat:
                    if _pin_heartbeat is None:
                        _pin_heartbeat = _PinHeartbeat()
                    else:
                        _pin_heartbeat.wake()
                doomed = {}
            else:
                # release_pins raced us between the write and this
                # record: the pin files must not outlive the token
                doomed = written
        for pin_path in doomed:
            file_utils.delete(pin_path)
    return token


def release_pins(token: int) -> None:
    with _pins_lock:
        _active_pins.pop(token, None)
        durable = _durable_pins.pop(token, None)
    if durable:
        for pin_path in durable["paths"]:
            file_utils.delete(pin_path)


def pinned_files() -> Set[str]:
    """Union of all currently pinned index files (normalized paths)."""
    with _pins_lock:
        snapshots = list(_active_pins.values())
    out: Set[str] = set()
    for s in snapshots:
        out |= s
    return out


def _scan_durable_pins(
    index_path: str, now: Optional[int] = None, reap: bool = True
) -> Tuple[Set[str], int]:
    """(files protected by UNEXPIRED pin files under ``index_path``,
    expired/torn pin files reaped). An expired pin belongs to a dead
    frontend — its query either finished or died with it, so the file
    set converges back to the referenced-or-quarantined partition; a
    torn pin file can protect nothing and is reaped the same way."""
    pins_dir = os.path.join(index_path, HYPERSPACE_PINS_DIR)
    if not os.path.isdir(pins_dir):
        return set(), 0
    now = now_ms() if now is None else now
    out: Set[str] = set()
    reaped = 0
    for name in sorted(os.listdir(pins_dir)):
        if not name.endswith(".json"):
            continue  # publish temps (.tmp_log_*) are not pins
        p = os.path.join(pins_dir, name)
        try:
            with open(p, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            expires = int(doc["expiresAtMs"])
        except (OSError, ValueError, KeyError, TypeError):
            if reap:
                file_utils.delete(p)
                reaped += 1
            continue
        if expires <= now:
            if reap:
                file_utils.delete(p)
                reaped += 1
            continue
        out.update(str(f).replace("\\", "/") for f in doc.get("files", ()))
    if reap:
        try:
            if not os.listdir(pins_dir):
                os.rmdir(pins_dir)
        except OSError:
            pass
    return out, reaped


def durable_pinned_files(
    index_path: str, now: Optional[int] = None
) -> Set[str]:
    """Files protected by live (lease-unexpired) cross-process pin files
    under ``index_path``; expired pins are reaped along the way."""
    files, _reaped = _scan_durable_pins(index_path, now)
    return files


def all_pinned_files(index_path: str, now: Optional[int] = None) -> Set[str]:
    """Everything a GC or vacuum of ``index_path`` must not delete:
    this process's in-memory pins UNION every process's live durable
    pin files (fleet mode)."""
    return pinned_files() | durable_pinned_files(index_path, now)


# ---------------------------------------------------------------------------
# Orphan GC
# ---------------------------------------------------------------------------


def _referenced_files(log_manager: IndexLogManager) -> Set[str]:
    """Every data file any parseable STABLE entry references. Stable
    entries are the only ones whose content is a promise — a transient
    entry's content either becomes stable (then its files appear there
    too) or gets rolled back (then its files are exactly the orphans)."""
    out: Set[str] = set()
    latest = log_manager.get_latest_id()
    if latest is None:
        return out
    for log_id in range(latest, -1, -1):
        try:
            entry = log_manager.get_log(log_id)
        except LogCorruptedError:
            continue
        if entry is not None and entry.state in States.STABLE_STATES:
            out.update(p.replace("\\", "/") for p in entry.content.files)
    return out


def find_orphans(index_path: str) -> List[str]:
    """Data files under the index's version dirs that no stable log
    entry references (quarantine excluded). The zero-orphans assert of
    the crash matrix and the chaos harness."""
    log_manager = IndexLogManager(index_path)
    if log_manager.get_latest_id() is None:
        return []
    referenced = _referenced_files(log_manager)
    orphans: List[str] = []
    for name in sorted(os.listdir(index_path)):
        if name in (
            HYPERSPACE_LOG_DIR,
            HYPERSPACE_QUARANTINE_DIR,
            HYPERSPACE_PINS_DIR,
            HYPERSPACE_SPILL_DIR,
        ):
            continue
        root = os.path.join(index_path, name)
        if not os.path.isdir(root):
            continue
        for p, _size, _mtime in file_utils.list_leaf_files(root):
            norm = p.replace("\\", "/")
            if path_utils.is_data_path(norm) and norm not in referenced:
                orphans.append(norm)
    return orphans


def gc_orphans(
    index_path: str,
    grace_ms: int = RECOVERY_ORPHAN_GRACE_MS_DEFAULT,
    now: Optional[int] = None,
    lease_ms: int = RECOVERY_LEASE_MS_DEFAULT,
) -> Dict[str, object]:
    """Quarantine-then-delete unreferenced index data files.

    Two phases, each idempotent:

    1. every data file under a version dir that no stable entry
       references — and no live in-process serve pin names — MOVES to
       ``_hyperspace_quarantine/<now_ms>/`` (directories left with no
       data files go wholesale, sidecars and all);
    2. quarantine stamps older than ``grace_ms`` are deleted.

    A LIVE writer (transient log tip whose lease has not expired) skips
    phase 1 entirely: its half-written version dir is referenced by no
    entry yet, and no per-file test can tell its work from a dead
    writer's leavings — only the lease can. Phase 2 still purges old
    stamps.

    With ``grace_ms=0`` the sweep is immediate (tests, the chaos
    harness); production keeps the default TTL so out-of-process
    readers of a just-vacated version get the grace window the
    in-process pin registry gives local queries.
    """
    now = now_ms() if now is None else now
    log_manager = IndexLogManager(index_path)
    report: Dict[str, object] = {
        "quarantined_files": 0,
        "quarantined_dirs": 0,
        "kept_pinned": 0,
        "purged_stamps": 0,
        "reaped_pins": 0,
        "skipped_live_writer": False,
    }
    latest_id = log_manager.get_latest_id()
    if latest_id is None:
        return report
    try:
        tip = log_manager.get_log(latest_id)
    except LogCorruptedError:
        tip = None
    if (
        tip is not None
        and tip.state not in States.STABLE_STATES
        and not is_stranded(tip, lease_ms, now)
    ):
        report["skipped_live_writer"] = True
        _purge_quarantine(index_path, grace_ms, now, report)
        return report
    referenced = _referenced_files(log_manager)
    durable, reaped = _scan_durable_pins(index_path, now)
    report["reaped_pins"] = reaped
    pinned = pinned_files() | durable
    quarantine_root = os.path.join(index_path, HYPERSPACE_QUARANTINE_DIR)
    stamp_dir = os.path.join(quarantine_root, str(now))

    def _move(src: str) -> None:
        rel = os.path.relpath(src, index_path)
        dst = os.path.join(stamp_dir, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.move(src, dst)

    for name in sorted(os.listdir(index_path)):
        if name in (
            HYPERSPACE_LOG_DIR,
            HYPERSPACE_QUARANTINE_DIR,
            HYPERSPACE_PINS_DIR,
            HYPERSPACE_SPILL_DIR,
        ):
            continue
        root = os.path.join(index_path, name)
        if not os.path.isdir(root):
            continue
        listed = file_utils.list_leaf_files(root)
        data = [
            p.replace("\\", "/")
            for p, _s, _m in listed
            if path_utils.is_data_path(p)
        ]
        live = [p for p in data if p in referenced]
        doomed = [p for p in data if p not in referenced and p not in pinned]
        report["kept_pinned"] += sum(
            1 for p in data if p not in referenced and p in pinned
        )
        if not live and len(doomed) == len(data):
            # nothing referenced or pinned survives in this version dir:
            # take the whole dir, sidecars included
            if data or listed:
                _move(root)
                report["quarantined_dirs"] += 1
            continue
        for p in doomed:
            _move(p)
            report["quarantined_files"] += 1

    _purge_quarantine(index_path, grace_ms, now, report)
    return report


def _purge_quarantine(
    index_path: str, grace_ms: int, now: int, report: Dict[str, object]
) -> None:
    """Phase 2: delete quarantine stamps older than the grace TTL."""
    quarantine_root = os.path.join(index_path, HYPERSPACE_QUARANTINE_DIR)
    if not os.path.isdir(quarantine_root):
        return
    for stamp in sorted(os.listdir(quarantine_root)):
        try:
            stamped_at = int(stamp)
        except ValueError:
            continue
        if stamped_at + grace_ms <= now:
            file_utils.delete(os.path.join(quarantine_root, stamp))
            report["purged_stamps"] += 1
    if not os.listdir(quarantine_root):
        file_utils.delete(quarantine_root)


def reap_spill_orphans(
    system_path: str,
    ttl_ms: int = SERVE_SPILL_ORPHAN_TTL_MS_DEFAULT,
    now: Optional[int] = None,
) -> Dict[str, int]:
    """Delete expired spill-tier leavings under
    ``<system_path>/_hyperspace_spill/`` (docs/out-of-core.md).

    Spill files are DERIVED state: every byte is reproducible from
    parquet, so the reaper deletes rather than quarantines — the
    ``gc_orphans`` move-then-grace dance exists to protect source-of-
    truth index data, which spill files never are. Three protections
    keep a live serve unharmed:

    * files a live in-process :class:`~hyperspace_tpu.execution\
.serve_cache.ServeCache` still indexes (``live_spill_paths()``) are
      never touched, mirroring the serve-pin exemption of
      :func:`gc_orphans`;
    * files younger than ``ttl_ms`` (``hyperspace.serve.spill\
.orphanTtlMs``) are kept — a sibling process's cache may index them,
      and a freshly published file is by definition younger than its
      writer's next eviction cycle;
    * deletion races are benign by construction: a restore that loses
      the race sees a vanished file and degrades to a cache miss.

    Torn ``.tmp_spool_*`` temps from a writer that died mid-publish
    (the ``mid_spill_write`` crash point) age out the same way.
    Idempotent; returns ``{"reaped": n, "kept_live": n, "kept_young":
    n}``.
    """
    from hyperspace_tpu.execution.serve_cache import live_spill_paths

    report = {"reaped": 0, "kept_live": 0, "kept_young": 0}
    spill_dir = os.path.join(system_path, HYPERSPACE_SPILL_DIR)
    if not os.path.isdir(spill_dir):
        return report
    now = now_ms() if now is None else now
    live = live_spill_paths()
    for name in sorted(os.listdir(spill_dir)):
        if not (name.endswith(".spill") or name.startswith(".tmp_spool_")):
            continue
        path = os.path.join(spill_dir, name)
        if path in live:
            report["kept_live"] += 1
            continue
        try:
            age_ms = now - int(os.path.getmtime(path) * 1000)
        except OSError:
            continue  # vanished under us — someone else reaped it
        if age_ms < ttl_ms:
            report["kept_young"] += 1
            continue
        try:
            file_utils.delete(path)
            report["reaped"] += 1
        except OSError:
            pass
    return report
