"""Index log entry — the versioned JSON metadata document.

Reference: ``index/IndexLogEntry.scala`` (703 LoC):

* ``FileInfo`` (:308-332) — (name, size, mtime, stable id)
* ``Directory`` (:123-303) — recursive file tree with ``merge``
* ``Content`` (:40-113) — a rooted ``Directory`` + helpers
* ``Hdfs``/``Update`` (:351-366) — source snapshot + quick-refresh delta
* ``Relation``/``SparkPlan``/``Source`` (:379-397) — provider-agnostic
  description of the indexed source
* ``LogicalPlanFingerprint``/``Signature`` (:335-343)
* ``IndexLogEntry`` (:408-590) — ties it all together + per-plan tag cache
* ``FileIdTracker`` (:627-703) — stable numeric id per (path,size,mtime)

The JSON layout is a faithful semantic port (field names are snake_case and
the Spark-plan string is replaced by our own relation description); the
polymorphic ``derivedDataset`` uses a ``"type"`` discriminator resolved via
the index registry (:mod:`hyperspace_tpu.indexes.registry`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.utils import paths as path_utils

LOG_VERSION = "0.1"

UNKNOWN_FILE_ID = -1


# ---------------------------------------------------------------------------
# FileInfo / Directory / Content
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FileInfo:
    """A leaf file: name (no directory), size, mtime (ms), stable id.

    Reference: IndexLogEntry.scala:308-332. Equality/hash ignore ``id`` as
    in the reference (id is assigned metadata, not identity).
    """

    name: str
    size: int
    modified_time: int
    id: int = UNKNOWN_FILE_ID

    def __eq__(self, other):
        return (
            isinstance(other, FileInfo)
            and self.name == other.name
            and self.size == other.size
            and self.modified_time == other.modified_time
        )

    def __hash__(self):
        return hash((self.name, self.size, self.modified_time))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "size": self.size,
            "modifiedTime": self.modified_time,
            "id": self.id,
        }

    @staticmethod
    def from_dict(d: dict) -> "FileInfo":
        return FileInfo(d["name"], d["size"], d["modifiedTime"], d.get("id", -1))


@dataclasses.dataclass
class Directory:
    """Recursive directory node (IndexLogEntry.scala:123-303)."""

    name: str
    files: List[FileInfo] = dataclasses.field(default_factory=list)
    subdirs: List["Directory"] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "files": [f.to_dict() for f in self.files],
            "subDirs": [d.to_dict() for d in self.subdirs],
        }

    @staticmethod
    def from_dict(d: dict) -> "Directory":
        return Directory(
            d["name"],
            [FileInfo.from_dict(f) for f in d.get("files", [])],
            [Directory.from_dict(s) for s in d.get("subDirs", [])],
        )

    # -- construction -------------------------------------------------------

    @staticmethod
    def _split_path(path: str) -> List[str]:
        """Directory components of ``path`` (excluding the file name).

        Scheme-qualified paths keep ``scheme://authority`` as the first
        component so object-store URIs round-trip unmangled.
        """
        if "://" in path:
            scheme, rest = path.split("://", 1)
            comps = [p for p in rest.split("/") if p]
            if not comps:
                return [scheme + "://"]
            return [f"{scheme}://{comps[0]}"] + comps[1:-1]
        return [p for p in path.split("/") if p][:-1]

    @staticmethod
    def from_leaf_files(files: Iterable[Tuple[str, FileInfo]]) -> "Directory":
        """Build the minimal tree containing ``(absolute_path, FileInfo)``.

        Mirrors ``Directory.fromLeafFiles`` (IndexLogEntry.scala:214-303):
        the root is the filesystem root ("/"), each path component becomes a
        nested Directory. ``scheme://authority`` prefixes become first-level
        nodes under the root.
        """
        root = Directory("/")
        for path, info in files:
            parts = Directory._split_path(path)
            node = root
            for part in parts:
                nxt = next((s for s in node.subdirs if s.name == part), None)
                if nxt is None:
                    nxt = Directory(part)
                    node.subdirs.append(nxt)
                node = nxt
            node.files.append(info)
        root._sort()
        return root

    def _sort(self) -> None:
        self.files.sort(key=lambda f: f.name)
        self.subdirs.sort(key=lambda d: d.name)
        for s in self.subdirs:
            s._sort()

    def merge(self, other: "Directory") -> "Directory":
        """Merge two trees rooted at the same name (IndexLogEntry.scala:149-171).

        Files are unioned (by (name,size,mtime) identity); ids from ``self``
        win on duplicates.
        """
        if self.name != other.name:
            raise HyperspaceException(
                f"Merging directories with different names: "
                f"{self.name!r} vs {other.name!r}"
            )
        seen = {}
        for f in list(self.files) + list(other.files):
            seen.setdefault((f.name, f.size, f.modified_time), f)
        merged_files = sorted(seen.values(), key=lambda f: f.name)
        by_name = {d.name: d for d in self.subdirs}
        merged_subdirs: List[Directory] = []
        other_names = set()
        for od in other.subdirs:
            other_names.add(od.name)
            if od.name in by_name:
                merged_subdirs.append(by_name[od.name].merge(od))
            else:
                merged_subdirs.append(od)
        for sd in self.subdirs:
            if sd.name not in other_names:
                merged_subdirs.append(sd)
        merged_subdirs.sort(key=lambda d: d.name)
        return Directory(self.name, merged_files, merged_subdirs)

    # -- traversal ----------------------------------------------------------

    def leaf_files(self, prefix: str = "") -> List[Tuple[str, FileInfo]]:
        if self.name == "/":
            base = prefix
        elif "://" in self.name:
            base = self.name  # scheme://authority node: no leading separator
        else:
            base = f"{prefix}/{self.name}"
        out = [(f"{base}/{f.name}", f) for f in self.files]
        for d in self.subdirs:
            out.extend(d.leaf_files(base))
        return out


@dataclasses.dataclass
class Content:
    """A rooted directory tree = the file set of an index version or source.

    Reference: IndexLogEntry.scala:40-113.
    """

    root: Directory

    def to_dict(self) -> dict:
        return {"root": self.root.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Content":
        return Content(Directory.from_dict(d["root"]))

    @staticmethod
    def from_leaf_files(
        files: Iterable[Tuple[str, int, int]],
        file_id_tracker: Optional["FileIdTracker"] = None,
    ) -> "Content":
        """files = (absolute_path, size, mtime_ms); ids via tracker if given."""
        pairs = []
        for p, size, mtime in files:
            p = p.replace("\\", "/")
            fid = (
                file_id_tracker.add_file(p, size, mtime)
                if file_id_tracker is not None
                else UNKNOWN_FILE_ID
            )
            pairs.append((p, FileInfo(p.rsplit("/", 1)[-1], size, mtime, fid)))
        return Content(Directory.from_leaf_files(pairs))

    @staticmethod
    def from_directory_scan(
        directory: str, file_id_tracker: Optional["FileIdTracker"] = None
    ) -> "Content":
        """Recursive listing of a real directory (Content.fromDirectory,
        IndexLogEntry.scala:86-96)."""
        from hyperspace_tpu.utils import files as file_utils

        listed = [
            t
            for t in file_utils.list_leaf_files(directory)
            if path_utils.is_data_path(t[0])
        ]
        return Content.from_leaf_files(listed, file_id_tracker)

    @property
    def files(self) -> List[str]:
        return [p for p, _ in self.root.leaf_files()]

    @property
    def file_infos(self) -> List[Tuple[str, FileInfo]]:
        return self.root.leaf_files()

    @property
    def size_in_bytes(self) -> int:
        return sum(f.size for _, f in self.root.leaf_files())

    def merge(self, other: "Content") -> "Content":
        return Content(self.root.merge(other.root))


# ---------------------------------------------------------------------------
# Source description
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Update:
    """Quick-refresh delta recorded in metadata (IndexLogEntry.scala:351)."""

    appended_files: Optional[Content] = None
    deleted_files: Optional[Content] = None

    def to_dict(self) -> dict:
        return {
            "appendedFiles": self.appended_files.to_dict()
            if self.appended_files
            else None,
            "deletedFiles": self.deleted_files.to_dict()
            if self.deleted_files
            else None,
        }

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["Update"]:
        if not d:
            return None
        return Update(
            Content.from_dict(d["appendedFiles"]) if d.get("appendedFiles") else None,
            Content.from_dict(d["deletedFiles"]) if d.get("deletedFiles") else None,
        )


@dataclasses.dataclass
class Relation:
    """Description of one indexed source relation.

    Reference: IndexLogEntry.scala:379-384 (rootPaths, Hdfs data w/ content
    + update, dataSchemaJson, fileFormat, options).
    """

    root_paths: List[str]
    content: Content                      # snapshot of source files at build
    schema_json: str                      # serialized arrow schema (JSON)
    file_format: str
    options: Dict[str, str] = dataclasses.field(default_factory=dict)
    update: Optional[Update] = None       # quick-refresh delta

    def to_dict(self) -> dict:
        return {
            "rootPaths": self.root_paths,
            "data": {
                "properties": {
                    "content": self.content.to_dict(),
                    "update": self.update.to_dict() if self.update else None,
                }
            },
            "dataSchemaJson": self.schema_json,
            "fileFormat": self.file_format,
            "options": dict(self.options),
        }

    @staticmethod
    def from_dict(d: dict) -> "Relation":
        props = d["data"]["properties"]
        return Relation(
            list(d["rootPaths"]),
            Content.from_dict(props["content"]),
            d["dataSchemaJson"],
            d["fileFormat"],
            dict(d.get("options", {})),
            Update.from_dict(props.get("update")),
        )


@dataclasses.dataclass
class SourcePlan:
    """Provider-agnostic stand-in for the reference's serialized SparkPlan
    (IndexLogEntry.scala:387-397): the list of leaf relations plus the
    source-provider name that produced them."""

    relations: List[Relation]
    provider: str = "default"

    def to_dict(self) -> dict:
        return {
            "relations": [r.to_dict() for r in self.relations],
            "provider": self.provider,
        }

    @staticmethod
    def from_dict(d: dict) -> "SourcePlan":
        return SourcePlan(
            [Relation.from_dict(r) for r in d["relations"]],
            d.get("provider", "default"),
        )


@dataclasses.dataclass(frozen=True)
class Signature:
    """(provider, value) plan fingerprint component (IndexLogEntry.scala:335)."""

    provider: str
    value: str

    def to_dict(self) -> dict:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_dict(d: dict) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclasses.dataclass
class LogicalPlanFingerprint:
    """Fingerprint of the source logical plan (IndexLogEntry.scala:338-343)."""

    signatures: List[Signature]
    kind: str = "LogicalPlan"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {"signatures": [s.to_dict() for s in self.signatures]},
        }

    @staticmethod
    def from_dict(d: dict) -> "LogicalPlanFingerprint":
        return LogicalPlanFingerprint(
            [Signature.from_dict(s) for s in d["properties"]["signatures"]],
            d.get("kind", "LogicalPlan"),
        )


@dataclasses.dataclass
class Source:
    plan: SourcePlan

    def to_dict(self) -> dict:
        return {"plan": self.plan.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "Source":
        return Source(SourcePlan.from_dict(d["plan"]))


# ---------------------------------------------------------------------------
# FileIdTracker
# ---------------------------------------------------------------------------


class FileIdTracker:
    """Stable numeric id per (path, size, mtime); basis of the lineage column.

    Reference: IndexLogEntry.scala:627-703. Ids never change for a given
    key; new keys get ``max_id + 1``.
    """

    def __init__(self):
        self._ids: Dict[Tuple[str, int, int], int] = {}
        self._max_id = UNKNOWN_FILE_ID

    @property
    def max_id(self) -> int:
        return self._max_id

    def add_file(self, path: str, size: int, mtime: int) -> int:
        key = (path, size, mtime)
        fid = self._ids.get(key)
        if fid is None:
            self._max_id += 1
            fid = self._max_id
            self._ids[key] = fid
        return fid

    def add_file_info(self, path: str, info: FileInfo) -> None:
        """Seed from a previous log entry's recorded ids
        (FileIdTracker.addFileInfo:657)."""
        if info.id == UNKNOWN_FILE_ID:
            raise HyperspaceException(f"File {path} has no id recorded")
        key = (path, info.size, info.modified_time)
        existing = self._ids.get(key)
        if existing is not None and existing != info.id:
            raise HyperspaceException(
                f"Conflicting ids for {key}: {existing} vs {info.id}"
            )
        self._ids[key] = info.id
        self._max_id = max(self._max_id, info.id)

    def get_file_id(self, path: str, size: int, mtime: int) -> Optional[int]:
        return self._ids.get((path, size, mtime))

    def id_to_file_mapping(self) -> List[Tuple[int, str]]:
        """(id, path) pairs (getIdToFileMapping:700) — the build-time
        broadcast table joined against input file names for lineage."""
        return [(fid, key[0]) for key, fid in self._ids.items()]


# ---------------------------------------------------------------------------
# LogEntry / IndexLogEntry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LogEntry:
    """Abstract base (LogEntry.scala:22-30): version, id, state, timestamp."""

    version: str = LOG_VERSION
    id: int = 0
    state: str = States.DOESNOTEXIST
    timestamp: int = dataclasses.field(
        default_factory=lambda: int(time.time() * 1000)
    )


class IndexLogEntry(LogEntry):
    """The full metadata document for one index version.

    Reference: IndexLogEntry.scala:408-590. ``derived_dataset`` is the
    polymorphic Index object (covering / z-order / data-skipping).
    """

    def __init__(
        self,
        name: str,
        derived_dataset,                    # indexes.base.Index
        content: Content,
        source: Source,
        fingerprint: LogicalPlanFingerprint,
        properties: Optional[Dict[str, str]] = None,
        state: str = States.DOESNOTEXIST,
        id: int = 0,
        timestamp: Optional[int] = None,
    ):
        super().__init__(
            LOG_VERSION,
            id,
            state,
            timestamp if timestamp is not None else int(time.time() * 1000),
        )
        self.name = name
        self.derived_dataset = derived_dataset
        self.content = content
        self.source = source
        self.fingerprint = fingerprint
        self.properties: Dict[str, str] = dict(properties or {})
        # Per-plan mutable tag cache (IndexLogEntry.scala:537-589). Keyed by
        # (plan_key, tag_name); never serialized.
        self._tags: Dict[Tuple[Any, str], Any] = {}

    # -- identity -----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, IndexLogEntry)
            and self.name == other.name
            and self.derived_dataset == other.derived_dataset
            and self.content.to_dict() == other.content.to_dict()
            and self.source.to_dict() == other.source.to_dict()
            and self.fingerprint.to_dict() == other.fingerprint.to_dict()
            and self.state == other.state
        )

    def __hash__(self):
        return hash((self.name, self.state, self.id))

    def __repr__(self):
        return (
            f"IndexLogEntry(name={self.name!r}, state={self.state}, id={self.id})"
        )

    # -- convenience --------------------------------------------------------
    @property
    def relations(self) -> List[Relation]:
        return self.source.plan.relations

    @property
    def relation(self) -> Relation:
        # Reference supports exactly one relation per index (CreateAction
        # validation); same here.
        return self.relations[0]

    @property
    def source_files_size_in_bytes(self) -> int:
        return self.relation.content.size_in_bytes

    def source_file_info_set(self) -> Dict[str, FileInfo]:
        """path -> FileInfo of the indexed source snapshot, with the quick-
        refresh Update applied (IndexLogEntry.sourceFileInfoSet)."""
        files = dict(self.relation.content.file_infos)
        if self.relation.update:
            upd = self.relation.update
            if upd.appended_files:
                files.update(dict(upd.appended_files.file_infos))
            if upd.deleted_files:
                for p, _ in upd.deleted_files.file_infos:
                    files.pop(p, None)
        return files

    @property
    def has_source_update(self) -> bool:
        """True when a quick refresh recorded a pending source delta
        (IndexLogEntry.hasSourceUpdate): the fingerprint matches the newer
        source but the index DATA still reflects the original snapshot, so
        serving requires Hybrid Scan compensation."""
        u = self.relation.update
        return u is not None and (
            u.appended_files is not None or u.deleted_files is not None
        )

    def file_id_tracker(self) -> FileIdTracker:
        """Rebuild the tracker from recorded source + index file ids."""
        t = FileIdTracker()
        for p, info in self.relation.content.file_infos:
            if info.id != UNKNOWN_FILE_ID:
                t.add_file_info(p, info)
        if self.relation.update and self.relation.update.appended_files:
            for p, info in self.relation.update.appended_files.file_infos:
                if info.id != UNKNOWN_FILE_ID:
                    t.add_file_info(p, info)
        return t

    def index_data_dir_id(self) -> int:
        """Latest ``v__=N`` version embedded in content paths."""
        from hyperspace_tpu.metadata.data_manager import version_from_path

        versions = [
            v
            for v in (version_from_path(p) for p in self.content.files)
            if v is not None
        ]
        return max(versions) if versions else 0

    def with_state(self, state: str) -> "IndexLogEntry":
        out = self.copy()
        out.state = state
        return out

    def copy(self) -> "IndexLogEntry":
        return IndexLogEntry.from_dict(self.to_dict())

    def copy_with_update(
        self, appended: Content, deleted: Content, fingerprint: LogicalPlanFingerprint
    ) -> "IndexLogEntry":
        """Quick refresh: record delta + new fingerprint without touching
        index data (IndexLogEntry.copyWithUpdate, used by RefreshQuickAction
        :70-79)."""
        out = self.copy()
        rel = out.relation
        prev = rel.update
        if prev:
            if prev.appended_files:
                appended = prev.appended_files.merge(appended)
            if prev.deleted_files:
                deleted = prev.deleted_files.merge(deleted)
        rel.update = Update(
            appended if appended.files else None, deleted if deleted.files else None
        )
        out.fingerprint = fingerprint
        return out

    # -- tags (IndexLogEntry.scala:537-589) ---------------------------------
    def set_tag(self, plan_key: Any, tag: str, value: Any) -> None:
        self._tags[(plan_key, tag)] = value

    def get_tag(self, plan_key: Any, tag: str) -> Optional[Any]:
        return self._tags.get((plan_key, tag))

    def unset_tag(self, plan_key: Any, tag: str) -> None:
        self._tags.pop((plan_key, tag), None)

    def collect_tag(self, tag: str) -> List[Tuple[Any, Any]]:
        """All (plan_key, value) pairs recorded under `tag` — the harvest
        side of the whyNot analysis (CandidateIndexAnalyzer reads the
        FILTER_REASONS tags written across plan nodes)."""
        return [(k, v) for (k, t), v in self._tags.items() if t == tag]

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "id": self.id,
            "state": self.state,
            "timestamp": self.timestamp,
            "name": self.name,
            "derivedDataset": self.derived_dataset.to_dict(),
            "content": self.content.to_dict(),
            "source": self.source.to_dict(),
            "fingerprint": self.fingerprint.to_dict(),
            "properties": dict(self.properties),
        }

    @staticmethod
    def from_dict(d: dict) -> "IndexLogEntry":
        from hyperspace_tpu.indexes.registry import index_from_dict

        entry = IndexLogEntry(
            name=d["name"],
            derived_dataset=index_from_dict(d["derivedDataset"]),
            content=Content.from_dict(d["content"]),
            source=Source.from_dict(d["source"]),
            fingerprint=LogicalPlanFingerprint.from_dict(d["fingerprint"]),
            properties=d.get("properties", {}),
            state=d["state"],
            id=d["id"],
            timestamp=d.get("timestamp"),
        )
        return entry
