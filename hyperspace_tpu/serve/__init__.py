"""Concurrent serve frontend (docs/serve-server.md) and the replicated
fleet member built on it (docs/fleet-serve.md).

The long-lived, many-queries-one-process plane over the single-query
engine: admission control (single-flight dedup + load shedding, per-
tenant SLO classes), snapshot-consistent index pinning, and
retry/degrade at the operation boundary — see
:mod:`hyperspace_tpu.serve.frontend`. In fleet mode
(``hyperspace.fleet.enabled``) the frontend becomes a
:class:`~hyperspace_tpu.serve.fleet.FleetFrontend`: durable cross-
process pins, index-version fanout over the bus
(:mod:`hyperspace_tpu.serve.bus`), and cross-process single-flight
through the claim/spool plane.
"""

from hyperspace_tpu.serve.frontend import ServeFrontend, plan_fingerprint


def __getattr__(name):
    # FleetFrontend lazily: most sessions never enter fleet mode, and
    # the fleet module pulls in the bus/spool machinery
    if name == "FleetFrontend":
        from hyperspace_tpu.serve.fleet import FleetFrontend

        return FleetFrontend
    raise AttributeError(name)


__all__ = ["ServeFrontend", "FleetFrontend", "plan_fingerprint"]
