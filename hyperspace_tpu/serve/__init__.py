"""Concurrent serve frontend (docs/serve-server.md).

The long-lived, many-queries-one-process plane over the single-query
engine: admission control (single-flight dedup + load shedding),
snapshot-consistent index pinning, and retry/degrade at the operation
boundary. See :mod:`hyperspace_tpu.serve.frontend`.
"""

from hyperspace_tpu.serve.frontend import ServeFrontend, plan_fingerprint

__all__ = ["ServeFrontend", "plan_fingerprint"]
