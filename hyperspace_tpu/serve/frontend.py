"""ServeFrontend — the concurrent serve plane (docs/serve-server.md).

Everything below this module executes ONE query; a process "serving
millions of users" is measured under contention. The frontend owns that
boundary:

* **Admission control.** Identical in-flight plans are deduplicated
  (single-flight by :func:`plan_fingerprint` + config version + pinned
  snapshot — N clients asking the same question cost one execution),
  and queries queued past ``hyperspace.serve.maxQueueDepth`` are shed
  with a typed :class:`ServeOverloadedError` at submit time, before any
  work is buffered.

* **Snapshot-consistent serving.** At admission each query pins the
  set of latestStable ACTIVE log entries (``metadata/log_manager.py``;
  one read, one consistent set) and the rewrite runs against that pin
  (``rules/apply.apply_hyperspace(entries=…)``) — a ``refresh`` /
  ``optimize`` / ``vacuum`` landing mid-query can never mix index
  versions inside one query. Index version file sets are immutable, so
  the pinned plan stays readable until a vacuum physically removes the
  old version — which surfaces as an I/O error and is healed by the
  retry below (re-pin + re-plan on the current snapshot). Each pin is
  also registered with the recovery plane
  (``metadata/recovery.register_pins``) for the life of the query, so
  orphan GC never quarantines a file a live serve still reads.

* **Retry / degrade at the operation boundary** (Exoshuffle doctrine:
  fault handling belongs in the application-level dataflow). TRANSIENT
  failures — real I/O errors, vacuumed-under-us files, or injected
  ``testing/faults.py`` faults — retry with exponential backoff
  (``hyperspace.serve.retry.*``), re-pinning the snapshot each attempt.
  PERSISTENT I/O failures of an index-rewritten query degrade to the
  unrewritten plan (serve straight from the source data — slower,
  bit-identical). Native-kernel faults never reach this module: every
  kernel dispatch degrades in place to its registered numpy/interpreted
  twin (``KERNEL_TWINS``, ``native.load``). Failing cache inserts are
  dropped in place (``ServeCache.insert_failures``). The result is the
  fault matrix the tests pin down: for every injection point ×
  {transient, persistent}, a serve either retries to a bit-identical
  result or degrades to an identical-output path — never a wrong
  answer, never a hung query.

Threading: queries run on the frontend's own pool (``hs-serve-*``).
Per-bucket parquet reads still go to the shared ``io/scan.scan_pool``
— serve workers BLOCK on scan futures, scan workers never block on
serve futures, so the two pools cannot deadlock (the scan pool's
documented discipline). One frontend lock guards admission state and
counters; nothing blocking and no I/O runs under it. The single-flight
map is SHARED_STATE-registered (``hyperspace_tpu/concurrency.py``,
hslint HS6xx audits every access; the runtime lock witness wraps
``_lock`` during the stress suites).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import List, Optional, Tuple

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import (
    HyperspaceException,
    ServeOverloadedError,
)
from hyperspace_tpu.metadata import recovery
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import planspec as obs_planspec
from hyperspace_tpu.obs import querylog as obs_querylog
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.testing.faults import InjectedFault


def plan_fingerprint(plan: LogicalPlan) -> Tuple:
    """Identity of a logical plan for single-flight purposes: the node
    structure (``repr`` covers operators, conditions, projections) plus
    each leaf relation's concrete file snapshot — two scans of the same
    directory at different snapshots must not coalesce."""
    leaves = tuple(
        (
            leaf.relation.files,
            leaf.relation.fmt,
            leaf.relation.excluded_file_ids,
            leaf.relation.options,
        )
        for leaf in plan.collect_leaves()
    )
    return (repr(plan), leaves)


class _SloClass:
    """Per-tenant admission state (``hyperspace.fleet.class.<name>.*``,
    docs/fleet-serve.md): ``max_concurrency`` caps how many class
    queries RUN at once (excess admissions wait in ``pending`` without
    occupying a worker thread), ``max_queue_depth`` sheds past that
    backlog — both 0 = unlimited. Mutated only under the frontend
    lock."""

    __slots__ = (
        "name",
        "max_concurrency",
        "max_queue_depth",
        "running",
        "pending",
        "admitted",
        "shed",
    )

    def __init__(self, name: str, max_concurrency: int, max_queue_depth: int):
        self.name = name
        self.max_concurrency = max_concurrency
        self.max_queue_depth = max_queue_depth
        self.running = 0
        self.pending: deque = deque()
        self.admitted = 0
        self.shed = 0

    def has_slot(self) -> bool:
        return self.max_concurrency <= 0 or self.running < self.max_concurrency


def _chain_future(inner: Future, outer: Future) -> None:
    """Propagate ``inner``'s outcome onto the caller-visible ``outer``
    (deferred SLO-class dispatch hands out ``outer`` at submit time)."""

    def _done(f: Future) -> None:
        exc = f.exception()
        if exc is not None:
            outer.set_exception(exc)
        else:
            outer.set_result(f.result())

    inner.add_done_callback(_done)


def _is_transient(exc: BaseException) -> bool:
    """Retryable? Injected faults carry the answer; every real OSError
    (missing file after a concurrent vacuum, flaky storage, Arrow I/O
    errors — OSError subclasses in pyarrow) is worth the retry budget.
    Engine errors (HyperspaceException et al.) are deterministic and
    retry would just repeat them."""
    if isinstance(exc, InjectedFault):
        return exc.transient
    return isinstance(exc, OSError)


class ServeFrontend:
    """Long-lived concurrent query frontend over one session.

    Usage (also ``session.serve_frontend`` for a shared instance)::

        fe = session.serve_frontend
        table = fe.serve(df)             # blocking
        fut = fe.submit(df)              # Future[pyarrow.Table]

    Results are shared between deduplicated callers — pyarrow Tables
    are immutable, so sharing is safe.
    """

    def __init__(self, session):
        self._session = session
        self._max_queue = session.conf.serve_max_queue_depth
        self.max_concurrency = session.conf.serve_max_concurrency
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_concurrency,
            thread_name_prefix="hs-serve",
        )
        self._lock = threading.Lock()
        self._inflight: dict = {}
        self._queued = 0
        self._closed = False
        # per-tenant SLO classes, frozen at construction like the pool
        # size (docs/fleet-serve.md); unknown class names see only the
        # global bounds
        self._slo_classes = {
            name: _SloClass(name, caps[0], caps[1])
            for name, caps in session.conf.fleet_slo_classes.items()
        }
        # counters (read via stats(); all mutated under _lock)
        self._admitted = 0
        self._completed = 0
        self._deduped = 0
        self._shed = 0
        self._retries = 0
        self._degraded = 0
        self._degraded_pins = 0
        self._failed = 0
        self._latencies: deque = deque(maxlen=4096)
        # observability plane (docs/observability.md): adopt the
        # session's hyperspace.obs.* settings (process-global,
        # last-writer-wins — the telemetry doctrine), open the durable
        # query log next to the lake, and export stats() as a live
        # registry view. All three are no-ops/None with obs off.
        self._obs_enabled = obs_trace.configure(session.conf)
        self._querylog = None
        if self._obs_enabled and session.conf.obs_querylog_enabled:
            self._querylog = obs_querylog.QueryLog(
                obs_querylog.obs_root(session.conf),
                max_bytes=session.conf.obs_querylog_max_bytes,
                max_files=session.conf.obs_querylog_max_files,
            )
        self._stats_view = obs_metrics.registry.register_weak_view(
            "serve_frontend", self
        )

    # -- snapshot pinning ---------------------------------------------------
    def _pin(self) -> Optional[Tuple]:
        """The latestStable ACTIVE entries, captured once — the query's
        index snapshot. Transient log-read failures retry inline with
        the serve backoff; a persistent failure degrades to pin=None
        (serve without indexes: correct, slower), because a dead
        metadata store must not take query serving down with it."""
        session = self._session
        if not session.is_hyperspace_enabled() or not session.conf.apply_enabled:
            return ()
        attempts = session.conf.serve_retry_max_attempts
        backoff = session.conf.serve_retry_backoff_ms / 1000.0
        for attempt in range(attempts):
            try:
                with obs_trace.span("pin"):
                    return tuple(
                        session.index_manager.get_indexes([States.ACTIVE])
                    )
            # catch-all IS the contract: pin failure of any shape must
            # degrade to serving without indexes, never fail the query
            except Exception as exc:  # hslint: disable=HS402
                if not _is_transient(exc) or attempt + 1 >= attempts:
                    with self._lock:
                        self._degraded_pins += 1
                    return None
                with self._lock:
                    self._retries += 1
                if backoff > 0:
                    time.sleep(backoff * (1 << attempt))
        return None

    def _register_pins(self, pin: Optional[Tuple]) -> int:
        """Record the pinned snapshot with the recovery plane. The fleet
        frontend (``serve/fleet.py``) overrides this to ALSO publish a
        lease-expiring durable pin file per index, so a GC or vacuum in
        another process sees the pin too."""
        return recovery.register_pins(pin)

    # -- admission ----------------------------------------------------------
    def submit(self, query, slo_class: Optional[str] = None) -> Future:
        """Admit one query (DataFrame or LogicalPlan). Returns a Future
        resolving to the pyarrow Table. Raises
        :class:`ServeOverloadedError` when the pending queue is full —
        nothing is buffered for a shed query.

        ``slo_class`` names a per-tenant admission class
        (``hyperspace.fleet.class.<name>.*``): class queries past the
        class ``maxQueueDepth`` shed BEFORE the global bound bites, and
        at most ``maxConcurrency`` of them run at once — excess
        admissions wait without occupying a worker thread, so a greedy
        batch tier cannot starve the interactive tier's workers. An
        unconfigured (or None) class sees only the global bounds."""
        plan = getattr(query, "logical_plan", query)
        if not isinstance(plan, LogicalPlan):
            raise HyperspaceException(
                f"serve() takes a DataFrame or LogicalPlan, got {type(query)}"
            )
        cls = self._slo_classes.get(slo_class) if slo_class else None
        # shed BEFORE pinning: an overloaded frontend must reject in
        # O(1) with no metadata I/O and no backoff sleeps on the caller
        # thread — that cheap typed rejection is the whole point of the
        # bound. The cost is that a shed query never gets the chance to
        # dedup onto an in-flight twin; under overload that trade is
        # the documented contract. Depth is re-checked at enqueue (the
        # pin read dropped the lock in between).
        with self._lock:
            self._check_admittable_locked(cls)
        # the query ROOT span starts HERE so queue-wait is on the trace;
        # a query that dedups onto an in-flight twin abandons it
        # unfinished (one root span per EXECUTION is the contract —
        # deduped submits share the winner's execution and its trace)
        root = obs_trace.root("serve.query", slo_class=slo_class)
        with obs_trace.activate(root):
            pin = self._pin()
        # register the pinned snapshot's files with the recovery plane:
        # orphan GC (metadata/recovery.gc_orphans) never quarantines a
        # pinned file, so a version that goes unreferenced mid-query
        # stays readable until the query releases it (_run's finally)
        pin_token = self._register_pins(pin)
        fp = (
            plan_fingerprint(plan),
            self._session.conf.version,
            None
            if pin is None
            else tuple((e.name, e.id) for e in pin),
        )
        if root.span_id is not None:
            import hashlib

            root.set(
                "fingerprint",
                hashlib.sha256(repr(fp).encode("utf-8")).hexdigest()[:16],
            )
            root.set("predicate", obs_querylog.predicate_shape(plan))
            if self._session.conf.obs_querylog_record_plans:
                # opt-in: specs carry literals (obs/planspec.py doctrine)
                spec = obs_planspec.to_spec(plan)
                if spec is not None:
                    root.set("replay", spec)
        try:
            with self._lock:
                existing = self._inflight.get(fp)
                if existing is not None:
                    self._deduped += 1
                    recovery.release_pins(pin_token)
                    return existing
                self._check_admittable_locked(cls)
                self._queued += 1
                self._admitted += 1
                if cls is not None:
                    cls.admitted += 1
                if cls is None or cls.has_slot():
                    if cls is not None:
                        cls.running += 1
                    fut = self._pool.submit(
                        self._run, plan, pin, pin_token, cls, root
                    )
                else:
                    # class concurrency cap reached: park the admission;
                    # a finishing class query dispatches it (the caller
                    # holds this outer future either way)
                    fut = Future()
                    cls.pending.append((plan, pin, pin_token, fut, root))
                self._inflight[fp] = fut
        except BaseException:
            recovery.release_pins(pin_token)
            raise
        fut.add_done_callback(lambda _f, fp=fp: self._forget(fp))
        return fut

    def _fleet_class_depth_locked(self, cls: _SloClass) -> int:
        """Peers' contribution to this class's queue depth (called with
        the lock held). The single-process frontend has no peers;
        ``FleetFrontend`` overrides this with gossiped live depths so a
        class bound is enforced FLEET-wide, not per-process."""
        return 0

    def _check_admittable_locked(self, cls: Optional[_SloClass] = None) -> None:
        """Raise unless a new query may enter (call with the lock held).
        The class bound is checked FIRST: a tenant over its own budget
        sheds with its class named, before it can pressure the global
        queue every other tenant shares."""
        if self._closed:
            raise HyperspaceException("ServeFrontend is closed")
        if cls is not None and cls.max_queue_depth > 0:
            fleet_depth = self._fleet_class_depth_locked(cls)
            if (
                len(cls.pending) + cls.running + fleet_depth
                >= cls.max_queue_depth
            ):
                cls.shed += 1
                self._shed += 1
                raise ServeOverloadedError(
                    f"SLO class {cls.name!r} queue full ({cls.running} "
                    f"running + {len(cls.pending)} pending + {fleet_depth} "
                    f"fleet >= maxQueueDepth {cls.max_queue_depth}); shedding"
                )
        if self._max_queue > 0 and self._queued >= self._max_queue:
            self._shed += 1
            raise ServeOverloadedError(
                f"serve queue full ({self._queued} pending >= "
                f"maxQueueDepth {self._max_queue}); shedding"
            )

    def _dispatch_pending_locked(self, cls: _SloClass) -> List[int]:
        """Hand parked class admissions to the pool while slots are free
        (call with the lock held). Returns the pin tokens of CANCELLED
        parked admissions — the caller releases them outside the lock
        (pin release is file I/O in fleet mode)."""
        cancelled: List[int] = []
        while cls.pending and cls.has_slot():
            plan, pin, pin_token, outer, root = cls.pending.popleft()
            # a parked outer future is a bare Future the caller may have
            # cancelled; claim it (RUNNING blocks further cancellation)
            # or drop the admission — a cancelled query must neither
            # ghost-execute nor leak its pin
            if not outer.set_running_or_notify_cancel():
                cancelled.append(pin_token)
                self._queued -= 1
                continue
            cls.running += 1
            inner = self._pool.submit(
                self._run, plan, pin, pin_token, cls, root
            )
            _chain_future(inner, outer)
        return cancelled

    def serve(self, query, slo_class: Optional[str] = None):
        """Blocking convenience: submit and wait."""
        return self.submit(query, slo_class=slo_class).result()

    def _forget(self, fp) -> None:
        with self._lock:
            self._inflight.pop(fp, None)

    # -- execution ----------------------------------------------------------
    def _execute_pinned(self, plan: LogicalPlan, pin: Optional[Tuple]):
        from hyperspace_tpu.execution import execute
        from hyperspace_tpu.rules.apply import apply_hyperspace

        session = self._session
        optimized = plan
        if pin:
            with obs_trace.span("rewrite"):
                optimized = apply_hyperspace(session, plan, entries=list(pin))
            cur = obs_trace.current()
            if cur is not None:
                cur.root.set(
                    "indexes", obs_querylog.indexes_in_plan(optimized)
                )
                cur.root.set("rule", obs_querylog.rule_flavor(plan))
        with obs_trace.span("execute"):
            return execute(optimized, session)

    def _run(
        self,
        plan: LogicalPlan,
        pin: Optional[Tuple],
        pin_token: int,
        cls: Optional[_SloClass] = None,
        root=obs_trace.NOOP,
    ):
        with self._lock:
            self._queued -= 1
        with obs_trace.activate(root):
            if root.span_id is not None:
                # admission -> worker pickup, on the root's own clock
                obs_trace.stage("queue_wait", root._t0)
            try:
                out = self._run_attempts(plan, pin, pin_token, cls, root)
                if root.span_id is not None:
                    root.set("status", "ok")
                    root.set("rows_returned", int(out.num_rows))
                    self._querylog_append(root)
                return out
            except BaseException:
                if root.span_id is not None:
                    root.set("status", "failed")
                    root.set("rows_returned", 0)
                    self._querylog_append(root)
                raise
            finally:
                root.finish()

    def _run_attempts(
        self,
        plan: LogicalPlan,
        pin: Optional[Tuple],
        pin_token: int,
        cls: Optional[_SloClass],
        root,
    ):
        session = self._session
        attempts = session.conf.serve_retry_max_attempts
        backoff = session.conf.serve_retry_backoff_ms / 1000.0
        t_start = time.perf_counter()
        attempt = 1
        try:
            while True:
                try:
                    out = self._execute_pinned(plan, pin)
                    self._record(t_start)
                    return out
                except Exception as exc:  # classified below; always re-raised
                    if _is_transient(exc) and attempt < attempts:
                        attempt += 1
                        with self._lock:
                            self._retries += 1
                        root.add_event(
                            "retry", attempt=attempt, error=str(exc)[:200]
                        )
                        if backoff > 0:
                            time.sleep(backoff * (1 << (attempt - 2)))
                        # re-pin: a vacuum may have removed the pinned
                        # version's files; the current snapshot serves.
                        # Swap the GC pin along with it.
                        recovery.release_pins(pin_token)
                        pin = self._pin()
                        pin_token = self._register_pins(pin)
                        continue
                    if isinstance(exc, OSError) and pin:
                        # persistent I/O failure of the index-rewritten
                        # query: degrade to the unrewritten plan (source
                        # data; bit-identical result — the covering-index
                        # equivalence the differential suite guarantees)
                        with self._lock:
                            self._degraded += 1
                        root.add_event("degrade", error=str(exc)[:200])
                        try:
                            out = self._execute_pinned(plan, ())
                        except Exception:
                            with self._lock:
                                self._failed += 1
                            raise exc from None
                        self._record(t_start)
                        return out
                    with self._lock:
                        self._failed += 1
                    raise
        finally:
            recovery.release_pins(pin_token)
            if cls is not None:
                with self._lock:
                    cls.running -= 1
                    dropped = self._dispatch_pending_locked(cls)
                for token in dropped:
                    recovery.release_pins(token)

    def _querylog_append(self, root) -> None:
        """One record per executed query (docs/observability.md schema;
        best-effort — an unwritable sidecar never fails the query)."""
        if self._querylog is None:
            return
        rec = {
            "ts_ms": root.start_ms,
            "trace_id": root.trace_id,
            "fingerprint": root.attrs.get("fingerprint", ""),
            "predicate": root.attrs.get("predicate", ""),
            "slo_class": root.attrs.get("slo_class"),
            "indexes": root.attrs.get("indexes", []),
            "rule": root.attrs.get("rule"),
            "duration_s": time.perf_counter() - root._t0,
            "stages": {
                k: round(v, 6) for k, v in root.stage_seconds().items()
            },
            "rows_returned": root.attrs.get("rows_returned", 0),
            # per-execution delta accumulated by the pruning pass onto
            # THIS root (obs_trace.accumulate) — never a module-global
            # read that a concurrent query could have overwritten
            "rows_pruned": int(root.attrs.get("rows_pruned", 0)),
            "events": [
                {k: v for k, v in ev.items()}
                for ev in root.events[-32:]
            ],
            "status": root.attrs.get("status", "ok"),
        }
        spec = root.attrs.get("replay")
        if spec is not None:
            rec["replay"] = spec
        self._querylog.append(rec)

    def _record(self, t_start: float) -> None:
        dt = time.perf_counter() - t_start
        with self._lock:
            self._completed += 1
            self._latencies.append(dt)

    # -- introspection / lifecycle ------------------------------------------
    def stats(self) -> dict:
        """One consistent snapshot of the frontend counters, plus p50/p99
        over the most recent completions (seconds). ``snapshot_at_ms``
        stamps WHEN — merge several frontends'/processes' snapshots
        with ``obs.merge_snapshots`` (it sums counters, maxes
        watermarks, drops percentiles), never by hand."""
        with self._lock:
            lat: List[float] = sorted(self._latencies)
            out = {
                "snapshot_at_ms": int(time.time() * 1000),
                "admitted": self._admitted,
                "completed": self._completed,
                "deduped": self._deduped,
                "shed": self._shed,
                "retries": self._retries,
                "degraded": self._degraded,
                "degraded_pins": self._degraded_pins,
                "failed": self._failed,
                "queued": self._queued,
                "inflight": len(self._inflight),
                "max_concurrency": self.max_concurrency,
            }
            if self._slo_classes:
                out["slo_classes"] = {
                    name: {
                        "admitted": cls.admitted,
                        "shed": cls.shed,
                        "running": cls.running,
                        "pending": len(cls.pending),
                        "max_concurrency": cls.max_concurrency,
                        "max_queue_depth": cls.max_queue_depth,
                    }
                    for name, cls in self._slo_classes.items()
                }
        if lat:
            out["p50_s"] = lat[len(lat) // 2]
            out["p99_s"] = lat[min(len(lat) - 1, (len(lat) * 99) // 100)]
        return out

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self, wait: bool = True) -> None:
        with self._lock:
            self._closed = True
            parked = [
                item for cls in self._slo_classes.values() for item in cls.pending
            ]
            for cls in self._slo_classes.values():
                cls.pending.clear()
        # parked class admissions can never dispatch once closed: fail
        # their futures and release their pins OUTSIDE the lock (a
        # caller-cancelled future takes no exception — the cancel
        # already resolved it)
        for _plan, _pin, pin_token, outer, _root in parked:
            recovery.release_pins(pin_token)
            if outer.set_running_or_notify_cancel():
                outer.set_exception(
                    HyperspaceException("ServeFrontend closed while queued")
                )
        self._pool.shutdown(wait=wait)
        if self._querylog is not None:
            self._querylog.close()
        # provider-matched: closing an OLD frontend must not tear down
        # a newer live frontend's view (last-wins registration)
        obs_metrics.registry.unregister_view(
            "serve_frontend", self._stats_view
        )

    def __enter__(self) -> "ServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
