"""Fast data plane transport — per-host push over Unix domain sockets.

The durable fleet planes (``serve/bus.py``, the claim/spool single-
flight in ``serve/fleet.py``) coordinate through files and polling:
always correct, kill -9 clean, but a fanout event waits a full
``fleet.bus.pollMs`` and every single-flight loser rides the claim
election plus an fsync'd Arrow spool round-trip. This module is the
microsecond path UNDER the same contracts (Exoshuffle's shape: the
durable plane stays the recovery substrate, the fast path is layered
above it and allowed to drop anything):

* **Framing.** One message per connection: an 8-byte length prefix pair
  (JSON header bytes, binary body bytes), then the frames. Results
  travel as Arrow IPC streams in the body — the same encoding the spool
  uses, so a fast handoff and a spool read decode identically.
* **Push** (:func:`push`) is fire-and-forget: a failed connect or send
  returns False and the durable plane delivers the same information a
  poll interval later (every fast message is idempotently replayable by
  construction — receivers key everything by snapshot fingerprints or
  bus event names).
* **Request** (:func:`request`) is one round trip with a deadline; any
  failure raises ``OSError`` and the caller falls back to the claim/
  spool election. The requester-side send seam carries the
  ``fastbus_send`` fault point (``testing/faults.py``), so the fault
  matrix can prove the fallback is bit-identical.
* **Serve** (:class:`FastBusServer`) binds a short socket path under the
  system temp dir — UDS paths are limited to ~100 bytes on Linux, so
  binding under a deep lake path is not safe; the member lease file
  (``serve/router.py``) carries the path to peers instead — and hands
  each message to a small handler pool.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import tempfile
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils

_log = logging.getLogger("hyperspace_tpu.fleet.fastbus")

#: (header length, body length) prefix — big-endian, fixed width
_FRAME = struct.Struct(">II")

#: defensive bound on either frame length: a torn/hostile peer must cost
#: one dropped connection, not an attempted multi-GiB allocation
_MAX_FRAME = 1 << 30


def socket_path() -> str:
    """A fresh, SHORT socket path under the system temp dir (never under
    the lake — pytest tmp dirs routinely exceed the ~100-byte UDS
    limit). The router's member file records it for peers."""
    return os.path.join(
        tempfile.gettempdir(),
        f"hsfb-{os.getpid()}-{uuid.uuid4().hex[:8]}.sock",
    )


# -- Arrow payload codec (identical to the spool encoding) -------------------


def table_to_bytes(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue().to_pybytes()


def table_from_bytes(data: bytes) -> pa.Table:
    return pa.ipc.open_stream(pa.py_buffer(data)).read_all()


# -- framing -----------------------------------------------------------------


def _send_frame(sock: socket.socket, header: Dict, body: bytes = b"") -> None:
    hdr = json.dumps(header).encode("utf-8")
    sock.sendall(_FRAME.pack(len(hdr), len(body)) + hdr + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("fastbus peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> Tuple[Dict, bytes]:
    hdr_len, body_len = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
    if hdr_len > _MAX_FRAME or body_len > _MAX_FRAME:
        raise ConnectionError(
            f"fastbus frame too large ({hdr_len}/{body_len} bytes)"
        )
    header = json.loads(_recv_exact(sock, hdr_len).decode("utf-8"))
    body = _recv_exact(sock, body_len) if body_len else b""
    return header, body


# -- client side -------------------------------------------------------------


def push(
    sock_path: str, header: Dict, body: bytes = b"", timeout_s: float = 0.5
) -> bool:
    """Fire-and-forget delivery of one message. Returns True when the
    frames were handed to the kernel, False on any socket failure — the
    durable plane is the retransmit. The armed ``fastbus_send`` fault
    raises out of here (an ``OSError`` the caller's degrade contract
    handles exactly like a dead peer)."""
    faults.check("fastbus_send", sock_path)
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(timeout_s)
            s.connect(sock_path)
            _send_frame(s, header, body)
        return True
    except OSError:
        return False


def request(
    sock_path: str,
    header: Dict,
    body: bytes = b"",
    timeout_s: float = 2.0,
) -> Tuple[Dict, bytes]:
    """One round trip: send a message, wait for the reply frame. Raises
    ``OSError`` on connect/send/receive failure or deadline — callers
    fall back to the durable plane (``serve/fleet.py`` counts it). The
    armed ``fastbus_send`` fault fires here too."""
    faults.check("fastbus_send", sock_path)
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout_s)
        s.connect(sock_path)
        _send_frame(s, header, body)
        return _recv_frame(s)


# -- server side -------------------------------------------------------------


class FastBusServer:
    """Accept loop + handler pool over one Unix socket.

    ``handler(header, body)`` returns ``(reply_header, reply_body)`` for
    request messages or ``None`` for one-way pushes. Handler exceptions
    are contained per connection — the fast plane is an optimization; a
    poisoned message costs one dropped connection, never the listener.
    """

    def __init__(
        self,
        handler: Callable[[Dict, bytes], Optional[Tuple[Dict, bytes]]],
        workers: int = 4,
    ):
        self._handler = handler
        self.path = socket_path()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(128)
        # the accept timeout is a SHUTDOWN poll, not a data-plane poll:
        # messages are dispatched the instant accept() returns
        self._sock.settimeout(0.2)
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers), thread_name_prefix="hs-fastbus"
        )
        self._thread = threading.Thread(
            target=self._accept_loop, name="hs-fastbus-accept", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # closed under us during stop()
            try:
                self._pool.submit(self._serve_conn, conn)
            except RuntimeError:
                conn.close()  # pool already shut down

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            header, body = _recv_frame(conn)
            reply = self._handler(header, body)
            if reply is not None:
                _send_frame(conn, reply[0], reply[1])
        except Exception as exc:  # hslint: disable=HS402
            # contain by contract (see class doc): requester timeouts
            # already cover a lost reply with the durable fallback
            _log.debug("fastbus connection failed: %s", exc)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        """Stop accepting, drain the handler pool, unlink the socket
        file (a clean member leaves nothing on disk)."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        self._pool.shutdown(wait=False)
        file_utils.delete(self.path)
