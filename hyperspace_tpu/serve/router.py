"""Owner routing for the fleet fast plane — membership + rendezvous.

The durable single-flight plane (``serve/fleet.py``) is an ELECTION:
every process races an atomic claim file, losers poll a spool. This
module removes the race for same-host peers: each fleet frontend
announces its fast-bus socket in a lease-expiring member file under
``<system.path>/_hyperspace_fleet/members/`` (the same lease
discriminator as writer and pin leases — a member that stops renewing
is dead and gets reaped, file and socket both), and plan digests are
rendezvous-hashed over the live member set so every process
independently agrees on ONE owner per digest. Single-flight then
becomes a direct send: the owner executes (or serves its in-memory
result cache) and streams the Arrow result straight back — no claim
file, no fsync'd spool round-trip. The durable planes stay underneath
as the always-correct fallback: a dead owner costs one failed connect
and a claim-election retry, never a wrong answer, and the spool still
receives every owner-side result (asynchronously) for cross-host peers
and crash recovery.

The router also carries the plane's one-way traffic: index-version
fanout pushes (``push_event_to_members``, called by the lifecycle
publisher next to its durable bus write), single-flight result-ready
wakeups, and per-class queue-depth gossip for fleet-wide SLO
enforcement. All of it is droppable by design.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from hyperspace_tpu.serve import bus as fleet_bus
from hyperspace_tpu.serve import fastbus
from hyperspace_tpu.utils import files as file_utils

_log = logging.getLogger("hyperspace_tpu.fleet.router")

#: member listings are cached this long — owner routing must not list a
#: directory per query (that would be the polling tax coming back)
_MEMBERS_CACHE_S = 0.25


def members_dir(conf) -> str:
    return os.path.join(fleet_bus.fleet_root(conf), "members")


def read_members(directory: str, now_ms: Optional[int] = None) -> Dict[str, Dict]:
    """``{owner: {"sock", "pid", "expiresAtMs"}}`` for every member file
    whose lease has not expired. Torn/unreadable files are skipped (the
    writer is mid-replace, or the member just got reaped)."""
    now = int(time.time() * 1000) if now_ms is None else now_ms
    out: Dict[str, Dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(
                os.path.join(directory, name), "r", encoding="utf-8"
            ) as fh:
                doc = json.load(fh)
            if int(doc["expiresAtMs"]) > now and doc.get("sock"):
                out[str(doc["owner"])] = doc
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def rendezvous_owner(owners, digest: str) -> Optional[str]:
    """Highest-random-weight choice: every process hashing the same
    member set picks the same owner, and a membership change only moves
    the digests that hashed to the lost/gained member."""
    best, best_score = None, b""
    for owner in owners:
        score = hashlib.sha256(f"{owner}:{digest}".encode("utf-8")).digest()
        if best is None or score > best_score:
            best, best_score = owner, score
    return best


def reap_members(
    directory: str, force_dead: bool = False
) -> Tuple[int, list]:
    """Reap expired member files and their socket files. With
    ``force_dead`` (same-host callers only — the harness's convergence
    check), a member whose pid no longer exists is reaped regardless of
    lease, the way a GC after the rung must not wait out a generous
    lease. Returns ``(reaped, leftover_paths)`` where leftovers are
    member or socket files that SHOULD be gone but survived."""
    now = int(time.time() * 1000)
    reaped = 0
    leftovers: list = []
    try:
        names = os.listdir(directory)
    except OSError:
        return 0, []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            expired = int(doc.get("expiresAtMs", 0)) <= now
            pid = int(doc.get("pid", 0))
        except (OSError, ValueError, TypeError):
            # torn or vanished: treat as expired garbage
            doc, expired, pid = {}, True, 0
        dead = False
        if force_dead and pid > 0:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                dead = True
            except OSError:
                pass
        if not (expired or dead):
            continue
        file_utils.delete(path)
        sock = doc.get("sock")
        if sock:
            file_utils.delete(sock)
            if os.path.exists(sock):
                leftovers.append(sock)
        if os.path.exists(path):
            leftovers.append(path)
        reaped += 1
    return reaped, leftovers


def push_event_to_members(conf, event: Dict) -> int:
    """Best-effort fast fanout of one (already durably published) bus
    event to every live member's socket; returns deliveries. Called by
    the lifecycle publisher (``serve/bus.publish_action_event``) right
    after its durable write — a member the push misses sees the same
    event at its next poll, keyed by the same bus file name, so the two
    planes can never double-apply."""
    delivered = 0
    for _owner, doc in read_members(members_dir(conf)).items():
        try:
            if fastbus.push(doc["sock"], {"type": "event", "event": event}):
                delivered += 1
        except OSError:
            # an armed fastbus_send fault (or any send failure) degrades
            # to durable-poll delivery — the push is an optimization
            continue
    return delivered


class FleetRouter:
    """One frontend's membership + routing handle on the fast plane.

    Owns the member lease file, the fast-bus server, and the ONE
    maintenance thread (lease renewal, gossip push, expired-member
    reaping). ``handler`` receives every inbound message
    (``serve/fleet.py`` dispatches by header type). Raises ``OSError``
    at construction when the plane cannot come up (unwritable members
    dir, socket bind failure) — the caller degrades to durable-only.
    """

    def __init__(
        self,
        conf,
        owner: str,
        handler: Callable[[Dict, bytes], Optional[Tuple[Dict, bytes]]],
    ):
        self.owner = owner
        self._dir = members_dir(conf)
        self._lease_ms = conf.fleet_fast_member_lease_ms
        self._gossip_s = conf.fleet_fast_gossip_ms / 1000.0
        self._gossip_source: Optional[Callable[[], Dict]] = None
        self._server = fastbus.FastBusServer(handler)
        self._member_path = os.path.join(self._dir, f"{owner}.json")
        os.makedirs(self._dir, exist_ok=True)
        # telemetry — single-writer (maintenance thread) except
        # push_sent, which request/push callers bump under _tel_lock
        self._tel_lock = threading.Lock()
        self.gossip_sent = 0
        self.push_sent = 0
        self.members_reaped = 0
        self._members_cache: Tuple[float, Dict[str, Dict]] = (0.0, {})
        self._renew()  # listed before the first query routes
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hs-fleet-router", daemon=True
        )
        self._thread.start()

    # -- membership ----------------------------------------------------------
    def _renew(self) -> None:
        file_utils.atomic_overwrite(
            self._member_path,
            json.dumps(
                {
                    "owner": self.owner,
                    "pid": os.getpid(),
                    "sock": self._server.path,
                    "expiresAtMs": int(time.time() * 1000) + self._lease_ms,
                }
            ),
        )

    def members(self, refresh: bool = False) -> Dict[str, Dict]:
        """The live member set (cached ~250ms — routing must not pay a
        directory listing per query)."""
        now = time.monotonic()
        ts, cached = self._members_cache
        if not refresh and now - ts < _MEMBERS_CACHE_S:
            return cached
        fresh = read_members(self._dir)
        self._members_cache = (now, fresh)
        return fresh

    def owner_of(self, digest: str) -> Optional[Tuple[str, str]]:
        """``(owner, sock_path)`` this digest routes to, or None when
        membership is unreadable/empty."""
        mem = self.members()
        winner = rendezvous_owner(mem.keys(), digest)
        if winner is None:
            return None
        return winner, mem[winner]["sock"]

    # -- one-way traffic -----------------------------------------------------
    def push_to_peers(self, header: Dict, body: bytes = b"") -> int:
        """Push one message to every live member except self; returns
        deliveries (failures are the durable plane's problem)."""
        delivered = 0
        for owner, doc in self.members().items():
            if owner == self.owner:
                continue
            try:
                if fastbus.push(doc["sock"], header, body):
                    delivered += 1
            except OSError:
                continue  # armed fault / dead peer: durable plane covers
        if delivered:
            with self._tel_lock:
                self.push_sent += delivered
        return delivered

    def set_gossip_source(self, source: Callable[[], Dict]) -> None:
        """Install the per-class depth snapshot provider; the
        maintenance thread pushes it to peers every gossip period."""
        self._gossip_source = source

    def push_gossip_now(self) -> int:
        """One immediate gossip push (tests and the admission path on
        sharp depth changes; the cadence push stays the steady state)."""
        source = self._gossip_source
        if source is None:
            return 0
        sent = self.push_to_peers(
            {"type": "gossip", "owner": self.owner, "classes": source()}
        )
        if sent:
            with self._tel_lock:
                self.gossip_sent += sent
        return sent

    # -- maintenance ---------------------------------------------------------
    def _loop(self) -> None:
        renew_due = time.monotonic() + self._lease_ms / 3000.0
        reap_due = time.monotonic() + self._lease_ms / 1000.0
        while not self._stop.wait(self._gossip_s):
            now = time.monotonic()
            try:
                if now >= renew_due:
                    self._renew()
                    renew_due = now + self._lease_ms / 3000.0
                self.push_gossip_now()
                if now >= reap_due:
                    reaped, _left = reap_members(self._dir)
                    if reaped:
                        with self._tel_lock:
                            self.members_reaped += reaped
                        self._members_cache = (0.0, {})
                    reap_due = now + self._lease_ms / 1000.0
            except OSError as exc:
                # a flaky members dir degrades the fast plane, never the
                # frontend: routing misses just fall back to claims
                _log.warning("fleet router maintenance failed: %s", exc)

    def stop(self) -> None:
        """Leave cleanly: stop the maintenance thread, close + unlink
        the socket, remove the member file."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._server.stop()
        file_utils.delete(self._member_path)
