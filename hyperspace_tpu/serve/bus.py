"""Index-version fanout bus — the fleet's publish/subscribe plane.

A refresh/optimize/vacuum in ONE frontend process used to be invisible
to its peers: their ``ServeCache`` entries for the outgoing index
version would just age out of the LRU (wasting budget) and their next
query would pay the full sidecar/zonemap re-read for the new version.
This module closes that gap with the smallest durable interface that
works on a plain shared filesystem (the Exoshuffle doctrine the whole
fleet follows — coordinate through small files next to the data, never
through shared memory):

* **Publish.** Every committed lifecycle action appends one JSON event
  file under ``<system.path>/_hyperspace_fleet/bus/`` (fsync-before-
  replace, ``utils/files.py``), named ``<ms>.<owner>.<n>.json`` so a
  lexicographic sort is a time sort. Events carry the index root to
  invalidate and — for actions that leave the index ACTIVE with fresh
  aggregate sidecars — the PUSHED ``("aggstate", fp)`` payload
  (``indexes/aggindex.fanout_payload``): metadata answers are tiny and
  version-addressed, so pushing beats making every peer re-read them.
* **Subscribe.** Each fleet frontend runs one poll thread
  (``hyperspace.fleet.bus.pollMs``) that lists the bus directory,
  applies unseen events oldest-first, and skips its own publications.
  Invalidation = ``ServeCache.evict_paths_under(root)`` + dropping the
  module LRUs; a push = ``aggindex.install_fanout_payload`` (validated
  against the current on-disk stats, so a stale push is dropped, never
  mis-keyed).
* **Retention.** Publishers prune event files older than
  ``hyperspace.fleet.bus.retainMs``. Correctness never depends on an
  event arriving: every cache key fingerprints the immutable file set,
  so a missed event costs a lazy re-read, not a stale answer.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from hyperspace_tpu import constants as C
from hyperspace_tpu.utils import files as file_utils

_log = logging.getLogger("hyperspace_tpu.fleet.bus")

#: this process's bus identity — subscribers skip events they published
_process_owner = uuid.uuid4().hex[:12]

# process-wide event sequence: every publisher (frontends, the
# lifecycle-action hook) names events through this one counter, so two
# publishes in the same millisecond can never collide on a file name
# (SHARED_STATE: guarded by _seq_lock)
_seq_lock = threading.Lock()
_seq = 0


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def fleet_root(conf) -> str:
    """``<hyperspace.system.path>/_hyperspace_fleet`` — the lake-level
    coordination directory (bus events + single-flight spool)."""
    system_path = conf.get_str(
        C.INDEX_SYSTEM_PATH, C.INDEX_SYSTEM_PATH_DEFAULT
    )
    return os.path.join(system_path, C.HYPERSPACE_FLEET_DIR)


def bus_dir(conf) -> str:
    return os.path.join(fleet_root(conf), "bus")


def _now_ms() -> int:
    return int(time.time() * 1000)


class FleetBus:
    """One process's handle on the fanout bus directory.

    Thread model: ``publish``/``poll_once`` may be called from any
    thread (they touch only local variables and the filesystem);
    ``start``/``stop`` manage the single poll thread. The seen-set is
    owned by the poll side (one mutator; ``poll_once`` from tests and
    the poll thread are never concurrent by contract)."""

    def __init__(
        self,
        directory: str,
        poll_ms: int = C.FLEET_BUS_POLL_MS_DEFAULT,
        retain_ms: int = C.FLEET_BUS_RETAIN_MS_DEFAULT,
        owner: Optional[str] = None,
    ):
        self.directory = directory
        # per-INSTANCE identity: a frontend must still receive events
        # published by a lifecycle action in its own process (the
        # action's publisher is a different instance), while skipping
        # its own publications
        self.owner = owner or uuid.uuid4().hex[:12]
        self.poll_ms = max(1, int(poll_ms))
        self.retain_ms = max(0, int(retain_ms))
        self._seen: set = set()
        self._primed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # telemetry (single-writer each: publish side / poll side)
        self.published = 0
        self.received = 0
        self.pruned = 0

    # -- publish -------------------------------------------------------------
    def publish(self, event: Dict) -> Optional[str]:
        """Append one event (fsync-before-replace); returns the event
        file name, or None when the bus directory is unwritable (the
        fleet degrades to age-out invalidation, never fails the
        action)."""
        name = f"{_now_ms():013d}.{self.owner}.{_next_seq():06d}.json"
        payload = dict(event)
        payload["owner"] = self.owner
        # the durable name rides IN the payload too: the fast push plane
        # forwards the same payload, and subscribers dedup push-vs-poll
        # delivery by this name
        payload["name"] = name
        try:
            file_utils.atomic_overwrite(
                os.path.join(self.directory, name), json.dumps(payload)
            )
        except OSError as exc:
            _log.warning("fleet bus publish failed: %s", exc)
            return None
        self.published += 1
        self._prune()
        return name

    def _prune(self) -> None:
        """Drop event files older than the retention window (publisher
        duty, best-effort)."""
        if self.retain_ms <= 0:
            return
        horizon = _now_ms() - self.retain_ms
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            stamp = name.split(".", 1)[0]
            if stamp.isdigit() and int(stamp) < horizon:
                file_utils.delete(os.path.join(self.directory, name))
                self.pruned += 1

    # -- subscribe -----------------------------------------------------------
    def prime(self) -> None:
        """Mark every event already on the bus as seen — a frontend
        attaching now starts from current state (its caches are empty;
        history would only be redundant work)."""
        try:
            self._seen = set(os.listdir(self.directory))
        except OSError:
            self._seen = set()
        self._primed = True

    def poll_once(self) -> List[Dict]:
        """Unseen peer events, oldest first (and marked seen)."""
        if not self._primed:
            self.prime()
            return []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        out: List[Dict] = []
        for name in names:
            if name in self._seen or not name.endswith(".json"):
                continue
            self._seen.add(name)
            try:
                with open(
                    os.path.join(self.directory, name), "r", encoding="utf-8"
                ) as fh:
                    event = json.load(fh)
            except (OSError, ValueError):
                continue  # pruned under us, or torn on a non-atomic mount
            if event.get("owner") == self.owner:
                continue
            self.received += 1
            out.append(event)
        # forget names that no longer exist so the seen-set stays bounded
        # by the retention window
        self._seen &= set(names)
        return out

    def start(self, callback: Callable[[Dict], None]) -> None:
        """Run the poll loop on a daemon thread, handing each peer event
        to ``callback`` (exceptions are contained per event — one bad
        payload must not kill the subscription)."""
        if self._thread is not None:
            return
        self.prime()

        def _loop() -> None:
            while not self._stop.wait(self.poll_ms / 1000.0):
                for event in self.poll_once():
                    try:
                        callback(event)
                    except Exception as exc:  # hslint: disable=HS402
                        # contain by contract: the bus is an optimization
                        # plane; a poisoned event costs one warning, not
                        # the subscription (every cache key is
                        # fingerprint-addressed, so skipping is safe)
                        _log.warning("fleet bus event failed: %s", exc)

        self._thread = threading.Thread(
            target=_loop, name="hs-fleet-bus", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# The lifecycle publisher (called by actions/base.py after commit)
# ---------------------------------------------------------------------------


def publish_action_event(session, index_name, index_path, action_name, entry):
    """Publish one committed lifecycle action to the fleet bus. No-op
    outside fleet mode; never raises (the action already committed — a
    failed fanout costs peers a lazy re-read, nothing else)."""
    conf = session.conf
    if not conf.fleet_enabled:
        return
    event: Dict = {
        "type": "index_changed",
        "action": action_name,
        "index": index_name,
        "root": str(index_path).replace("\\", "/"),
    }
    # cross-process trace propagation (docs/observability.md): the
    # publishing action's trace id rides the event, so a peer's
    # eviction/install is linkable to the lifecycle action that caused
    # it (None with obs off — the field is simply absent)
    from hyperspace_tpu.obs import trace as obs_trace

    trace_id = obs_trace.current_trace_id()
    if trace_id is not None:
        event["trace_id"] = trace_id
    try:
        if (
            entry is not None
            and entry.state == C.States.ACTIVE
            and conf.index_agg_enabled
        ):
            from hyperspace_tpu.indexes import aggindex

            payload = aggindex.fanout_payload(entry.content.files)
            if payload is not None:
                event["aggstate"] = payload
        bus = FleetBus(
            bus_dir(conf),
            poll_ms=conf.fleet_bus_poll_ms,
            retain_ms=conf.fleet_bus_retain_ms,
        )
        name = bus.publish(event)
        if name is not None and conf.fleet_fast_enabled:
            # fast fanout AFTER the durable write: peers the push
            # reaches evict in microseconds; peers it misses see the
            # identical payload (same "name") at their next poll
            from hyperspace_tpu.serve import router as fleet_router

            fleet_router.push_event_to_members(
                conf, {**event, "owner": bus.owner, "name": name}
            )
    except Exception as exc:  # hslint: disable=HS402
        # catch-all IS the contract: fanout is best-effort by design
        _log.warning("fleet bus publish failed for %s: %s", index_name, exc)
