"""FleetFrontend — one member of a replicated serve fleet.

``ServeFrontend`` (PR 8) scales one PROCESS to 64 clients; "millions of
users" means N processes on M hosts sharing one index lake. This module
is the per-process member of that fleet (docs/fleet-serve.md). The
design rule, inherited from the crash-safe lifecycle plane and argued by
Exoshuffle (PAPERS.md): the fleet coordinates through small, durable,
lease-stamped files next to the data it protects — never through shared
memory, never through a coordinator service. Three planes on top of the
inherited frontend:

* **Durable pins.** Every admitted query's pinned snapshot is ALSO
  published as a lease-expiring file under
  ``<index>/_hyperspace_pins/`` (``metadata/recovery.register_pins
  (durable=True)``), heartbeat-renewed — so an orphan GC or a vacuum
  running in ANOTHER process never deletes files under a live query,
  and a frontend that dies (kill -9) stops renewing and its pins are
  reaped at lease expiry instead of leaking forever.

* **Version fanout.** The frontend subscribes to the fleet bus
  (``serve/bus.py``): a refresh/optimize/vacuum committed by any peer
  evicts this process's ``ServeCache`` entries for the changed index
  (instead of letting dead versions age out of the LRU) and INSTALLS
  pushed ``("aggstate", fp)`` payloads — metadata answers are tiny and
  version-addressed, so the first point aggregate over the new snapshot
  folds straight from RAM.

* **Cross-process single-flight.** The in-process dedup saved 256 of
  512 identical queries at one process; at eight processes it would
  save none. Identical plans (same fingerprint, same pinned snapshot)
  now elect ONE executor fleet-wide through an atomic claim file, and
  the winner publishes its answer as an Arrow IPC file in a bounded
  result spool the losers read. Correctness never depends on the
  election: a lost claim plus a missing result just executes locally
  after ``hyperspace.fleet.singleflight.waitMs`` — the timeout forfeits
  the dedup win, never the answer — and results are keyed by the
  immutable snapshot fingerprint, so a stale spool entry is
  unreachable, not wrong.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
import uuid
from typing import Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.metadata import recovery
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.serve import bus as fleet_bus
from hyperspace_tpu.serve.frontend import ServeFrontend, plan_fingerprint
from hyperspace_tpu.utils import files as file_utils

_log = logging.getLogger("hyperspace_tpu.fleet")

#: claim losers re-check the spool at this cadence while waiting
_SPOOL_POLL_S = 0.01


def spool_dir(conf) -> str:
    return os.path.join(fleet_bus.fleet_root(conf), "spool")


class FleetFrontend(ServeFrontend):
    """A :class:`ServeFrontend` wired into the fleet planes. Drop-in:
    ``session.serve_frontend`` returns one automatically when
    ``hyperspace.fleet.enabled`` is true."""

    def __init__(self, session):
        super().__init__(session)
        conf = session.conf
        self._spool_dir = spool_dir(conf)
        self._pin_lease_ms = conf.fleet_pin_lease_ms
        self._sf_enabled = conf.fleet_singleflight_enabled
        self._sf_wait_s = conf.fleet_singleflight_wait_ms / 1000.0
        self._sf_claim_ms = conf.fleet_singleflight_claim_ms
        self._spool_max_bytes = conf.fleet_spool_max_bytes
        # fleet counters (mutated under the frontend lock, like the
        # base counters; all I/O happens outside it)
        self._spool_hits = 0
        self._claims_won = 0
        self._claim_waits = 0
        self._sf_local = 0
        self._bus_events = 0
        self._bus_evicted = 0
        self._bus_installed = 0
        self._bus = fleet_bus.FleetBus(
            fleet_bus.bus_dir(conf),
            poll_ms=conf.fleet_bus_poll_ms,
            retain_ms=conf.fleet_bus_retain_ms,
        )
        self._bus.start(self._on_bus_event)

    # -- durable pins --------------------------------------------------------
    def _register_pins(self, pin: Optional[Tuple]) -> int:
        return recovery.register_pins(
            pin, durable=True, lease_ms=self._pin_lease_ms
        )

    # -- version fanout ------------------------------------------------------
    def _on_bus_event(self, event: dict) -> None:
        if event.get("type") != "index_changed":
            return
        with self._lock:
            self._bus_events += 1
        root = event.get("root")
        cache = self._session.serve_cache
        evicted = 0
        from hyperspace_tpu.indexes import aggindex, zonemaps

        if root:
            if cache is not None:
                evicted = cache.evict_paths_under(str(root))
            # the module LRUs hold assembled per-version state too —
            # scoped the same way (fingerprint-keyed, so this is pure
            # memory reclamation: a refresh of index A must not cost
            # index B its warm state on every peer)
            zonemaps.invalidate_paths_under(str(root))
            aggindex.invalidate_paths_under(str(root))
        installed = False
        payload = event.get("aggstate")
        if payload:
            # the push plane (ROADMAP 2c): install the new version's
            # aggregate state instead of waiting for a lazy re-read
            installed = aggindex.install_fanout_payload(payload, cache)
        with self._lock:
            self._bus_evicted += evicted
            self._bus_installed += bool(installed)

    # -- cross-process single-flight -----------------------------------------
    def _plan_digest(self, plan, pin) -> Optional[str]:
        """Fleet-wide identity of (plan, pinned snapshot): the in-process
        fingerprint minus the process-local conf version, hashed. Every
        component is strings/ints/tuples, so ``repr`` is deterministic
        across processes."""
        try:
            key = (
                plan_fingerprint(plan),
                tuple((e.name, e.id) for e in pin),
            )
            return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()[:40]
        except Exception:  # hslint: disable=HS402
            # any unfingerprintable plan simply skips the dedup plane
            return None

    def _read_spool(self, path: str):
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            return pa.ipc.open_stream(pa.py_buffer(data)).read_all()
        except (OSError, pa.ArrowInvalid):
            return None

    def _write_spool(self, path: str, table) -> None:
        """Publish a result (fsync-before-replace; best-effort — an
        unwritable spool costs peers the dedup win, not the answer)."""
        try:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as writer:
                writer.write_table(table)
            file_utils.atomic_overwrite_bytes(
                path, sink.getvalue().to_pybytes()
            )
        except (OSError, pa.ArrowInvalid) as exc:
            _log.warning("fleet spool write failed: %s", exc)
            return
        self._prune_spool()

    def _prune_spool(self) -> None:
        """Keep the spool inside its byte budget (oldest results first)
        and sweep expired claims + crash-leaked publish temps."""
        try:
            names = os.listdir(self._spool_dir)
        except OSError:
            return
        now = time.time()
        entries = []
        for name in names:
            p = os.path.join(self._spool_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            if name.endswith(".arrow.trace"):
                # trace-link sidecar: lives and dies with its result.
                # Published BEFORE the .arrow, so an orphan is only
                # reaped past the claim lease — a peer pruning in the
                # sidecar->result publish window must not eat it
                if name[: -len(".trace")] not in names and (
                    (now - st.st_mtime) * 1000 > self._sf_claim_ms
                ):
                    file_utils.delete(p)
            elif name.endswith(".arrow"):
                entries.append((st.st_mtime, st.st_size, p))
            elif name.startswith(".tmp_spool_"):
                # a kill -9 mid-publish leaks the temp; claim lease is a
                # generous upper bound on how long a legitimate publish
                # can still be in flight
                if (now - st.st_mtime) * 1000 > self._sf_claim_ms:
                    file_utils.delete(p)
            elif name.endswith(".claim"):
                if (now - st.st_mtime) * 1000 > self._sf_claim_ms:
                    file_utils.delete(p)
        total = sum(size for _m, size, _p in entries)
        if self._spool_max_bytes <= 0:
            return
        for _mtime, size, p in sorted(entries):
            if total <= self._spool_max_bytes:
                break
            file_utils.delete(p)
            file_utils.delete(p + ".trace")
            total -= size

    def _try_claim(self, claim_path: str) -> str:
        """One attempt at the executor election: ``"won"`` | ``"held"``
        (a live peer owns it) | ``"error"`` (spool unusable — execute
        locally, the plane is an optimization)."""
        nonce = uuid.uuid4().hex
        payload = json.dumps(
            {
                "owner": fleet_bus._process_owner,
                "nonce": nonce,
                "pid": os.getpid(),
                "expiresAtMs": int(time.time() * 1000) + self._sf_claim_ms,
                # the claimant's trace id: waiting losers link their
                # root span to the winner's trace (cross-process
                # single-flight shows up as ONE logical execution in
                # the obs plane; absent with obs off)
                "traceId": obs_trace.current_trace_id(),
            }
        )
        try:
            if file_utils.atomic_write_if_absent(claim_path, payload):
                return "won"
            # held: by a live winner, or leaked by a dead one (kill -9
            # mid-serve) — the lease decides, exactly like the writer
            # and pin leases
            try:
                with open(claim_path, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
                expires = int(doc["expiresAtMs"])
            except (OSError, ValueError, KeyError, TypeError):
                expires = 0  # torn/vanished: treat as expired
            if expires <= int(time.time() * 1000):
                # takeover by atomic REPLACE, never delete+create: a
                # delete could destroy a racing contender's fresh claim
                # and elect two winners. Racers overwrite each other;
                # the settle-then-verify read picks exactly one (last
                # write) and the others keep waiting.
                file_utils.atomic_overwrite(claim_path, payload)
                time.sleep(0.002)
                try:
                    with open(claim_path, "r", encoding="utf-8") as fh:
                        if json.load(fh).get("nonce") == nonce:
                            return "won"
                except (OSError, ValueError):
                    pass
            return "held"
        except OSError:
            return "error"

    def _execute_pinned(self, plan, pin: Optional[Tuple]):
        if not self._sf_enabled or not pin:
            # unpinned/degraded serves skip the plane: their identity is
            # not snapshot-addressed, so sharing would be unsound
            return super()._execute_pinned(plan, pin)
        digest = self._plan_digest(plan, pin)
        if digest is None:
            return super()._execute_pinned(plan, pin)
        result_path = os.path.join(self._spool_dir, digest + ".arrow")
        claim_path = os.path.join(self._spool_dir, digest + ".claim")
        deadline = time.monotonic() + self._sf_wait_s
        waiting = False
        while True:
            out = self._read_spool(result_path)
            if out is not None:
                with self._lock:
                    self._spool_hits += 1
                # link loser -> winner: the result's trace sidecar names
                # the executing process's trace, so a cross-process
                # dedup reads as ONE logical execution in the obs plane
                obs_trace.event(
                    "spool_hit",
                    digest=digest,
                    winner_trace_id=self._read_trace_sidecar(result_path),
                )
                return out
            verdict = self._try_claim(claim_path)
            if verdict == "won":
                with self._lock:
                    self._claims_won += 1
                obs_trace.event("singleflight_won", digest=digest)
                try:
                    out = super()._execute_pinned(plan, pin)
                except BaseException:
                    # free the peers immediately: a failed winner must
                    # not make every waiter ride out the claim lease
                    file_utils.delete(claim_path)
                    raise
                # sidecar BEFORE the result: a loser polling every 2ms
                # must never see the .arrow without its trace link
                self._write_trace_sidecar(result_path)
                self._write_spool(result_path, out)
                file_utils.delete(claim_path)
                return out
            if verdict == "error" or time.monotonic() >= deadline:
                # forfeits the dedup win, never the answer
                with self._lock:
                    self._sf_local += 1
                return super()._execute_pinned(plan, pin)
            if not waiting:
                waiting = True
                with self._lock:
                    self._claim_waits += 1
                obs_trace.event(
                    "singleflight_wait",
                    digest=digest,
                    winner_trace_id=self._read_claim_trace(claim_path),
                )
            time.sleep(_SPOOL_POLL_S)

    # -- trace linkage (docs/observability.md; best-effort everywhere) -------
    def _write_trace_sidecar(self, result_path: str) -> None:
        """Publish the winner's trace id next to its spooled result so
        later spool hits can link to it (claim files vanish at commit)."""
        trace_id = obs_trace.current_trace_id()
        if trace_id is None:
            return
        try:
            file_utils.atomic_overwrite(
                result_path + ".trace", json.dumps({"traceId": trace_id})
            )
        except OSError:
            pass

    def _read_trace_sidecar(self, result_path: str) -> Optional[str]:
        try:
            with open(result_path + ".trace", "r", encoding="utf-8") as fh:
                return json.load(fh).get("traceId")
        except (OSError, ValueError):
            return None

    def _read_claim_trace(self, claim_path: str) -> Optional[str]:
        try:
            with open(claim_path, "r", encoding="utf-8") as fh:
                return json.load(fh).get("traceId")
        except (OSError, ValueError):
            return None

    # -- introspection / lifecycle ------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        with self._lock:
            out["fleet"] = {
                "spool_hits": self._spool_hits,
                "claims_won": self._claims_won,
                "claim_waits": self._claim_waits,
                "singleflight_local": self._sf_local,
                "bus_events": self._bus_events,
                "bus_evicted": self._bus_evicted,
                "bus_installed": self._bus_installed,
                "bus_published": self._bus.published,
            }
        return out

    def close(self, wait: bool = True) -> None:
        self._bus.stop()
        super().close(wait=wait)
