"""FleetFrontend — one member of a replicated serve fleet.

``ServeFrontend`` (PR 8) scales one PROCESS to 64 clients; "millions of
users" means N processes on M hosts sharing one index lake. This module
is the per-process member of that fleet (docs/fleet-serve.md). The
design rule, inherited from the crash-safe lifecycle plane and argued by
Exoshuffle (PAPERS.md): the fleet coordinates CORRECTNESS through small,
durable, lease-stamped files next to the data it protects. Three durable
planes on top of the inherited frontend:

* **Durable pins.** Every admitted query's pinned snapshot is ALSO
  published as a lease-expiring file under
  ``<index>/_hyperspace_pins/`` (``metadata/recovery.register_pins
  (durable=True)``), heartbeat-renewed — so an orphan GC or a vacuum
  running in ANOTHER process never deletes files under a live query,
  and a frontend that dies (kill -9) stops renewing and its pins are
  reaped at lease expiry instead of leaking forever.

* **Version fanout.** The frontend subscribes to the fleet bus
  (``serve/bus.py``): a refresh/optimize/vacuum committed by any peer
  evicts this process's ``ServeCache`` entries for the changed index
  (instead of letting dead versions age out of the LRU) and INSTALLS
  pushed ``("aggstate", fp)`` payloads — metadata answers are tiny and
  version-addressed, so the first point aggregate over the new snapshot
  folds straight from RAM.

* **Cross-process single-flight.** Identical plans (same fingerprint,
  same pinned snapshot) elect ONE executor fleet-wide through an atomic
  claim file, and the winner publishes its answer as an Arrow IPC file
  in a bounded result spool the losers read. Correctness never depends
  on the election: a lost claim plus a missing result just executes
  locally after ``hyperspace.fleet.singleflight.waitMs`` — the timeout
  forfeits the dedup win, never the answer — and results are keyed by
  the immutable snapshot fingerprint, so a stale spool entry is
  unreachable, not wrong.

Those planes POLL, and the polling tax is why 2 fleet processes used to
lose to one process with 64 clients (ROADMAP item 3). The FAST data
plane (``hyperspace.fleet.fast.*``; ``serve/fastbus.py`` transport,
``serve/router.py`` membership) removes the tax without touching the
correctness story:

* **Push bus.** Fanout events, single-flight result-ready wakeups and
  SLO gossip are pushed over per-host Unix sockets in microseconds;
  every push is idempotently replayable from the durable planes (bus
  events carry their durable file name, results are digest-addressed),
  so a dropped push costs one poll interval, nothing else.

* **Owner routing.** Plan digests rendezvous-hash to ONE live member
  (lease-stamped member files). The owner serves from an in-memory
  digest->result LRU or executes once; peers ship the plan spec and
  stream the Arrow result back — no claim election, no fsync'd spool
  round-trip. The spool still receives owner results asynchronously
  (cross-host peers, crash recovery), and ANY fast-path failure — dead
  owner, timeout, armed ``fastbus_send`` fault, digest mismatch — falls
  back to the claim/spool plane. The owner re-derives the digest from
  the shipped spec against its own pinned snapshot and answers only on
  an exact match, so a reply is always THE answer to the requested
  (plan, snapshot) identity.

* **Fleet-wide SLO.** Per-class queue depths gossip between members;
  the admission check counts live peers' depths, so a batch tier
  saturating one process sheds fleet-wide before the interactive tier
  queues anywhere.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.metadata import recovery
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import planspec as obs_planspec
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.serve import bus as fleet_bus
from hyperspace_tpu.serve import fastbus
from hyperspace_tpu.serve.frontend import ServeFrontend, plan_fingerprint
from hyperspace_tpu.serve.router import FleetRouter
from hyperspace_tpu.utils import files as file_utils

_log = logging.getLogger("hyperspace_tpu.fleet")

#: claim losers re-check the spool at this cadence while waiting (the
#: result-ready push usually wakes them first; this is the roof)
_SPOOL_POLL_S = 0.01

#: jittered exponential backoff between LOST claim attempts — losers
#: must not hammer the claim file at a fixed cadence (the election part
#: of the polling tax; base doubles per loss up to the cap, then a
#: 0.5-1.5x jitter decorrelates the herd)
_ELECTION_BACKOFF_BASE_S = 0.01
_ELECTION_BACKOFF_CAP_S = 0.5

#: bus event names applied via fast push, remembered so the durable
#: poll skips re-applying them (idempotent either way; this caps the
#: memory, not the correctness)
_FAST_APPLIED_MAX = 512

# election telemetry as registered metrics (obs/sites.py: the
# serve.fleet module is an OBS_SITES "metric" site) — process-global
# across frontends, exported by every sink; the per-instance stats()
# counters stay the per-frontend view
_election_attempts_total = obs_metrics.registry.counter(
    "hs_fleet_election_attempts_total",
    "Cross-process single-flight claim attempts",
)
_election_wins_total = obs_metrics.registry.counter(
    "hs_fleet_election_wins_total",
    "Cross-process single-flight claims won",
)
_election_losses_total = obs_metrics.registry.counter(
    "hs_fleet_election_losses_total",
    "Cross-process single-flight claims lost (a live peer held it)",
)


def spool_dir(conf) -> str:
    return os.path.join(fleet_bus.fleet_root(conf), "spool")


class FleetFrontend(ServeFrontend):
    """A :class:`ServeFrontend` wired into the fleet planes. Drop-in:
    ``session.serve_frontend`` returns one automatically when
    ``hyperspace.fleet.enabled`` is true."""

    def __init__(self, session):
        super().__init__(session)
        conf = session.conf
        self._spool_dir = spool_dir(conf)
        self._pin_lease_ms = conf.fleet_pin_lease_ms
        self._sf_enabled = conf.fleet_singleflight_enabled
        self._sf_wait_s = conf.fleet_singleflight_wait_ms / 1000.0
        self._sf_claim_ms = conf.fleet_singleflight_claim_ms
        self._spool_max_bytes = conf.fleet_spool_max_bytes
        # fleet counters (mutated under the frontend lock, like the
        # base counters; all I/O happens outside it)
        self._spool_hits = 0
        self._claims_won = 0
        self._claim_waits = 0
        self._sf_local = 0
        self._bus_events = 0
        self._bus_evicted = 0
        self._bus_installed = 0
        self._election_attempts = 0
        self._election_wins = 0
        self._election_losses = 0
        self._spool_reaped_traces = 0
        self._spool_reaped_claims = 0
        self._spool_reaped_tmp = 0
        self._spool_pruned_results = 0
        # fast plane counters
        self._fast_result_hits = 0
        self._fast_dedup_joins = 0
        self._fast_handoffs = 0
        self._fast_fallbacks = 0
        self._fast_requests_served = 0
        self._fast_push_received = 0
        self._fast_wakes = 0
        self._gossip_received = 0
        self._spool_publishes = 0
        self._spool_publish_drops = 0
        # push-vs-poll wait telemetry (satellite of ROADMAP item 3: the
        # bench ladder records how long serves waited on each plane)
        self._fast_wait_ms_total = 0.0
        self._fast_waits = 0
        self._poll_wait_ms_total = 0.0
        self._poll_waits = 0
        # fast plane state (all mutated under the frontend lock)
        self._fast_results: OrderedDict = OrderedDict()
        self._fast_results_bytes = 0
        self._fast_inflight: Dict[str, Future] = {}
        self._wake_events: Dict[str, list] = {}
        self._fast_applied: set = set()
        self._fast_applied_order: deque = deque()
        self._peer_slo: Dict[str, Tuple[float, Dict]] = {}
        self._fast_enabled = conf.fleet_fast_enabled
        self._fast_routing = conf.fleet_fast_routing_enabled
        self._fast_timeout_s = conf.fleet_fast_request_timeout_ms / 1000.0
        self._fast_cache_bytes = conf.fleet_fast_result_cache_bytes
        self._slo_fleet_wide = conf.fleet_fast_slo_fleet_wide
        self._gossip_stale_s = max(10 * conf.fleet_fast_gossip_ms, 2000) / 1000.0
        self._bus = fleet_bus.FleetBus(
            fleet_bus.bus_dir(conf),
            poll_ms=conf.fleet_bus_poll_ms,
            retain_ms=conf.fleet_bus_retain_ms,
        )
        self._bus.start(self._on_durable_bus_event)
        self._router: Optional[FleetRouter] = None
        self._publish_q: Optional[queue.Queue] = None
        self._publish_thread: Optional[threading.Thread] = None
        if self._fast_enabled:
            try:
                self._router = FleetRouter(
                    conf, owner=self._bus.owner, handler=self._on_fast_message
                )
                self._router.set_gossip_source(self._gossip_payload)
            except OSError as exc:
                # the fast plane is an optimization: an unbindable socket
                # or unwritable members dir degrades to durable-only
                _log.warning("fleet fast plane unavailable: %s", exc)
                self._router = None
            else:
                self._publish_q = queue.Queue(maxsize=16)
                self._publish_thread = threading.Thread(
                    target=self._publish_loop,
                    name="hs-fleet-publish",
                    daemon=True,
                )
                self._publish_thread.start()

    # -- durable pins --------------------------------------------------------
    def _register_pins(self, pin: Optional[Tuple]) -> int:
        return recovery.register_pins(
            pin, durable=True, lease_ms=self._pin_lease_ms
        )

    # -- version fanout ------------------------------------------------------
    def _on_durable_bus_event(self, event: dict) -> None:
        """The poll-plane subscriber: skips events already applied via
        fast push (keyed by the durable bus file name both planes carry
        — re-applying would be idempotent, just wasted evictions)."""
        name = event.get("name")
        if name:
            with self._lock:
                if name in self._fast_applied:
                    return
        self._on_bus_event(event)

    def _on_bus_event(self, event: dict) -> None:
        if event.get("type") != "index_changed":
            return
        with self._lock:
            self._bus_events += 1
        root = event.get("root")
        cache = self._session.serve_cache
        evicted = 0
        from hyperspace_tpu.indexes import aggindex, zonemaps

        if root:
            if cache is not None:
                evicted = cache.evict_paths_under(str(root))
            # the module LRUs hold assembled per-version state too —
            # scoped the same way (fingerprint-keyed, so this is pure
            # memory reclamation: a refresh of index A must not cost
            # index B its warm state on every peer)
            zonemaps.invalidate_paths_under(str(root))
            aggindex.invalidate_paths_under(str(root))
        installed = False
        payload = event.get("aggstate")
        if payload:
            # the push plane (ROADMAP 2c): install the new version's
            # aggregate state instead of waiting for a lazy re-read
            installed = aggindex.install_fanout_payload(payload, cache)
        with self._lock:
            self._bus_evicted += evicted
            self._bus_installed += bool(installed)

    # -- fast plane: inbound -------------------------------------------------
    def _on_fast_message(
        self, header: dict, body: bytes
    ) -> Optional[Tuple[dict, bytes]]:
        """Dispatch one pushed/requested message (fastbus handler
        threads). One-way types return None; ``exec`` returns a reply."""
        mtype = header.get("type")
        if mtype == "event":
            event = header.get("event") or {}
            if event.get("owner") == self._bus.owner:
                return None  # own publication, mirror the poll-side skip
            name = event.get("name")
            with self._lock:
                self._fast_push_received += 1
                if name:
                    if name in self._fast_applied:
                        return None  # durable poll beat the push
                    self._fast_applied.add(name)
                    self._fast_applied_order.append(name)
                    while len(self._fast_applied_order) > _FAST_APPLIED_MAX:
                        self._fast_applied.discard(
                            self._fast_applied_order.popleft()
                        )
            self._on_bus_event(event)
            return None
        if mtype == "gossip":
            owner = header.get("owner")
            if owner and owner != self._bus.owner:
                with self._lock:
                    self._gossip_received += 1
                    self._peer_slo[owner] = (
                        time.monotonic(),
                        header.get("classes") or {},
                    )
            return None
        if mtype == "result_ready":
            with self._lock:
                self._fast_wakes += 1
                entry = self._wake_events.get(header.get("digest"))
            if entry is not None:
                entry[0].set()
            return None
        if mtype == "exec":
            return self._handle_exec(header)
        return {"status": "bad_request"}, b""

    def _handle_exec(self, header: dict) -> Tuple[dict, bytes]:
        """Owner side of a routed single-flight: result cache, else
        rebuild the shipped plan spec, pin, VERIFY the digest matches
        the requested identity, execute through the local in-memory
        single-flight, stream the Arrow result back. Any mismatch or
        failure replies "miss" — the requester's durable fallback is
        the correctness plane, this path only ever returns the exact
        answer to the requested (plan, snapshot) digest."""
        digest = header.get("digest")
        if not digest:
            return {"status": "bad_request"}, b""
        with self._lock:
            out = self._fast_cache_get_locked(digest)
            if out is not None:
                self._fast_result_hits += 1
                self._fast_requests_served += 1
        if out is not None:
            return {"status": "hit"}, fastbus.table_to_bytes(out)
        spec = header.get("spec")
        if spec is None:
            return {"status": "miss", "reason": "no_spec"}, b""
        try:
            plan = obs_planspec.from_spec(self._session, spec)
        except Exception:  # hslint: disable=HS402
            # an unreplayable spec degrades to a miss, never an error
            # reply the requester has to interpret
            return {"status": "miss", "reason": "spec"}, b""
        pin = self._pin()
        if not pin:
            return {"status": "miss", "reason": "pin"}, b""
        token = self._register_pins(pin)
        try:
            if self._plan_digest(plan, pin) != digest:
                # snapshot skew between requester and owner (a refresh
                # mid-flight): answering would be answering a DIFFERENT
                # question — the requester falls back to its own plane
                return {"status": "miss", "reason": "snapshot"}, b""
            try:
                out = self._serve_digest(digest, plan, pin)
            except Exception:  # hslint: disable=HS402
                return {"status": "miss", "reason": "exec"}, b""
            with self._lock:
                self._fast_requests_served += 1
            return {"status": "hit"}, fastbus.table_to_bytes(out)
        finally:
            recovery.release_pins(token)

    # -- fast plane: result cache + local single-flight ----------------------
    def _fast_cache_get_locked(self, digest: str):
        item = self._fast_results.get(digest)
        if item is None:
            return None
        self._fast_results.move_to_end(digest)
        return item[0]

    def _fast_cache_put(self, digest: str, table) -> None:
        if self._fast_cache_bytes <= 0:
            return
        try:
            nbytes = int(table.nbytes)
        except (TypeError, ValueError):
            return
        if nbytes > self._fast_cache_bytes:
            return
        with self._lock:
            old = self._fast_results.pop(digest, None)
            if old is not None:
                self._fast_results_bytes -= old[1]
            self._fast_results[digest] = (table, nbytes)
            self._fast_results_bytes += nbytes
            while self._fast_results_bytes > self._fast_cache_bytes:
                _k, (_t, nb) = self._fast_results.popitem(last=False)
                self._fast_results_bytes -= nb

    def _serve_digest(self, digest: str, plan, pin):
        """Owner-side serve of one digest: result cache -> in-process
        single-flight (followers join the leader's Future) -> execute
        -> cache + async spool publish. No claim file anywhere — owner
        routing made this process THE executor for the digest."""
        with self._lock:
            out = self._fast_cache_get_locked(digest)
            if out is not None:
                self._fast_result_hits += 1
                return out
            fut = self._fast_inflight.get(digest)
            if fut is None:
                fut = Future()
                self._fast_inflight[digest] = fut
                leader = True
            else:
                leader = False
        if not leader:
            try:
                out = fut.result(timeout=self._sf_wait_s)
                with self._lock:
                    self._fast_dedup_joins += 1
                return out
            except Exception:  # hslint: disable=HS402
                # failed/slow leader: forfeit the dedup win, never the
                # answer (the exact claim-timeout contract, in memory)
                with self._lock:
                    self._sf_local += 1
                return super()._execute_pinned(plan, pin)
        try:
            out = super()._execute_pinned(plan, pin)
        except BaseException as exc:
            fut.set_exception(exc)
            with self._lock:
                self._fast_inflight.pop(digest, None)
            raise
        self._fast_cache_put(digest, out)
        fut.set_result(out)
        with self._lock:
            self._fast_inflight.pop(digest, None)
        self._spool_publish_async(digest, out)
        return out

    # -- fast plane: outbound ------------------------------------------------
    def _fast_serve(self, digest: str, plan, pin):
        """Requester side of owner routing. Returns the Table, or None
        — the caller continues on the durable claim/spool plane."""
        router = self._router
        if router is None or not self._fast_routing:
            return None
        with self._lock:
            out = self._fast_cache_get_locked(digest)
            if out is not None:
                self._fast_result_hits += 1
                return out
        target = router.owner_of(digest)
        if target is None:
            return None
        owner, sock = target
        if owner == router.owner:
            return self._serve_digest(digest, plan, pin)
        spec = obs_planspec.to_spec(plan)
        if spec is None:
            return None  # unshippable plan: durable plane handles it
        t0 = time.monotonic()
        try:
            reply, body = fastbus.request(
                sock,
                {
                    "type": "exec",
                    "digest": digest,
                    "spec": spec,
                    "wait_ms": int(self._fast_timeout_s * 1000),
                },
                timeout_s=self._fast_timeout_s,
            )
            if reply.get("status") == "hit" and body:
                out = fastbus.table_from_bytes(body)
                with self._lock:
                    self._fast_handoffs += 1
                    self._fast_waits += 1
                    self._fast_wait_ms_total += (
                        time.monotonic() - t0
                    ) * 1000.0
                obs_trace.event("fast_handoff", digest=digest, owner=owner)
                self._fast_cache_put(digest, out)
                return out
        except (OSError, ValueError, pa.ArrowInvalid):
            # dead owner / timeout / armed fastbus_send fault / torn
            # reply: all the same degradation — the durable plane is
            # the answer, this was only the fast lane to it
            pass
        with self._lock:
            self._fast_fallbacks += 1
            self._fast_waits += 1
            self._fast_wait_ms_total += (time.monotonic() - t0) * 1000.0
        obs_trace.event("fast_fallback", digest=digest, owner=owner)
        return None

    # -- fast plane: async spool publish ------------------------------------
    def _spool_publish_async(self, digest: str, table) -> None:
        """Queue the owner's result for background spool publication
        (cross-host peers + crash recovery keep the durable artifact;
        the fsync just left the serve hot path). Overflow drops the
        publish — the spool is an optimization, peers re-execute."""
        if self._publish_q is None:
            return
        try:
            self._publish_q.put_nowait((digest, table))
        except queue.Full:
            with self._lock:
                self._spool_publish_drops += 1

    def _publish_loop(self) -> None:
        while True:
            item = self._publish_q.get()
            if item is None:
                return
            digest, table = item
            result_path = os.path.join(self._spool_dir, digest + ".arrow")
            self._write_trace_sidecar(result_path)
            self._write_spool(result_path, table)
            with self._lock:
                self._spool_publishes += 1
            if self._router is not None:
                self._router.push_to_peers(
                    {"type": "result_ready", "digest": digest}
                )

    # -- fleet-wide SLO ------------------------------------------------------
    def _gossip_payload(self) -> Dict[str, int]:
        """Per-class local depth snapshot the router pushes to peers."""
        with self._lock:
            return {
                name: cls.running + len(cls.pending)
                for name, cls in self._slo_classes.items()
            }

    def _fleet_class_depth_locked(self, cls) -> int:
        """Live peers' gossiped depth for this class (called with the
        frontend lock held — _peer_slo mutates under the same lock).
        Stale entries are ignored: a dead peer must not pin its last
        depth into every admission decision forever."""
        if not self._slo_fleet_wide or self._router is None:
            return 0
        horizon = time.monotonic() - self._gossip_stale_s
        return sum(
            classes.get(cls.name, 0)
            for ts, classes in self._peer_slo.values()
            if ts >= horizon
        )

    # -- cross-process single-flight -----------------------------------------
    def _plan_digest(self, plan, pin) -> Optional[str]:
        """Fleet-wide identity of (plan, pinned snapshot): the in-process
        fingerprint minus the process-local conf version, hashed. Every
        component is strings/ints/tuples, so ``repr`` is deterministic
        across processes."""
        try:
            key = (
                plan_fingerprint(plan),
                tuple((e.name, e.id) for e in pin),
            )
            return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()[:40]
        except Exception:  # hslint: disable=HS402
            # any unfingerprintable plan simply skips the dedup plane
            return None

    def _read_spool(self, path: str):
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            return pa.ipc.open_stream(pa.py_buffer(data)).read_all()
        except (OSError, pa.ArrowInvalid):
            return None

    def _write_spool(self, path: str, table) -> None:
        """Publish a result (fsync-before-replace; best-effort — an
        unwritable spool costs peers the dedup win, not the answer)."""
        try:
            file_utils.atomic_overwrite_bytes(
                path, fastbus.table_to_bytes(table)
            )
        except (OSError, pa.ArrowInvalid) as exc:
            _log.warning("fleet spool write failed: %s", exc)
            return
        self._prune_spool()

    def _prune_spool(self) -> None:
        """Keep the spool inside its byte budget (oldest results first)
        and sweep expired claims, orphaned trace sidecars and crash-
        leaked publish temps on the same lease-aged pass — every reap
        counted into ``stats()`` so a leak shows up as a number, not a
        du(1) surprise."""
        try:
            names = os.listdir(self._spool_dir)
        except OSError:
            return
        now = time.time()
        entries = []
        reaped_traces = reaped_claims = reaped_tmp = pruned = 0
        for name in names:
            p = os.path.join(self._spool_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            if name.endswith(".arrow.trace"):
                # trace-link sidecar: lives and dies with its result.
                # Published BEFORE the .arrow, so an orphan is only
                # reaped past the claim lease — a peer pruning in the
                # sidecar->result publish window must not eat it
                if name[: -len(".trace")] not in names and (
                    (now - st.st_mtime) * 1000 > self._sf_claim_ms
                ):
                    file_utils.delete(p)
                    reaped_traces += 1
            elif name.endswith(".arrow"):
                entries.append((st.st_mtime, st.st_size, p))
            elif name.startswith(".tmp_spool_"):
                # a kill -9 mid-publish leaks the temp; claim lease is a
                # generous upper bound on how long a legitimate publish
                # can still be in flight
                if (now - st.st_mtime) * 1000 > self._sf_claim_ms:
                    file_utils.delete(p)
                    reaped_tmp += 1
            elif name.endswith(".claim"):
                if (now - st.st_mtime) * 1000 > self._sf_claim_ms:
                    file_utils.delete(p)
                    reaped_claims += 1
        total = sum(size for _m, size, _p in entries)
        if self._spool_max_bytes > 0:
            for _mtime, size, p in sorted(entries):
                if total <= self._spool_max_bytes:
                    break
                file_utils.delete(p)
                file_utils.delete(p + ".trace")
                total -= size
                pruned += 1
        if reaped_traces or reaped_claims or reaped_tmp or pruned:
            with self._lock:
                self._spool_reaped_traces += reaped_traces
                self._spool_reaped_claims += reaped_claims
                self._spool_reaped_tmp += reaped_tmp
                self._spool_pruned_results += pruned

    def _try_claim(self, claim_path: str) -> str:
        """One attempt at the executor election: ``"won"`` | ``"held"``
        (a live peer owns it) | ``"error"`` (spool unusable — execute
        locally, the plane is an optimization)."""
        nonce = uuid.uuid4().hex
        payload = json.dumps(
            {
                "owner": fleet_bus._process_owner,
                "nonce": nonce,
                "pid": os.getpid(),
                "expiresAtMs": int(time.time() * 1000) + self._sf_claim_ms,
                # the claimant's trace id: waiting losers link their
                # root span to the winner's trace (cross-process
                # single-flight shows up as ONE logical execution in
                # the obs plane; absent with obs off)
                "traceId": obs_trace.current_trace_id(),
            }
        )
        try:
            if file_utils.atomic_write_if_absent(claim_path, payload):
                return "won"
            # held: by a live winner, or leaked by a dead one (kill -9
            # mid-serve) — the lease decides, exactly like the writer
            # and pin leases
            try:
                with open(claim_path, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
                expires = int(doc["expiresAtMs"])
            except (OSError, ValueError, KeyError, TypeError):
                expires = 0  # torn/vanished: treat as expired
            if expires <= int(time.time() * 1000):
                # takeover by atomic REPLACE, never delete+create: a
                # delete could destroy a racing contender's fresh claim
                # and elect two winners. Racers overwrite each other;
                # the settle-then-verify read picks exactly one (last
                # write) and the others keep waiting.
                file_utils.atomic_overwrite(claim_path, payload)
                time.sleep(0.002)
                try:
                    with open(claim_path, "r", encoding="utf-8") as fh:
                        if json.load(fh).get("nonce") == nonce:
                            return "won"
                except (OSError, ValueError):
                    pass
            return "held"
        except OSError:
            return "error"

    def _execute_pinned(self, plan, pin: Optional[Tuple]):
        if not self._sf_enabled or not pin:
            # unpinned/degraded serves skip the plane: their identity is
            # not snapshot-addressed, so sharing would be unsound
            return super()._execute_pinned(plan, pin)
        digest = self._plan_digest(plan, pin)
        if digest is None:
            return super()._execute_pinned(plan, pin)
        if self._fast_enabled and self._router is not None:
            out = self._fast_serve(digest, plan, pin)
            if out is not None:
                return out
        return self._execute_durable(digest, plan, pin)

    # -- wake registry (durable losers park on a result-ready push) ----------
    def _register_wake(self, digest: str) -> threading.Event:
        with self._lock:
            entry = self._wake_events.get(digest)
            if entry is None:
                entry = [threading.Event(), 0]
                self._wake_events[digest] = entry
            entry[1] += 1
            return entry[0]

    def _unregister_wake(self, digest: str) -> None:
        with self._lock:
            entry = self._wake_events.get(digest)
            if entry is not None:
                entry[1] -= 1
                if entry[1] <= 0:
                    self._wake_events.pop(digest, None)

    def _execute_durable(self, digest: str, plan, pin):
        """The claim/spool election — the always-correct plane the fast
        path degrades to. Losers park on a result-ready push (roofed by
        the spool poll cadence) and retry the claim with jittered
        exponential backoff instead of hammering it at a fixed rate."""
        result_path = os.path.join(self._spool_dir, digest + ".arrow")
        claim_path = os.path.join(self._spool_dir, digest + ".claim")
        deadline = time.monotonic() + self._sf_wait_s
        waiting = False
        losses = 0
        next_claim_at = 0.0
        wake: Optional[threading.Event] = None
        t_wait0: Optional[float] = None

        def _note_poll_wait() -> None:
            if t_wait0 is not None:
                with self._lock:
                    self._poll_waits += 1
                    self._poll_wait_ms_total += (
                        time.monotonic() - t_wait0
                    ) * 1000.0

        try:
            while True:
                out = self._read_spool(result_path)
                if out is not None:
                    with self._lock:
                        self._spool_hits += 1
                    _note_poll_wait()
                    # link loser -> winner: the result's trace sidecar
                    # names the executing process's trace, so a cross-
                    # process dedup reads as ONE logical execution
                    obs_trace.event(
                        "spool_hit",
                        digest=digest,
                        winner_trace_id=self._read_trace_sidecar(result_path),
                    )
                    self._fast_cache_put(digest, out)
                    return out
                now = time.monotonic()
                verdict = None
                if now >= next_claim_at:
                    with self._lock:
                        self._election_attempts += 1
                    _election_attempts_total.inc()
                    verdict = self._try_claim(claim_path)
                    if verdict == "won":
                        with self._lock:
                            self._claims_won += 1
                            self._election_wins += 1
                        _election_wins_total.inc()
                        _note_poll_wait()
                        obs_trace.event("singleflight_won", digest=digest)
                        try:
                            out = super()._execute_pinned(plan, pin)
                        except BaseException:
                            # free the peers immediately: a failed winner
                            # must not make every waiter ride out the
                            # claim lease
                            file_utils.delete(claim_path)
                            raise
                        # sidecar BEFORE the result: a loser polling every
                        # 2ms must never see the .arrow without its link
                        self._write_trace_sidecar(result_path)
                        self._write_spool(result_path, out)
                        file_utils.delete(claim_path)
                        if self._router is not None:
                            # wake parked losers NOW, not a poll later
                            self._router.push_to_peers(
                                {"type": "result_ready", "digest": digest}
                            )
                        self._fast_cache_put(digest, out)
                        return out
                    if verdict == "held":
                        losses += 1
                        with self._lock:
                            self._election_losses += 1
                        _election_losses_total.inc()
                        delay = min(
                            _ELECTION_BACKOFF_CAP_S,
                            _ELECTION_BACKOFF_BASE_S * (1 << min(losses, 6)),
                        )
                        next_claim_at = now + delay * (
                            0.5 + random.random()
                        )
                if verdict == "error" or now >= deadline:
                    # forfeits the dedup win, never the answer
                    with self._lock:
                        self._sf_local += 1
                    _note_poll_wait()
                    return super()._execute_pinned(plan, pin)
                if not waiting:
                    waiting = True
                    t_wait0 = now
                    with self._lock:
                        self._claim_waits += 1
                    obs_trace.event(
                        "singleflight_wait",
                        digest=digest,
                        winner_trace_id=self._read_claim_trace(claim_path),
                    )
                    wake = self._register_wake(digest)
                timeout = min(
                    _SPOOL_POLL_S,
                    max(0.001, next_claim_at - time.monotonic()),
                    max(0.001, deadline - time.monotonic()),
                )
                if wake is not None:
                    if wake.wait(timeout):
                        wake.clear()
                else:
                    time.sleep(timeout)
        finally:
            if wake is not None:
                self._unregister_wake(digest)

    # -- trace linkage (docs/observability.md; best-effort everywhere) -------
    def _write_trace_sidecar(self, result_path: str) -> None:
        """Publish the winner's trace id next to its spooled result so
        later spool hits can link to it (claim files vanish at commit)."""
        trace_id = obs_trace.current_trace_id()
        if trace_id is None:
            return
        try:
            file_utils.atomic_overwrite(
                result_path + ".trace", json.dumps({"traceId": trace_id})
            )
        except OSError:
            pass

    def _read_trace_sidecar(self, result_path: str) -> Optional[str]:
        try:
            with open(result_path + ".trace", "r", encoding="utf-8") as fh:
                return json.load(fh).get("traceId")
        except (OSError, ValueError):
            return None

    def _read_claim_trace(self, claim_path: str) -> Optional[str]:
        try:
            with open(claim_path, "r", encoding="utf-8") as fh:
                return json.load(fh).get("traceId")
        except (OSError, ValueError):
            return None

    # -- introspection / lifecycle ------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        router = self._router
        with self._lock:
            out["fleet"] = {
                "spool_hits": self._spool_hits,
                "claims_won": self._claims_won,
                "claim_waits": self._claim_waits,
                "singleflight_local": self._sf_local,
                "election_attempts": self._election_attempts,
                "election_wins": self._election_wins,
                "election_losses": self._election_losses,
                "spool_reaped_traces": self._spool_reaped_traces,
                "spool_reaped_claims": self._spool_reaped_claims,
                "spool_reaped_tmp": self._spool_reaped_tmp,
                "spool_pruned_results": self._spool_pruned_results,
                "spool_publishes": self._spool_publishes,
                "spool_publish_drops": self._spool_publish_drops,
                "bus_events": self._bus_events,
                "bus_evicted": self._bus_evicted,
                "bus_installed": self._bus_installed,
                "bus_published": self._bus.published,
                # fast plane (0/1 per frontend so merged snapshots count
                # fast-armed members; merge_snapshots sums counters)
                "fast_frontends": int(router is not None),
                "fast_result_hits": self._fast_result_hits,
                "fast_dedup_joins": self._fast_dedup_joins,
                "fast_handoffs": self._fast_handoffs,
                "fast_fallbacks": self._fast_fallbacks,
                "fast_requests_served": self._fast_requests_served,
                "fast_push_received": self._fast_push_received,
                "fast_wakes": self._fast_wakes,
                "gossip_received": self._gossip_received,
                "fast_result_cache_bytes": self._fast_results_bytes,
                "fast_wait_ms_total": round(self._fast_wait_ms_total, 3),
                "fast_waits": self._fast_waits,
                "poll_wait_ms_total": round(self._poll_wait_ms_total, 3),
                "poll_waits": self._poll_waits,
            }
        if router is not None:
            out["fleet"]["fast_push_sent"] = router.push_sent
            out["fleet"]["gossip_sent"] = router.gossip_sent
            out["fleet"]["members_reaped"] = router.members_reaped
        return out

    def close(self, wait: bool = True) -> None:
        if self._router is not None:
            self._router.stop()
        if self._publish_q is not None:
            self._publish_q.put(None)
            if self._publish_thread is not None:
                self._publish_thread.join(timeout=5.0)
        self._bus.stop()
        super().close(wait=wait)
