"""Per-machine dispatch calibration for the host/native/device split.

The dispatch policy in ``ops/sort.py`` / ``ops/hash.py`` needs two kinds
of crossover point per op:

* **native min rows** — below it numpy's vectorized passes beat the
  native C++ kernel's ctypes/threading overhead; above it the native
  kernel wins (adaptive radix lexsort, single-pass murmur3);
* **host max rows** — above it a device dispatch (transfer + kernel +
  readback) would beat the host; below it transfer dominates.

Round 5 baked one topology's measurements into module constants (VERDICT
weak #4: "one-topology dispatch constants"). This module replaces them
with a **measured** probe: a few-hundred-millisecond microbenchmark run
once per machine and cached as JSON next to the native ``.so`` cache
(same ``_cache_dir`` policy: package dir when writable, else XDG). The
cache is keyed by the machine fingerprint (cpu count, platform, probe
version); a changed fingerprint re-probes.

The ops constants remain as FALLBACK DEFAULTS: calibration disabled
(``HS_CALIBRATE=0``), probe failure, or a direct test override of the
constant all fall back to them (see ``_host_sort_max_rows`` in
``ops/sort.py``). A field value of 0 here means "no measurement — use
the fallback".

Device probing is skipped on the CPU backend: the "device" is the same
host CPU plus XLA dispatch overhead, so the host path wins by
construction and the probe would only burn a compile. On an accelerator
(tpu/gpu) the probe times one padded-shape device lexsort/hash against
the host path at doubling sizes and records the crossover (or "host
always wins" as an effectively-infinite threshold, which is what the
round-5 tunnel-attached chip measured).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Optional

import numpy as np

_log = logging.getLogger("hyperspace_tpu.native.calibrate")

# Bump when the probe methodology changes; stale cache files re-probe.
_PROBE_VERSION = 6

# Effectively-infinite row count: "this engine never loses on this
# machine" (e.g. host vs device on a CPU backend, or a tunnel-attached
# chip where transfer always dominates).
_NEVER = 1 << 62

# Candidate native-vs-numpy crossover sizes. Bounded so the whole probe
# stays well under a second: each size is timed with a handful of reps
# of ops that run in at most a few ms at the top size.
_NATIVE_PROBE_SIZES = [1 << 12, 1 << 13, 1 << 14, 1 << 15, 1 << 16, 1 << 17]

# Host-vs-device probe sizes (accelerator backends only). Each size pays
# one XLA compile on first touch; the result is cached per machine so
# the cost is once-ever, not per-session.
_DEVICE_PROBE_SIZES = [1 << 18, 1 << 20, 1 << 22]


@dataclasses.dataclass(frozen=True)
class Thresholds:
    """Measured dispatch thresholds; 0 = no measurement (use fallback)."""

    host_sort_max_rows: int = 0
    native_sort_min_rows: int = 0
    host_hash_max_rows: int = 0
    native_hash_min_rows: int = 0
    native_partition_min_rows: int = 0
    native_expand_min_rows: int = 0
    native_gather_min_rows: int = 0
    native_range_mask_min_rows: int = 0
    native_fused_pipeline_min_rows: int = 0
    exchange_compact_min_rows: int = 0
    source: str = "defaults"


_DEFAULTS = Thresholds()
_cached: Optional[Thresholds] = None
# Re-entrancy guard: the device probe calls the ops dispatch functions
# (lexsort_perm / bucket_ids_host), which consult thresholds() — while a
# probe is running they must see the defaults, not recurse into a probe.
_probing = False
# One probe per process: without this the session warm thread and the
# first query thread could both probe (duplicate work, interleaved
# timings). RLock, not Lock — the probe re-enters thresholds() on its
# own thread via the ops dispatch (see _probing above).
_probe_lock = threading.RLock()


def _enabled() -> bool:
    return os.environ.get("HS_CALIBRATE", "1") != "0"


def _machine_key() -> dict:
    from hyperspace_tpu import native

    try:
        import jax

        platform = jax.default_backend()
    # any jax failure (missing install, no backend, plugin crash) must
    # degrade to a host-only fingerprint, never break thresholds()
    except Exception:  # hslint: disable=HS402
        platform = "none"
    return {
        "version": _PROBE_VERSION,
        "cpus": native._cores(),
        "platform": platform,
    }


def _cache_file() -> str:
    from hyperspace_tpu import native

    return os.path.join(native._cache_dir(), "_hs_calibration.json")


def _time_best(fn, reps: int = 3) -> float:
    """Best-of-reps wall time — the right statistic for a crossover probe
    (interference only ever slows a trial down)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


class _NativeBusy(Exception):
    """Another thread holds the native build lock (one-time g++ run).
    Probing now would block a query thread behind the compile — abort
    without caching so a later call (post-compile) measures for real."""


def _native_lib_or_busy():
    """``native.load(wait=False)``, distinguishing "unavailable" (None —
    probe the numpy-only crossover) from "mid-compile" (_NativeBusy)."""
    from hyperspace_tpu import native

    lib = native.load(wait=False)
    if lib is None and native._lib is None and not native._load_failed:
        raise _NativeBusy
    return lib


def _probe_native_sort_min() -> int:
    """Smallest probe size where the native lexsort beats np.lexsort, or
    0 when the native kernel is unavailable / never wins in range."""
    from hyperspace_tpu import native

    if _native_lib_or_busy() is None:
        return 0
    rng = np.random.default_rng(42)
    for n in _NATIVE_PROBE_SIZES:
        # the build shape: a narrow-range major plane over random minors
        planes = np.ascontiguousarray(
            np.stack(
                [
                    rng.integers(0, 256, n).astype(np.uint32),
                    rng.integers(0, 2**32, n, dtype=np.uint64).astype(
                        np.uint32
                    ),
                ]
            )
        )
        t_native = _time_best(lambda: native.lexsort_u32(planes))
        t_numpy = _time_best(lambda: np.lexsort(planes[::-1]))
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2  # native loses in range: keep it rare


def _probe_native_hash_min() -> int:
    from hyperspace_tpu import native

    if _native_lib_or_busy() is None:
        return 0
    from hyperspace_tpu.ops import hash as hash_mod

    rng = np.random.default_rng(43)
    for n in _NATIVE_PROBE_SIZES:
        reps = rng.integers(-(2**62), 2**62, size=(1, n), dtype=np.int64)
        t_native = _time_best(lambda: native.bucket_ids_i64(reps, 200))
        t_numpy = _time_best(
            lambda: hash_mod.bucket_ids_numpy(reps, 200)
        )
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_native_partition_min() -> int:
    """Crossover for the counting-scatter partition kernel vs its numpy
    twin. Probed separately from the lexsort: the scatter is O(n) with
    near-zero per-row work, so its ctypes overhead amortizes at a very
    different size than the radix sort's."""
    from hyperspace_tpu import native
    from hyperspace_tpu.ops import sort as sort_mod

    if _native_lib_or_busy() is None:
        return 0
    rng = np.random.default_rng(45)
    for n in _NATIVE_PROBE_SIZES:
        ids = rng.integers(0, 200, n).astype(np.int32)
        t_native = _time_best(
            lambda: native.partition_by_bucket_i32(ids, 200)
        )
        t_numpy = _time_best(
            lambda: sort_mod.partition_by_bucket_numpy(ids, 200)
        )
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_native_expand_min() -> int:
    """Crossover for the match-range expansion kernel vs the numpy
    repeat/cumsum chain — probed at the PAIR count (the dispatch unit of
    ``ops/join.expand_match_ranges``)."""
    from hyperspace_tpu import native
    from hyperspace_tpu.ops import join as join_mod

    if _native_lib_or_busy() is None:
        return 0
    rng = np.random.default_rng(46)
    for n in _NATIVE_PROBE_SIZES:
        # serve shape: most left rows match 0-2 right rows
        cnt = rng.integers(0, 3, n).astype(np.int64)
        lo = rng.integers(0, n, n).astype(np.int64)
        total = int(cnt.sum())
        t_native = _time_best(
            lambda: native.expand_match_ranges_i64(lo, cnt, total)
        )
        t_numpy = _time_best(
            lambda: join_mod.expand_match_ranges_numpy(lo, cnt)
        )
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_native_gather_min() -> int:
    """Crossover for the threaded native gather vs numpy fancy indexing
    (the serve join's assemble stage), probed at the INDEX count."""
    from hyperspace_tpu import native

    if _native_lib_or_busy() is None:
        return 0
    rng = np.random.default_rng(47)
    for n in _NATIVE_PROBE_SIZES:
        src = rng.integers(-(2**62), 2**62, n, dtype=np.int64)
        idx = rng.integers(0, n, n).astype(np.int64)
        t_native = _time_best(lambda: native.gather_i64(src, idx))
        t_numpy = _time_best(lambda: src[idx])
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_native_range_mask_min() -> int:
    """Crossover for the fused range-mask kernel vs its numpy twin,
    probed at the ROW count with a serve-shaped predicate (two int64
    bound terms + one float64 term, ~10% selectivity)."""
    from hyperspace_tpu import native

    if _native_lib_or_busy() is None:
        return 0
    rng = np.random.default_rng(48)
    for n in _NATIVE_PROBE_SIZES:
        a = rng.integers(0, 1 << 20, n, dtype=np.int64)
        b = rng.integers(0, 1 << 20, n, dtype=np.int64)
        c = rng.normal(0.0, 1.0, n)
        cols = [a, b, c.view(np.float64)]
        valids = [None, None, None]
        is_f64 = [False, False, True]
        lo_i = [1000, 0, 0]
        hi_i = [110000, 200000, 0]
        lo_f = [0.0, 0.0, -1.0]
        hi_f = [0.0, 0.0, 1.0]
        flags = [
            (True, True, False, True),
            (True, True, False, False),
            (True, True, True, False),
        ]
        t_native = _time_best(
            lambda: native.range_mask_u8(
                cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags, n
            )
        )
        t_numpy = _time_best(
            lambda: (a >= 1000)
            & (a < 110000)
            & (b >= 0)
            & (b <= 200000)
            & (c > -1.0)
            & (c <= 1.0)
        )
        if t_native < t_numpy:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_native_fused_pipeline_min() -> int:
    """Crossover for the fused serve-pipeline pass
    (``hs_fused_filter_agg``) vs the interpreted chain (mask → filtered
    batch → factorize → segment reductions), probed at the SCANNED row
    count with a serve-shaped workload: a two-term predicate (~50%
    selective), one ~200-ary int64 group key, and count/sum/min
    aggregates."""
    from hyperspace_tpu.execution import pipeline_compiler as pc
    from hyperspace_tpu.io.columnar import Column, ColumnarBatch
    from hyperspace_tpu.plan.nodes import AggSpec

    if _native_lib_or_busy() is None:
        return 0
    import pyarrow as pa

    rng = np.random.default_rng(49)
    schema = {"k": pa.int64(), "a": pa.int64(), "b": pa.float64()}
    terms = (
        ("a", 1000, False, 110000, True, False),
        ("b", -1.0, True, None, False, False),
    )
    group_by = ["k"]
    aggs = [
        AggSpec("count", None, "n"),
        AggSpec("sum", "b", "s"),
        AggSpec("min", "a", "m"),
    ]
    for n in _NATIVE_PROBE_SIZES:
        batch = ColumnarBatch(
            {
                "k": Column(
                    "numeric",
                    pa.int64(),
                    values=rng.integers(0, 200, n, dtype=np.int64),
                ),
                "a": Column(
                    "numeric",
                    pa.int64(),
                    values=rng.integers(0, 1 << 18, n, dtype=np.int64),
                ),
                "b": Column(
                    "numeric", pa.float64(), values=rng.normal(0.0, 1.0, n)
                ),
            }
        )
        if (
            pc.kernel_filter_aggregate(batch, terms, group_by, aggs, schema)
            is None
        ):
            return 0  # kernel unavailable: fallback constant decides
        t_native = _time_best(
            lambda: pc.kernel_filter_aggregate(
                batch, terms, group_by, aggs, schema
            )
        )
        t_interp = _time_best(
            lambda: pc.interpreted_filter_aggregate(
                batch, terms, group_by, aggs, schema
            )
        )
        if t_native < t_interp:
            return n
    return _NATIVE_PROBE_SIZES[-1] * 2


def _probe_exchange_compact_min(platform: str) -> int:
    """Exchange-strategy crossover (``parallel/shuffle.py``): the
    smallest probe size where the ``compact`` host-packed exchange beats
    the ``flat`` padded all_to_all on this machine's device mesh, or 0
    when no crossover was measured (auto keeps ``flat``).

    Skipped on CPU backends outright — ``auto`` resolves a CPU mesh to
    the ``host`` strategy before ever consulting this threshold, so the
    probe would only burn compiles. On an accelerator the probe pays one
    compile per (strategy, size), cached per machine like the other
    device probes."""
    if platform in ("cpu", "none"):
        return 0
    import jax

    if len(jax.devices()) < 2:
        return 0
    if jax.process_count() > 1:
        # never run collectives from a lazily-triggered per-host probe
        # (peers may not be probing -> hang), and a multi-process job
        # coerces every strategy to twostage anyway — the threshold is
        # never consulted there
        return 0
    from hyperspace_tpu.parallel.mesh import default_mesh
    from hyperspace_tpu.parallel import shuffle as shuffle_mod

    mesh = default_mesh()
    rng = np.random.default_rng(50)
    for n in _DEVICE_PROBE_SIZES:
        reps = rng.integers(-(2**62), 2**62, size=(1, n), dtype=np.int64)
        payloads = [reps[0], rng.normal(0.0, 1.0, n)]

        def run(strategy):
            shuffle_mod.bucket_shuffle(
                mesh, reps, payloads, 200, strategy=strategy
            )

        run("flat")  # warm both compiles out of the measurement
        run("compact")
        if _time_best(lambda: run("compact")) < _time_best(
            lambda: run("flat")
        ):
            return n
    return 0


def _probe_host_max(op: str, platform: str) -> int:
    """Smallest size where the device beats the host for ``op`` ("sort" |
    "hash"), extrapolated monotonic; _NEVER when the host wins at every
    probe size (transfer-dominated topologies)."""
    if platform in ("cpu", "none"):
        # the "device" IS this host CPU plus dispatch overhead
        return _NEVER
    import jax.numpy as jnp

    from hyperspace_tpu.ops import pad_len
    from hyperspace_tpu.ops import hash as hash_mod
    from hyperspace_tpu.ops import sort as sort_mod

    rng = np.random.default_rng(44)
    for n in _DEVICE_PROBE_SIZES:
        if op == "sort":
            planes = rng.integers(
                0, 2**32, size=(2, n), dtype=np.uint64
            ).astype(np.uint32)

            def host():
                sort_mod.lexsort_perm(planes)

            n_pad = pad_len(n)
            padded = np.concatenate(
                [
                    planes,
                    np.full((2, n_pad - n), np.uint32(0xFFFFFFFF)),
                ],
                axis=1,
            )

            def device():
                np.asarray(sort_mod.lexsort_indices(jnp.asarray(padded)))

        else:
            reps = rng.integers(-(2**62), 2**62, size=(1, n), dtype=np.int64)

            def host():
                hash_mod.bucket_ids_host(reps, 200)

            words = hash_mod.split_words_np(reps)
            n_pad = pad_len(n)
            padded = np.concatenate(
                [words, np.zeros((2, n_pad - n), dtype=np.uint32)], axis=1
            )

            def device():
                np.asarray(
                    hash_mod._bucket_ids_words(jnp.asarray(padded), 200, 42)
                )

        device()  # warm the compile out of the measurement
        if _time_best(device) < _time_best(host):
            return n
    return _NEVER


def _probe() -> Thresholds:
    key = _machine_key()
    t0 = time.perf_counter()
    # Fail fast when the warm thread is mid-compile of the native .so:
    # on an accelerator the device probe below pays multi-second XLA
    # compiles, all discarded if a later native probe raises _NativeBusy.
    _native_lib_or_busy()
    out = Thresholds(
        host_sort_max_rows=_probe_host_max("sort", key["platform"]),
        native_sort_min_rows=_probe_native_sort_min(),
        host_hash_max_rows=_probe_host_max("hash", key["platform"]),
        native_hash_min_rows=_probe_native_hash_min(),
        native_partition_min_rows=_probe_native_partition_min(),
        native_expand_min_rows=_probe_native_expand_min(),
        native_gather_min_rows=_probe_native_gather_min(),
        native_range_mask_min_rows=_probe_native_range_mask_min(),
        native_fused_pipeline_min_rows=_probe_native_fused_pipeline_min(),
        exchange_compact_min_rows=_probe_exchange_compact_min(
            key["platform"]
        ),
        source="calibrated",
    )
    _log.info(
        "dispatch calibration probed in %.0fms: %s",
        (time.perf_counter() - t0) * 1e3,
        out,
    )
    return out


def _load_cache() -> Optional[Thresholds]:
    try:
        with open(_cache_file(), "r") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if data.get("machine") != _machine_key():
        return None
    t = data.get("thresholds", {})
    try:
        return Thresholds(
            host_sort_max_rows=int(t["host_sort_max_rows"]),
            native_sort_min_rows=int(t["native_sort_min_rows"]),
            host_hash_max_rows=int(t["host_hash_max_rows"]),
            native_hash_min_rows=int(t["native_hash_min_rows"]),
            native_partition_min_rows=int(t["native_partition_min_rows"]),
            native_expand_min_rows=int(t["native_expand_min_rows"]),
            native_gather_min_rows=int(t["native_gather_min_rows"]),
            native_range_mask_min_rows=int(
                t["native_range_mask_min_rows"]
            ),
            native_fused_pipeline_min_rows=int(
                t["native_fused_pipeline_min_rows"]
            ),
            exchange_compact_min_rows=int(t["exchange_compact_min_rows"]),
            source="calibrated",
        )
    except (KeyError, TypeError, ValueError):
        return None


def _store_cache(t: Thresholds) -> None:
    """Publish the calibration JSON with write-to-temp + atomic rename.

    This is the concurrency pattern documented in
    ``docs/static-analysis.md`` (HS502 worked example): two processes
    calibrating concurrently must never let a reader interleave with a
    partial write. The temp name is pid-qualified so concurrent writers
    never clobber each other's temp, ``os.replace`` makes the publish
    atomic (readers see the old file or the new file, never a torn one),
    and the fsync before rename keeps a crash from publishing an empty
    file on journaled filesystems. Losing the last-writer race is fine:
    both writers hold equivalent measurements for this machine key.
    """
    path = _cache_file()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(
                {
                    "machine": _machine_key(),
                    "thresholds": {
                        k: getattr(t, k)
                        for k in (
                            "host_sort_max_rows",
                            "native_sort_min_rows",
                            "host_hash_max_rows",
                            "native_hash_min_rows",
                            "native_partition_min_rows",
                            "native_expand_min_rows",
                            "native_gather_min_rows",
                            "native_range_mask_min_rows",
                            "native_fused_pipeline_min_rows",
                            "exchange_compact_min_rows",
                        )
                    },
                },
                f,
                indent=2,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def thresholds() -> Thresholds:
    """The machine's dispatch thresholds: cached measurement, else a
    fresh probe (cached for later processes), else the zeroed defaults
    (callers fall back to their constants)."""
    global _cached, _probing
    if _cached is not None:
        return _cached
    if _probing or not _enabled():
        return _DEFAULTS
    # Lock-held I/O by design: the JSON cache read/write and the probe
    # itself are what the lock serializes (one probe per process); the
    # lock-free _cached fast path above keeps queries off this lock.
    with _probe_lock:  # hslint: disable=HS502
        if _cached is not None:  # another thread probed while we waited
            return _cached
        if _probing:
            return _DEFAULTS
        got = _load_cache()
        if got is None:
            _probing = True
            try:
                got = _probe()
            except _NativeBusy:
                # the session warm thread is mid-compile of the native
                # .so: don't block this (query) thread behind it and
                # don't cache a degraded measurement — defaults now, a
                # later call probes for real
                return _DEFAULTS
            # catch-all is the contract: a failed probe must cost only the
            # fallback constants, never a query
            except Exception as exc:  # hslint: disable=HS402
                _log.warning(
                    "dispatch calibration failed; using defaults: %s", exc
                )
                got = _DEFAULTS
            else:
                _store_cache(got)
            finally:
                _probing = False
        _cached = got
        return _cached


def invalidate() -> None:
    """Forget the in-process memo (tests; a config flip mid-process).
    Takes the probe lock: a rebind racing a mid-probe publish must not
    resurrect the dropped value (HS602, SHARED_STATE)."""
    global _cached
    with _probe_lock:
        _cached = None
