"""Optional native (C++) host kernels.

The TPU compute path is JAX/XLA; this package accelerates the HOST side
of the pipeline, where the dispatch policy (see ``ops/sort.py``) keeps
host-resident batches because transfer to a tunnel-attached chip dwarfs
the compute. The one hot host op is the stable multi-plane lexsort behind
the bucketed sorted write (reference:
``index/DataFrameWriterExtensions.scala:58-67``).

The kernel is compiled from ``hs_native.cpp`` on first use with ``g++``
and cached next to the source, keyed by a hash of the source so edits
rebuild automatically. Everything degrades gracefully: no compiler, a
failed build, or ``HS_NATIVE=0`` all fall back to the numpy twins with
identical (stable) semantics — callers treat ``None`` from the wrappers
as "use numpy".
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "hs_native.cpp")
_lock = threading.Lock()
_lib = None
_load_failed = False


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(os.path.dirname(__file__), f"_hs_native_{digest}.so")


def _compile(path: str) -> bool:
    """Build the shared library; atomic publish via rename so concurrent
    processes never load a half-written file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    # No -march=native: the kernel is scalar counting-sort (memory-bound,
    # nothing to vectorize), and a cached .so may outlive the machine it
    # was built on (baked image, shared filesystem) — ISA-specific code
    # would then SIGILL with no chance for the numpy fallback to engage.
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load():
    """The loaded CDLL, or None when native kernels are unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("HS_NATIVE", "1") == "0":
            _load_failed = True
            return None
        path = _cache_path()
        if not os.path.exists(path) and not _compile(path):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.hs_lexsort_u32.restype = ctypes.c_int
            lib.hs_lexsort_u32.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int32,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32,
            ]
        except (OSError, AttributeError):
            _load_failed = True
            return None
        _lib = lib
        return _lib


def _n_threads() -> int:
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return max(1, min(cores, 16))


def lexsort_u32(planes: np.ndarray) -> Optional[np.ndarray]:
    """Stable ascending lexsort permutation by uint32 ``planes`` [k, n]
    (plane 0 major) — bit-identical to ``np.lexsort(planes[::-1])``.
    Returns None when the native kernel is unavailable, so callers fall
    back to numpy."""
    lib = load()
    if lib is None:
        return None
    planes = np.ascontiguousarray(planes, dtype=np.uint32)
    k, n = planes.shape
    out = np.empty(n, dtype=np.int64)
    ptrs = (ctypes.c_void_p * k)(
        *(planes[i].ctypes.data for i in range(k))
    )
    rc = lib.hs_lexsort_u32(
        ptrs,
        ctypes.c_int32(k),
        ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(_n_threads()),
    )
    if rc != 0:
        return None
    return out
