"""Optional native (C++) host kernels.

The TPU compute path is JAX/XLA; this package accelerates the HOST side
of the pipeline, where the dispatch policy (see ``ops/sort.py``) keeps
host-resident batches because transfer to a tunnel-attached chip dwarfs
the compute. Three hot host ops live here (measured on the bench chip,
4M rows): the stable multi-plane radix lexsort behind the bucketed
sorted write (3.3x over np.lexsort; reference:
``index/DataFrameWriterExtensions.scala:58-67``), the murmur3 bucket-id
hash (8.6x over the vectorized numpy mix), and the linear merge-join
behind the co-bucketed serve join (O(n+m+pairs) with biased emit
straight into preallocated pair buffers).

The kernels are compiled from ``hs_native.cpp`` on first use with
``g++`` and cached next to the source, keyed by a hash of the source so
edits rebuild automatically. Everything degrades gracefully: no
compiler, a failed build (negative-cached via a ``.failed`` marker
holding the compiler stderr), or ``HS_NATIVE=0`` all fall back to the
numpy twins with identical (stable) semantics — callers treat ``None``
from the wrappers as "use numpy".
"""

from __future__ import annotations

import ctypes
import glob
import hashlib
import logging
import os
import subprocess
import threading
import time as _time
from typing import Optional, Tuple

import numpy as np

from hyperspace_tpu.testing import faults as _faults

_log = logging.getLogger("hyperspace_tpu.native")

_SRC = os.path.join(os.path.dirname(__file__), "hs_native.cpp")
_lock = threading.Lock()
_lib = None
_load_failed = False

# Machine-checked parity registry (hslint HS1xx, hyperspace_tpu/analysis):
# every extern "C" export in hs_native.cpp maps to (ctypes wrapper defined
# in this module, numpy twin the differential tests compare against).
# Adding a kernel without registering it here — or without a test in
# tests/ referencing it — fails `python -m hyperspace_tpu.analysis`.
KERNEL_TWINS = {
    "hs_lexsort_u32": ("lexsort_u32", "numpy.lexsort"),
    "hs_partition_by_bucket": (
        "partition_by_bucket_i32",
        "hyperspace_tpu.ops.sort.partition_by_bucket_numpy",
    ),
    "hs_merge_join_count_i64": (
        "merge_join_count_i64",
        "hyperspace_tpu.execution.join_exec.merge_join_indices",
    ),
    "hs_merge_join_emit_i64": (
        "merge_join_emit_into",
        "hyperspace_tpu.execution.join_exec.merge_join_indices",
    ),
    "hs_bucket_ids_i64": (
        "bucket_ids_i64",
        "hyperspace_tpu.ops.hash.bucket_ids_numpy",
    ),
    "hs_expand_match_ranges_i64": (
        "expand_match_ranges_i64",
        "hyperspace_tpu.ops.join.expand_match_ranges_numpy",
    ),
    "hs_gather_i64": ("gather_i64", "numpy.take"),
    "hs_gather_f64": ("gather_f64", "numpy.take"),
    "hs_range_mask": (
        "range_mask_u8",
        "hyperspace_tpu.ops.filter.range_mask_numpy",
    ),
    # Fused-pipeline exports (docs/serve-compiler.md): the registered
    # twin is the INTERPRETED CHAIN the kernel replaces, not a single
    # numpy op — hslint HS105 enforces an in-package pipeline twin for
    # every hs_fused_* export, so whole-pipeline parity is what the
    # differential tests witness.
    "hs_fused_filter_select": (
        "fused_filter_select",
        "hyperspace_tpu.execution.pipeline_compiler.filter_select_interpreted",
    ),
    "hs_fused_filter_agg": (
        "fused_filter_agg",
        "hyperspace_tpu.execution.pipeline_compiler.interpreted_filter_aggregate",
    ),
}


def _cache_dir() -> str:
    """Directory for the compiled .so: next to the source when writable
    (shared across users/processes, survives with the checkout), else a
    per-user cache dir (read-only site-packages installs — root-owned
    images, zipapp-adjacent layouts — must still get native kernels AND
    a persistable .failed marker)."""
    pkg = os.path.dirname(__file__)
    if os.access(pkg, os.W_OK):
        return pkg
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    path = os.path.join(base, "hyperspace_tpu", "native")
    os.makedirs(path, exist_ok=True)
    return path


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_cache_dir(), f"_hs_native_{digest}.so")


# How long another source revision's .so/.failed artifacts survive in a
# shared cache dir before cleanup removes them. Deleting them eagerly
# made two checkouts sharing one XDG cache recompile on every
# alternating process start (each start destroyed the other's .so); the
# age gate keeps every ACTIVE revision's artifacts while still
# reclaiming truly-stale ones. "Active" is tracked via mtime: load()
# touches the .so on every successful CDLL load (atime is unreliable —
# relatime/noatime mounts), so a revision some process still uses never
# ages past the threshold, while a genuinely abandoned one does.
_SUPERSEDED_TTL_S = 7 * 24 * 3600.0


def _cleanup_superseded(keep: str) -> None:
    """Drop STALE artifacts of other source revisions (the cache is keyed
    by a source hash, so every edit would otherwise strand one .so
    forever — a real leak on shared filesystems and baked images) and
    ORPHANED ``.tmp.<pid>`` compile scratch files (a SIGKILLed g++ leaves
    one behind; nothing else ever reclaims it). Only artifacts older
    than ``_SUPERSEDED_TTL_S`` are removed: a younger .so likely belongs
    to another live checkout sharing this cache dir (two checkouts
    deleting each other's .so recompile forever), and a younger tmp may
    be another process mid-compile — unlinking its tmp would fail its
    ``os.replace`` and latch a bogus .failed marker. A week-old tmp is
    unambiguously an orphan, whatever revision it belongs to."""
    pattern = os.path.join(os.path.dirname(keep), "_hs_native_*")
    now = _time.time()
    for old in glob.glob(pattern):
        # tmp files are swept even for the CURRENT revision (orphans of
        # this .so's own past compiles); live artifacts of the current
        # revision (.so, .failed) are never touched
        if ".tmp." not in os.path.basename(old) and old.startswith(keep):
            continue
        try:
            if now - os.path.getmtime(old) >= _SUPERSEDED_TTL_S:
                os.unlink(old)
        except OSError:
            pass


def _compile(path: str) -> bool:
    """Build the shared library; atomic publish via rename so concurrent
    processes never load a half-written file. A failure writes a
    ``.failed`` marker with the compiler's stderr next to the source —
    later processes skip the doomed ~2s retry and operators get a
    diagnostic instead of a silent numpy fallback."""
    tmp = f"{path}.tmp.{os.getpid()}"
    # No -march=native: the kernel is scalar counting-sort (memory-bound,
    # nothing to vectorize), and a cached .so may outlive the machine it
    # was built on (baked image, shared filesystem) — ISA-specific code
    # would then SIGILL with no chance for the numpy fallback to engage.
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, path)
        _cleanup_superseded(path)
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        stderr = getattr(exc, "stderr", b"") or b""
        detail = stderr.decode("utf-8", "replace")[-2000:] or str(exc)
        # Transient failures (compiler timed out on a loaded machine,
        # ENOSPC, OOM-killed g++) must NOT latch the machine-wide negative
        # cache: this process falls back to numpy, the next one retries.
        # Only a deterministic failure — a real compile error, or no g++
        # on PATH at all (FileNotFoundError) — earns the marker; without
        # it a toolchain-less machine would retry and warn in every
        # process forever.
        transient = isinstance(
            exc, (OSError, subprocess.TimeoutExpired)
        ) and not isinstance(exc, FileNotFoundError)
        if isinstance(exc, subprocess.CalledProcessError):
            # g++ killed by a signal (negative returncode: OOM killer on
            # a loaded machine) or out of disk mid-write is transient
            # too, even though both surface as CalledProcessError.
            transient = exc.returncode < 0 or b"No space left" in stderr
        _log.warning(
            "native kernel build failed; falling back to numpy twins%s: %s",
            "" if transient else " (delete %s.failed to retry)" % path,
            detail,
        )
        if not transient:
            # temp + atomic rename (the docs/static-analysis.md pattern):
            # _failed_marker_fresh in another process must never read a
            # half-written marker or see its mtime before the content.
            marker_tmp = f"{path}.failed.tmp.{os.getpid()}"
            try:
                with open(marker_tmp, "w") as f:
                    f.write(detail)
                os.replace(marker_tmp, path + ".failed")
            except OSError:
                try:
                    os.unlink(marker_tmp)
                except OSError:
                    pass
        return False


# How long a .failed negative-cache marker disables native kernels. A
# marker older than this is treated as stale and the compile retried:
# machines change (toolchain upgrades, freed disk), and a day-old latch
# silently costing 3x on every sort is worse than one ~2s retry per day.
_FAILED_MARKER_TTL_S = 24 * 3600.0


def _failed_marker_fresh(marker: str) -> bool:
    """True when the negative-cache marker exists and is young enough to
    honor. Stale markers are removed (best effort) so the caller retries
    the compile. TTL override: HS_NATIVE_FAILED_TTL (seconds)."""
    try:
        age = _time.time() - os.path.getmtime(marker)
    except OSError:
        return False
    try:
        ttl = float(
            os.environ.get("HS_NATIVE_FAILED_TTL", _FAILED_MARKER_TTL_S)
        )
    except ValueError:
        # a malformed override must not crash load() out of a query path
        ttl = _FAILED_MARKER_TTL_S
    if age <= ttl:
        return True
    try:
        os.unlink(marker)
    except OSError:
        pass
    return False


def load(wait: bool = True):
    """The loaded CDLL, or None when native kernels are unavailable.

    ``wait=False`` returns None instead of blocking when another thread
    is mid-compile — hot paths fall back to numpy for the couple of
    seconds a background pre-warm (``HyperspaceSession`` startup) needs,
    rather than stalling a query on the one-time g++ run."""
    global _lib, _load_failed
    # Fault-injection seam (testing/faults.py, "kernel_dispatch"): every
    # kernel wrapper begins with load(wait=False), and None from a
    # wrapper IS the registered degrade path — the numpy/interpreted
    # twin (KERNEL_TWINS) with identical output. One choke point covers
    # every native dispatch, generalizing the lexsort rc-2 fallback.
    if _faults.degraded("kernel_dispatch"):
        return None
    if _lib is not None or _load_failed:
        return _lib
    # Lock-held I/O is the point here: the one-time g++ compile and CDLL
    # load are deliberately serialized so exactly one thread builds;
    # everyone else either waits (wait=True) or falls back to numpy.
    if not _lock.acquire(blocking=wait):  # hslint: disable=HS502
        return None
    try:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("HS_NATIVE", "1") == "0":
            _load_failed = True
            return None
        try:
            path = _cache_path()
        except OSError as exc:
            # stripped install (no .cpp) or unusable cache dir: numpy
            # fallback, never a crash on a query path
            _log.warning("native kernels unavailable: %s", exc)
            _load_failed = True
            return None
        if not os.path.exists(path):
            if _failed_marker_fresh(path + ".failed"):
                _log.warning(
                    "native kernel disabled: previous build failed "
                    "(see %s.failed; delete it to retry)",
                    path,
                )
                _load_failed = True
                return None
            if not _compile(path):
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(path)
            lib.hs_lexsort_u32.restype = ctypes.c_int
            lib.hs_lexsort_u32.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int32,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32,
            ]
            _i64p = ctypes.POINTER(ctypes.c_int64)
            lib.hs_merge_join_count_i64.restype = ctypes.c_int64
            lib.hs_merge_join_count_i64.argtypes = [
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
            ]
            lib.hs_merge_join_emit_i64.restype = ctypes.c_int64
            lib.hs_merge_join_emit_i64.argtypes = [
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                _i64p,
                _i64p,
            ]
            lib.hs_bucket_ids_i64.restype = ctypes.c_int
            lib.hs_bucket_ids_i64.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int32,
                ctypes.c_int64,
                ctypes.c_uint32,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.hs_partition_by_bucket.restype = ctypes.c_int
            lib.hs_partition_by_bucket.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
                ctypes.c_int32,
                _i64p,
                _i64p,
                ctypes.c_int32,
            ]
            lib.hs_expand_match_ranges_i64.restype = ctypes.c_int64
            lib.hs_expand_match_ranges_i64.argtypes = [
                _i64p,
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                _i64p,
                _i64p,
                ctypes.c_int64,
                ctypes.c_int32,
            ]
            _u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.hs_range_mask.restype = ctypes.c_int
            lib.hs_range_mask.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                _u8p,
                _i64p,
                _i64p,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                _u8p,
                _u8p,
                _u8p,
                _u8p,
                ctypes.c_int32,
                ctypes.c_int64,
                _u8p,
                ctypes.c_int32,
            ]
            _vpp = ctypes.POINTER(ctypes.c_void_p)
            _dp = ctypes.POINTER(ctypes.c_double)
            lib.hs_fused_filter_select.restype = ctypes.c_int64
            lib.hs_fused_filter_select.argtypes = [
                _vpp, _vpp, _u8p, _i64p, _i64p, _dp, _dp,
                _u8p, _u8p, _u8p, _u8p,
                ctypes.c_int32, ctypes.c_int64, _i64p, ctypes.c_int32,
            ]
            lib.hs_fused_filter_agg.restype = ctypes.c_int64
            lib.hs_fused_filter_agg.argtypes = [
                # filter terms
                _vpp, _vpp, _u8p, _i64p, _i64p, _dp, _dp,
                _u8p, _u8p, _u8p, _u8p, ctypes.c_int32,
                # group keys
                _vpp, _vpp, _u8p, ctypes.c_int32,
                # aggs
                _vpp, _vpp, _u8p, ctypes.c_int32,
                # rows
                ctypes.c_int64, ctypes.c_int64,
                # state
                _i64p, ctypes.c_int64,
                _i64p, _i64p, _u8p, _i64p, _u8p,
                _i64p, _dp, _i64p, _i64p,
                ctypes.c_int64, _i64p, _i64p, ctypes.c_int32,
            ]
            _f64p = ctypes.POINTER(ctypes.c_double)
            lib.hs_gather_i64.restype = ctypes.c_int
            lib.hs_gather_i64.argtypes = [
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int32,
            ]
            lib.hs_gather_f64.restype = ctypes.c_int
            lib.hs_gather_f64.argtypes = [
                _f64p,
                ctypes.c_int64,
                _i64p,
                ctypes.c_int64,
                _f64p,
                ctypes.c_int32,
            ]
        except (OSError, AttributeError):
            _load_failed = True
            return None
        try:
            # refresh the liveness timestamp _cleanup_superseded gates
            # on: a revision that only ever LOADS its cached .so must
            # not age past the TTL and get reaped by a sibling checkout
            os.utime(path)
        except OSError:
            pass
        # sweep stale artifacts on every successful load, not only after
        # a compile: a steady-state process never compiles, so orphaned
        # .tmp.<pid> files and superseded revisions would otherwise
        # outlive every producer
        _cleanup_superseded(path)
        _lib = lib
        return _lib
    finally:
        _lock.release()


def _cores() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _n_threads(n: int) -> int:
    """Thread count scaled to the input: one thread per ~64k rows, capped
    by cores and 16. Just-above-threshold inputs (32k rows) would
    otherwise pay 15 thread spawn/joins per byte pass for ~2k-row chunks
    — more overhead than the whole numpy sort."""
    return max(1, min(_cores(), 16, n >> 16))


def lexsort_u32(
    planes: np.ndarray, n_threads: Optional[int] = None
) -> Optional[np.ndarray]:
    """Stable ascending lexsort permutation by uint32 ``planes`` [k, n]
    (plane 0 major) — bit-identical to ``np.lexsort(planes[::-1])``.
    Returns None when the native kernel is unavailable, so callers fall
    back to numpy. ``n_threads`` overrides the size-scaled default — the
    partitioned build runs many per-bucket sorts on its own pool and
    gives each sort a slice of the core budget."""
    lib = load(wait=False)
    if lib is None:
        return None
    planes = np.ascontiguousarray(planes, dtype=np.uint32)
    k, n = planes.shape
    out = np.empty(n, dtype=np.int64)
    ptrs = (ctypes.c_void_p * k)(
        *(planes[i].ctypes.data for i in range(k))
    )
    rc = lib.hs_lexsort_u32(
        ptrs,
        ctypes.c_int32(k),
        ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(n_threads if n_threads else _n_threads(n)),
    )
    if rc != 0:
        return None
    return out


def partition_by_bucket_i32(
    bucket_ids: np.ndarray, num_buckets: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Stable counting scatter of row indices by int32 bucket id:
    ``(order, offsets)`` where ``order[offsets[b]:offsets[b+1]]`` holds
    bucket ``b``'s row indices in original order — bit-identical to
    ``np.argsort(bucket_ids, kind="stable")`` plus a bincount prefix sum
    (the numpy twin, ``ops/sort.partition_by_bucket``). Returns None when
    the native kernel is unavailable or the ids are malformed."""
    lib = load(wait=False)
    if lib is None:
        return None
    bucket_ids = np.ascontiguousarray(bucket_ids, dtype=np.int32)
    n = len(bucket_ids)
    order = np.empty(n, dtype=np.int64)
    offsets = np.empty(num_buckets + 1, dtype=np.int64)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    rc = lib.hs_partition_by_bucket(
        bucket_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n),
        ctypes.c_int32(num_buckets),
        order.ctypes.data_as(_i64p),
        offsets.ctypes.data_as(_i64p),
        ctypes.c_int32(_n_threads(n)),
    )
    if rc != 0:
        return None
    return order, offsets


def merge_join_count_i64(
    l_sorted: np.ndarray, r_sorted: np.ndarray
) -> Optional[int]:
    """Pair count of the inner join of two ASCENDING-sorted int64 key
    arrays (one linear merge, no allocation), or None when the native
    kernel is unavailable."""
    lib = load(wait=False)
    if lib is None:
        return None
    l_sorted = np.ascontiguousarray(l_sorted, dtype=np.int64)
    r_sorted = np.ascontiguousarray(r_sorted, dtype=np.int64)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    return lib.hs_merge_join_count_i64(
        l_sorted.ctypes.data_as(_i64p),
        len(l_sorted),
        r_sorted.ctypes.data_as(_i64p),
        len(r_sorted),
    )


def merge_join_emit_into(
    l_sorted: np.ndarray,
    r_sorted: np.ndarray,
    li_out: np.ndarray,
    ri_out: np.ndarray,
    l_bias: int = 0,
    r_bias: int = 0,
) -> bool:
    """Emit the join pairs (biased by l_bias/r_bias) into the caller's
    preallocated CONTIGUOUS int64 slices, whose length must equal
    ``merge_join_count_i64``'s result. Returns False when the native
    kernel is unavailable or the emitted count mismatches."""
    for out in (li_out, ri_out):
        # the kernel writes int64 through the raw base pointer — a
        # strided view or other dtype would be silently clobbered, so
        # make the contract violation loud (programming error, not a
        # fall-back condition)
        if out.dtype != np.int64 or not out.flags.c_contiguous:
            raise ValueError(
                "merge_join_emit_into requires C-contiguous int64 outputs"
            )
    lib = load(wait=False)
    if lib is None:
        return False
    l_sorted = np.ascontiguousarray(l_sorted, dtype=np.int64)
    r_sorted = np.ascontiguousarray(r_sorted, dtype=np.int64)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    emitted = lib.hs_merge_join_emit_i64(
        l_sorted.ctypes.data_as(_i64p),
        len(l_sorted),
        r_sorted.ctypes.data_as(_i64p),
        len(r_sorted),
        ctypes.c_int64(l_bias),
        ctypes.c_int64(r_bias),
        li_out.ctypes.data_as(_i64p),
        ri_out.ctypes.data_as(_i64p),
    )
    return emitted == len(li_out)


def merge_join_i64(
    l_sorted: np.ndarray, r_sorted: np.ndarray
) -> Optional[tuple]:
    """Inner-join pair indices (li, ri) of two ASCENDING-sorted int64 key
    arrays (duplicates allowed): one linear merge per pass, pairs ordered
    by left index then right index — identical to the numpy
    searchsorted + repeat expansion it replaces. Returns None when the
    native kernel is unavailable."""
    total = merge_join_count_i64(l_sorted, r_sorted)
    if total is None:
        return None
    li = np.empty(total, dtype=np.int64)
    ri = np.empty(total, dtype=np.int64)
    if total and not merge_join_emit_into(l_sorted, r_sorted, li, ri):
        return None  # pragma: no cover — would be a kernel bug
    return li, ri


def expand_match_ranges_i64(
    lo: np.ndarray,
    cnt: np.ndarray,
    total: int,
    l_map: Optional[np.ndarray] = None,
    r_map: Optional[np.ndarray] = None,
    l_bias: int = 0,
    r_bias: int = 0,
    n_threads: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Expand per-left-row match ranges ``(lo, cnt)`` into (li, ri) pairs
    with optional index maps and biases — bit-identical to the numpy
    repeat/cumsum chain (``ops/join.expand_match_ranges_numpy``, the
    registered twin). ``total`` must equal ``cnt.sum()`` (callers already
    have it from the count pass); the kernel re-validates it against its
    own prefix sum BEFORE writing, and bounds-checks the maps, so a
    malformed call can never overrun the buffers — it returns None and
    the numpy fallback raises the appropriate error instead."""
    lib = load(wait=False)
    if lib is None:
        return None
    lo = np.ascontiguousarray(lo, dtype=np.int64)
    cnt = np.ascontiguousarray(cnt, dtype=np.int64)
    _i64p = ctypes.POINTER(ctypes.c_int64)

    def p(a):
        if a is None:
            return ctypes.cast(None, _i64p)
        return a.ctypes.data_as(_i64p)

    if l_map is not None:
        l_map = np.ascontiguousarray(l_map, dtype=np.int64)
    if r_map is not None:
        r_map = np.ascontiguousarray(r_map, dtype=np.int64)
    li = np.empty(total, dtype=np.int64)
    ri = np.empty(total, dtype=np.int64)
    emitted = lib.hs_expand_match_ranges_i64(
        lo.ctypes.data_as(_i64p),
        cnt.ctypes.data_as(_i64p),
        ctypes.c_int64(len(lo)),
        p(l_map),
        ctypes.c_int64(0 if l_map is None else len(l_map)),
        p(r_map),
        ctypes.c_int64(0 if r_map is None else len(r_map)),
        ctypes.c_int64(l_bias),
        ctypes.c_int64(r_bias),
        li.ctypes.data_as(_i64p),
        ri.ctypes.data_as(_i64p),
        ctypes.c_int64(total),
        ctypes.c_int32(n_threads if n_threads else _n_threads(total)),
    )
    if emitted != total:
        return None
    return li, ri


def _gather_64(values: np.ndarray, idx: np.ndarray) -> Optional[np.ndarray]:
    """Shared driver of the 8-byte gathers; ``values`` dtype picks the
    export. Returns None (numpy fallback) when the kernel is unavailable
    or any index is out of range — numpy's negative-index wrapping and
    IndexError semantics are preserved by falling back, never emulated."""
    lib = load(wait=False)
    if lib is None:
        return None
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty(len(idx), dtype=values.dtype)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    if values.dtype == np.float64:
        _f64p = ctypes.POINTER(ctypes.c_double)
        rc = lib.hs_gather_f64(
            values.ctypes.data_as(_f64p),
            ctypes.c_int64(len(values)),
            idx.ctypes.data_as(_i64p),
            ctypes.c_int64(len(idx)),
            out.ctypes.data_as(_f64p),
            ctypes.c_int32(_n_threads(len(idx))),
        )
    else:
        rc = lib.hs_gather_i64(
            values.ctypes.data_as(_i64p),
            ctypes.c_int64(len(values)),
            idx.ctypes.data_as(_i64p),
            ctypes.c_int64(len(idx)),
            out.ctypes.data_as(_i64p),
            ctypes.c_int32(_n_threads(len(idx))),
        )
    if rc != 0:
        return None
    return out


def gather_i64(
    values: np.ndarray, idx: np.ndarray
) -> Optional[np.ndarray]:
    """Threaded bounds-checked ``values[idx]`` for contiguous int64
    arrays — bit-exact twin of ``numpy.take`` on in-range indices. None
    on unavailability or out-of-range indices (numpy fallback)."""
    values = np.ascontiguousarray(values, dtype=np.int64)
    return _gather_64(values, idx)


def gather_f64(
    values: np.ndarray, idx: np.ndarray
) -> Optional[np.ndarray]:
    """Threaded bounds-checked ``values[idx]`` for contiguous float64
    arrays — bit-exact twin of ``numpy.take`` (bitwise moves: NaN
    payloads survive). None on unavailability or out-of-range indices."""
    values = np.ascontiguousarray(values, dtype=np.float64)
    return _gather_64(values, idx)


def _u8_flags(xs) -> np.ndarray:
    return np.asarray([1 if x else 0 for x in xs], dtype=np.uint8)


def _term_args(cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags):
    """The 11 leading ctypes arguments every range-term kernel takes
    (hs_range_mask / hs_fused_filter_select / hs_fused_filter_agg's
    filter section). Returns (args, keepalive): ``keepalive`` pins the
    temporary numpy arrays for the duration of the call."""
    k = len(cols)
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    _f64p = ctypes.POINTER(ctypes.c_double)
    col_ptrs = (ctypes.c_void_p * k)(*(c.ctypes.data for c in cols))
    valid_arrs = [
        None if v is None else np.ascontiguousarray(v, dtype=np.uint8)
        for v in valids
    ]
    valid_ptrs = (ctypes.c_void_p * k)(
        *(None if v is None else v.ctypes.data for v in valid_arrs)
    )
    is_f64_a = _u8_flags(is_f64)
    has_lo = _u8_flags(f[0] for f in flags)
    has_hi = _u8_flags(f[1] for f in flags)
    lo_strict = _u8_flags(f[2] for f in flags)
    hi_strict = _u8_flags(f[3] for f in flags)
    lo_i_a = np.asarray(lo_i, dtype=np.int64)
    hi_i_a = np.asarray(hi_i, dtype=np.int64)
    lo_f_a = np.asarray(lo_f, dtype=np.float64)
    hi_f_a = np.asarray(hi_f, dtype=np.float64)
    keep = (
        cols, valid_arrs, is_f64_a, has_lo, has_hi, lo_strict, hi_strict,
        lo_i_a, hi_i_a, lo_f_a, hi_f_a,
    )
    args = [
        col_ptrs,
        valid_ptrs,
        is_f64_a.ctypes.data_as(_u8p),
        lo_i_a.ctypes.data_as(_i64p),
        hi_i_a.ctypes.data_as(_i64p),
        lo_f_a.ctypes.data_as(_f64p),
        hi_f_a.ctypes.data_as(_f64p),
        has_lo.ctypes.data_as(_u8p),
        has_hi.ctypes.data_as(_u8p),
        lo_strict.ctypes.data_as(_u8p),
        hi_strict.ctypes.data_as(_u8p),
    ]
    return args, keep


def range_mask_u8(
    cols,
    valids,
    is_f64,
    lo_i,
    hi_i,
    lo_f,
    hi_f,
    flags,
    n: int,
) -> Optional[np.ndarray]:
    """Fused range mask over ``k`` terms: per term a contiguous 8-byte
    column array (int64 view or float64), optional bool validity, and
    lo/hi bounds with ``flags`` = (has_lo, has_hi, lo_strict, hi_strict)
    — the single-pass twin of ``ops/filter.range_mask_numpy`` (the
    registered KERNEL_TWINS reference). Returns a bool mask, or None when
    the native kernel is unavailable (caller runs the numpy twin)."""
    lib = load(wait=False)
    if lib is None:
        return None
    k = len(cols)
    if k == 0 or n == 0:
        return np.ones(n, dtype=bool)
    args, _keep = _term_args(cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags)
    out = np.empty(n, dtype=np.uint8)
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.hs_range_mask(
        *args,
        ctypes.c_int32(k),
        ctypes.c_int64(n),
        out.ctypes.data_as(_u8p),
        ctypes.c_int32(_n_threads(n)),
    )
    if rc != 0:
        return None
    return out.view(np.bool_)


def fused_filter_select(
    cols,
    valids,
    is_f64,
    lo_i,
    hi_i,
    lo_f,
    hi_f,
    flags,
    n: int,
) -> Optional[np.ndarray]:
    """Passing row indices (ascending int64) of the fused range-term
    conjunction — one pass computing AND compacting, replacing the
    interpreted chain's materialized mask + ``np.nonzero`` (the
    registered twin: ``pipeline_compiler.filter_select_interpreted``).
    Same term layout as :func:`range_mask_u8`. Returns None when the
    native kernel is unavailable (caller runs the interpreted chain)."""
    lib = load(wait=False)
    if lib is None:
        return None
    k = len(cols)
    if k == 0 or n == 0:
        return np.arange(n, dtype=np.int64)
    args, _keep = _term_args(cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags)
    out = np.empty(n, dtype=np.int64)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    got = lib.hs_fused_filter_select(
        *args,
        ctypes.c_int32(k),
        ctypes.c_int64(n),
        out.ctypes.data_as(_i64p),
        ctypes.c_int32(_n_threads(n)),
    )
    if got < 0:
        return None
    # copy: the n-capacity scratch must not stay pinned behind a small view
    return out[:got].copy()


def fused_filter_agg(
    f_cols,
    f_valids,
    f_is_f64,
    f_lo_i,
    f_hi_i,
    f_lo_f,
    f_hi_f,
    f_flags,
    k_cols,
    k_valids,
    k_is_f64,
    a_cols,
    a_valids,
    a_ops,
    n: int,
    row_start: int,
    ht: np.ndarray,
    g_hash: np.ndarray,
    g_reps: np.ndarray,
    g_nulls: np.ndarray,
    g_kvals: np.ndarray,
    g_kvalid: np.ndarray,
    acc_i: np.ndarray,
    acc_f: np.ndarray,
    acc_cnt: np.ndarray,
    acc_aux: np.ndarray,
    n_groups: int,
    rows_passed: int,
    rebuild: bool,
) -> Optional[Tuple[int, int, int]]:
    """One chunk through the fused filter→group→aggregate pass
    (``hs_fused_filter_agg``; state contract documented on the kernel).
    Returns ``(rows_consumed, n_groups, rows_passed)`` — consumed <
    ``n - row_start`` means the group table filled and the caller must
    grow the state and re-call at the new offset — or None when the
    native kernel is unavailable or rejects the arguments (caller runs
    the interpreted twin, ``pipeline_compiler.interpreted_filter_aggregate``)."""
    lib = load(wait=False)
    if lib is None:
        return None
    targs, _keep = _term_args(
        f_cols, f_valids, f_is_f64, f_lo_i, f_hi_i, f_lo_f, f_hi_f, f_flags
    )
    n_keys = len(k_cols)
    n_aggs = len(a_ops)
    key_ptrs = (ctypes.c_void_p * max(n_keys, 1))(
        *(c.ctypes.data for c in k_cols) if n_keys else (None,)
    )
    kvalid_arrs = [
        None if v is None else np.ascontiguousarray(v, dtype=np.uint8)
        for v in k_valids
    ]
    kvalid_ptrs = (ctypes.c_void_p * max(n_keys, 1))(
        *(None if v is None else v.ctypes.data for v in kvalid_arrs)
        if n_keys
        else (None,)
    )
    k_is_f64_a = _u8_flags(k_is_f64)
    agg_ptrs = (ctypes.c_void_p * max(n_aggs, 1))(
        *(None if c is None else c.ctypes.data for c in a_cols)
        if n_aggs
        else (None,)
    )
    avalid_arrs = [
        None if v is None else np.ascontiguousarray(v, dtype=np.uint8)
        for v in a_valids
    ]
    avalid_ptrs = (ctypes.c_void_p * max(n_aggs, 1))(
        *(None if v is None else v.ctypes.data for v in avalid_arrs)
        if n_aggs
        else (None,)
    )
    a_ops_a = np.asarray(a_ops, dtype=np.uint8)
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    _f64p = ctypes.POINTER(ctypes.c_double)
    ng = ctypes.c_int64(n_groups)
    rp = ctypes.c_int64(rows_passed)
    consumed = lib.hs_fused_filter_agg(
        *targs,
        ctypes.c_int32(len(f_cols)),
        key_ptrs,
        kvalid_ptrs,
        k_is_f64_a.ctypes.data_as(_u8p),
        ctypes.c_int32(n_keys),
        agg_ptrs,
        avalid_ptrs,
        a_ops_a.ctypes.data_as(_u8p),
        ctypes.c_int32(n_aggs),
        ctypes.c_int64(n),
        ctypes.c_int64(row_start),
        ht.ctypes.data_as(_i64p),
        ctypes.c_int64(len(ht)),
        g_hash.ctypes.data_as(_i64p),
        g_reps.ctypes.data_as(_i64p),
        g_nulls.ctypes.data_as(_u8p),
        g_kvals.ctypes.data_as(_i64p),
        g_kvalid.ctypes.data_as(_u8p),
        acc_i.ctypes.data_as(_i64p),
        acc_f.ctypes.data_as(_f64p),
        acc_cnt.ctypes.data_as(_i64p),
        acc_aux.ctypes.data_as(_i64p),
        ctypes.c_int64(g_reps.shape[1] if g_reps.ndim == 2 else len(g_hash)),
        ctypes.byref(ng),
        ctypes.byref(rp),
        ctypes.c_int32(1 if rebuild else 0),
    )
    if consumed < 0:
        return None
    return int(consumed), int(ng.value), int(rp.value)


def bucket_ids_i64(
    key_reps: np.ndarray, num_buckets: int, seed: int = 42
) -> Optional[np.ndarray]:
    """Murmur3-32 bucket ids over [k, n] int64 key reps in one pass per
    row — bit-exact twin of ``ops/hash.bucket_ids_host``. Returns None
    when the native kernel is unavailable."""
    lib = load(wait=False)
    if lib is None:
        return None
    key_reps = np.ascontiguousarray(key_reps, dtype=np.int64)
    k, n = key_reps.shape
    out = np.empty(n, dtype=np.int32)
    ptrs = (ctypes.c_void_p * k)(
        *(key_reps[i].ctypes.data for i in range(k))
    )
    rc = lib.hs_bucket_ids_i64(
        ptrs,
        ctypes.c_int32(k),
        ctypes.c_int64(n),
        ctypes.c_uint32(seed),
        ctypes.c_uint32(num_buckets),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return out
