// Native host kernels for hyperspace_tpu.
//
// The TPU compute path is JAX/XLA; these kernels cover the HOST side of
// the build/serve pipeline (the dispatch policy in ops/sort.py keeps
// host-resident batches off the device because PCIe/tunnel transfer
// dwarfs the compute). The hot host op is the stable multi-plane lexsort
// behind the bucketed sorted write (reference: the sort-within-bucket of
// index/DataFrameWriterExtensions.scala:58-67); numpy's lexsort runs one
// full stable argsort per plane with an index gather each time, while
// this kernel runs one adaptive LSD radix sort over all planes and skips
// byte passes whose digits are constant across rows — on real index
// workloads most passes are (bucket ids span a few bits, the hi word of
// a small int64 key is the constant sign bit).
//
// Contract: identical output to np.lexsort(planes[::-1]) — stable,
// ascending, plane 0 major. Ties keep input order; counting sort is
// stable by construction and planes are processed least-significant
// first, so the composition is stable overall.
//
// Threading: pass n_threads > 1 to split histogram+scatter by contiguous
// input chunks (per-chunk digit offsets keep stability). The caller
// picks n_threads from the machine; 1 means plain loops with no thread
// machinery at all.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Buffers {
  std::vector<int64_t> perm_a, perm_b;
  std::vector<uint32_t> key_a, key_b;
};

// One stable counting-sort pass by byte `shift` of key_a, moving
// (key, perm) pairs into (key_b, perm_b). Single-threaded.
void pass_serial(Buffers& buf, int64_t n, int shift) {
  int64_t count[256] = {0};
  const uint32_t* ka = buf.key_a.data();
  for (int64_t i = 0; i < n; ++i) ++count[(ka[i] >> shift) & 0xFF];
  int64_t offset[256];
  int64_t running = 0;
  for (int d = 0; d < 256; ++d) {
    offset[d] = running;
    running += count[d];
  }
  const int64_t* pa = buf.perm_a.data();
  uint32_t* kb = buf.key_b.data();
  int64_t* pb = buf.perm_b.data();
  for (int64_t i = 0; i < n; ++i) {
    int64_t pos = offset[(ka[i] >> shift) & 0xFF]++;
    kb[pos] = ka[i];
    pb[pos] = pa[i];
  }
}

// Run fn(0..T-1), fn(0) on the calling thread. If a spawn fails
// (std::system_error from pthread_create under a pids cgroup limit),
// already-spawned threads are joined BEFORE the exception propagates —
// destroying a joinable std::thread calls std::terminate, which would
// abort the process instead of reaching the extern "C" catch(...) that
// turns resource exhaustion into rc=2 / numpy fallback.
template <typename F>
void run_on_threads(int T, F&& fn) {
  std::vector<std::thread> ts;
  ts.reserve(T > 1 ? T - 1 : 0);
  try {
    for (int t = 1; t < T; ++t) ts.emplace_back(fn, t);
  } catch (...) {
    for (auto& th : ts) th.join();
    throw;
  }
  fn(0);
  for (auto& th : ts) th.join();
}

// Threaded variant: per-chunk histograms, then global offsets laid out
// digit-major chunk-minor so each chunk scatters into disjoint, stably
// ordered slots.
void pass_threaded(Buffers& buf, int64_t n, int shift, int n_threads) {
  const int T = n_threads;
  std::vector<int64_t> counts(static_cast<size_t>(T) * 256, 0);
  const uint32_t* ka = buf.key_a.data();
  const int64_t chunk = (n + T - 1) / T;
  auto hist = [&](int t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    int64_t* c = counts.data() + static_cast<size_t>(t) * 256;
    for (int64_t i = lo; i < hi; ++i) ++c[(ka[i] >> shift) & 0xFF];
  };
  run_on_threads(T, hist);
  // offsets[t][d]: digit-major, chunk-minor prefix sum
  std::vector<int64_t> offsets(static_cast<size_t>(T) * 256);
  int64_t running = 0;
  for (int d = 0; d < 256; ++d) {
    for (int t = 0; t < T; ++t) {
      offsets[static_cast<size_t>(t) * 256 + d] = running;
      running += counts[static_cast<size_t>(t) * 256 + d];
    }
  }
  const int64_t* pa = buf.perm_a.data();
  uint32_t* kb = buf.key_b.data();
  int64_t* pb = buf.perm_b.data();
  auto scatter = [&](int t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    int64_t* off = offsets.data() + static_cast<size_t>(t) * 256;
    for (int64_t i = lo; i < hi; ++i) {
      int64_t pos = off[(ka[i] >> shift) & 0xFF]++;
      kb[pos] = ka[i];
      pb[pos] = pa[i];
    }
  };
  run_on_threads(T, scatter);
}

// Shared range-term predicate: one conjunct of the serve-path residual
// mask (ops/filter.py lower_range_terms + native_range_bounds) — an
// int64 or float64 column, optional lo/hi bounds with strictness, an
// optional validity byte mask. Used by hs_range_mask,
// hs_fused_filter_select and hs_fused_filter_agg so the three kernels
// evaluate EXACTLY the same predicate semantics (IEEE float compares:
// NaN fails every bound, same as the numpy twin).
struct RangeTerms {
  const void** cols;
  const uint8_t** valids;  // may be nullptr / entries may be nullptr
  const uint8_t* is_f64;
  const int64_t* lo_i;
  const int64_t* hi_i;
  const double* lo_f;
  const double* hi_f;
  const uint8_t* has_lo;
  const uint8_t* has_hi;
  const uint8_t* lo_strict;
  const uint8_t* hi_strict;
  int32_t k;
};

inline bool terms_pass(const RangeTerms& t, int64_t r) {
  for (int32_t i = 0; i < t.k; ++i) {
    if (t.valids != nullptr && t.valids[i] != nullptr && !t.valids[i][r])
      return false;
    if (t.is_f64[i]) {
      const double v = static_cast<const double*>(t.cols[i])[r];
      if (t.has_lo[i] && !(t.lo_strict[i] ? v > t.lo_f[i] : v >= t.lo_f[i]))
        return false;
      if (t.has_hi[i] && !(t.hi_strict[i] ? v < t.hi_f[i] : v <= t.hi_f[i]))
        return false;
    } else {
      const int64_t v = static_cast<const int64_t*>(t.cols[i])[r];
      if (t.has_lo[i] && !(t.lo_strict[i] ? v > t.lo_i[i] : v >= t.lo_i[i]))
        return false;
      if (t.has_hi[i] && !(t.hi_strict[i] ? v < t.hi_i[i] : v <= t.hi_i[i]))
        return false;
    }
  }
  return true;
}

// splitmix64 finalizer: the fused-aggregate group hash. Quality matters
// only for probe-length distribution; identity never depends on it (full
// rep/null equality is compared on every probe hit).
inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

}  // namespace

extern "C" {

// Stable ascending lexsort of n rows by k uint32 planes; planes[0] is
// the MAJOR key. Writes the permutation into out (int64, length n).
// Returns 0 on success, 1 on bad arguments, 2 on resource exhaustion
// (std::bad_alloc / thread spawn failure — the Python wrapper falls back
// to numpy, whose MemoryError is catchable, instead of std::terminate
// aborting the process at the extern "C" boundary).
int hs_lexsort_u32(const uint32_t** planes, int32_t k, int64_t n,
                   int64_t* out, int32_t n_threads) {
  if (n < 0 || k < 0 || (n > 0 && out == nullptr)) return 1;
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  if (n <= 1 || k == 0) return 0;
  if (n_threads < 1) n_threads = 1;

  try {
    Buffers buf;
    buf.perm_a.resize(n);
    buf.perm_b.resize(n);
    buf.key_a.resize(n);
    buf.key_b.resize(n);
    std::memcpy(buf.perm_a.data(), out, static_cast<size_t>(n) * 8);

    for (int p = k - 1; p >= 0; --p) {
      const uint32_t* plane = planes[p];
      // Byte-activity mask: a byte position where every row agrees cannot
      // change the order — skip its pass. Order-independent, so it runs on
      // the raw plane BEFORE paying the random gather; a constant plane
      // (e.g. the hi word of small int64 keys) costs one sequential scan.
      uint32_t mask = 0;
      const uint32_t v0 = plane[0];
      for (int64_t i = 1; i < n; ++i) mask |= plane[i] ^ v0;
      if (mask == 0) continue;
      // Gather the plane into the current permutation order (sequential
      // writes; the random reads are the unavoidable cost of composing
      // with the earlier planes' order).
      const int64_t* pa = buf.perm_a.data();
      uint32_t* ka = buf.key_a.data();
      for (int64_t i = 0; i < n; ++i) ka[i] = plane[pa[i]];
      for (int shift = 0; shift < 32; shift += 8) {
        if (((mask >> shift) & 0xFF) == 0) continue;
        if (n_threads > 1) {
          pass_threaded(buf, n, shift, n_threads);
        } else {
          pass_serial(buf, n, shift);
        }
        buf.perm_a.swap(buf.perm_b);
        buf.key_a.swap(buf.key_b);
      }
    }
    std::memcpy(out, buf.perm_a.data(), static_cast<size_t>(n) * 8);
  } catch (...) {
    return 2;
  }
  return 0;
}

// Stable counting scatter: partition n row indices by their int32 bucket
// id. out_order receives the indices grouped bucket-major (ascending
// bucket id), original order preserved within each bucket; out_offsets
// (length num_buckets + 1) receives the run boundaries, so bucket b's
// rows are out_order[out_offsets[b] .. out_offsets[b+1]).
//
// This is the partition-first half of the covering-index build: instead
// of one global lexsort by (bucket, keys) whose permutation gathers walk
// the whole working set, the build histograms bucket ids (sequential
// read), scatters row indices into contiguous per-bucket runs
// (sequential writes per bucket cursor), then sorts each bucket
// independently with a working set of ~total/num_buckets.
//
// Returns 0 on success, 1 on bad arguments (including any bucket id
// outside [0, num_buckets)), 2 on resource exhaustion.
int hs_partition_by_bucket(const int32_t* bucket_ids, int64_t n,
                           int32_t num_buckets, int64_t* out_order,
                           int64_t* out_offsets, int32_t n_threads) {
  if (n < 0 || num_buckets <= 0 || out_offsets == nullptr ||
      (n > 0 && (bucket_ids == nullptr || out_order == nullptr)))
    return 1;
  if (n_threads < 1) n_threads = 1;
  const int T = n_threads;
  try {
    // Per-chunk histograms (also validates ids: one branchy pass is
    // cheaper than scattering through a poisoned offset table).
    std::vector<int64_t> counts(static_cast<size_t>(T) * num_buckets, 0);
    const int64_t chunk = T > 1 ? (n + T - 1) / T : n;
    std::vector<uint8_t> bad(T, 0);
    auto hist = [&](int t) {
      int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
      int64_t* c = counts.data() + static_cast<size_t>(t) * num_buckets;
      for (int64_t i = lo; i < hi; ++i) {
        const int32_t b = bucket_ids[i];
        if (b < 0 || b >= num_buckets) {
          bad[t] = 1;
          return;
        }
        ++c[b];
      }
    };
    run_on_threads(T, hist);
    for (int t = 0; t < T; ++t)
      if (bad[t]) return 1;
    // Bucket-major chunk-minor offsets: chunk t's slots for bucket b
    // follow chunk t-1's, so the scatter is stable across chunks.
    std::vector<int64_t> offsets(static_cast<size_t>(T) * num_buckets);
    int64_t running = 0;
    for (int32_t b = 0; b < num_buckets; ++b) {
      out_offsets[b] = running;
      for (int t = 0; t < T; ++t) {
        offsets[static_cast<size_t>(t) * num_buckets + b] = running;
        running += counts[static_cast<size_t>(t) * num_buckets + b];
      }
    }
    out_offsets[num_buckets] = running;
    auto scatter = [&](int t) {
      int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
      int64_t* off = offsets.data() + static_cast<size_t>(t) * num_buckets;
      for (int64_t i = lo; i < hi; ++i) out_order[off[bucket_ids[i]]++] = i;
    };
    run_on_threads(T, scatter);
  } catch (...) {
    return 2;
  }
  return 0;
}

// Inner-join pair count of two ASCENDING-sorted int64 key arrays
// (duplicates allowed on both sides): one linear merge, no allocation.
// This is the serve-side payoff of the co-bucketed covering index — both
// bucket slices come off disk key-sorted (reference: the no-shuffle SMJ
// of covering/JoinIndexRule.scala:619-634), so matching is O(n+m+pairs)
// sequential instead of n binary searches into m.
int64_t hs_merge_join_count_i64(const int64_t* l, int64_t n,
                                const int64_t* r, int64_t m) {
  int64_t total = 0;
  int64_t i = 0, j = 0;
  while (i < n && j < m) {
    if (l[i] < r[j]) {
      ++i;
    } else if (l[i] > r[j]) {
      ++j;
    } else {
      const int64_t v = l[i];
      int64_t i2 = i, j2 = j;
      while (i2 < n && l[i2] == v) ++i2;
      while (j2 < m && r[j2] == v) ++j2;
      total += (i2 - i) * (j2 - j);
      i = i2;
      j = j2;
    }
  }
  return total;
}

// Emit the matching pairs of two ASCENDING-sorted int64 key arrays into
// li/ri (capacity = hs_merge_join_count_i64's result), with l_bias/r_bias
// added to every emitted index. Order: left index ascending, right index
// ascending within each left row — identical to the numpy
// searchsorted+repeat expansion it replaces. The biases let a per-bucket
// caller emit GLOBAL row ids straight into one preallocated output,
// skipping the per-bucket offset-add and concatenate passes entirely.
int64_t hs_merge_join_emit_i64(const int64_t* l, int64_t n,
                               const int64_t* r, int64_t m, int64_t l_bias,
                               int64_t r_bias, int64_t* li, int64_t* ri) {
  int64_t out = 0;
  int64_t i = 0, j = 0;
  while (i < n && j < m) {
    if (l[i] < r[j]) {
      ++i;
    } else if (l[i] > r[j]) {
      ++j;
    } else {
      const int64_t v = l[i];
      int64_t j2 = j;
      while (j2 < m && r[j2] == v) ++j2;
      for (; i < n && l[i] == v; ++i) {
        for (int64_t jj = j; jj < j2; ++jj) {
          li[out] = i + l_bias;
          ri[out] = jj + r_bias;
          ++out;
        }
      }
      j = j2;
    }
  }
  return out;
}

// Expand per-left-row match ranges into explicit (li, ri) pairs — the
// serve-side half of the merge join that the numpy path spends ~6 full
// array passes on (repeat + cumsum + arange + repeat + gather; the
// "repeat/cumsum chain" of execution/join_exec.py). One pass here: for
// left row i with cnt[i] matches starting at sorted-right position
// lo[i], emit cnt[i] pairs. Optional l_map/r_map (nullptr = identity)
// compose the argsort/rowmap indirections the callers otherwise apply
// as separate gather passes: li = l_map[i] + l_bias, ri =
// r_map[lo[i]+j] + r_bias. Pair order: left row ascending, right
// position ascending within each left row — identical to the numpy
// expansion (ops/join.expand_match_ranges_numpy, the registered twin).
//
// Threading: rows are chunked by a serial prefix sum of cnt, so each
// thread writes a disjoint contiguous output slice. `capacity` is the
// caller's li/ri allocation (= cnt's sum, which the Python wrapper
// already computed): it is validated BEFORE any write, so a
// miscomputed caller total can never overrun the buffers — the same
// defensive posture as the gathers' bounds check. Map lengths are
// validated too (l_map positionally: l_map_len >= n; r_map per element,
// since lo+cnt ranges are data-dependent); a violation returns -1 and
// the Python fallback surfaces numpy's own IndexError. Returns the
// emitted pair count, -1 on bad arguments, -2 on resource exhaustion.
int64_t hs_expand_match_ranges_i64(const int64_t* lo, const int64_t* cnt,
                                   int64_t n, const int64_t* l_map,
                                   int64_t l_map_len, const int64_t* r_map,
                                   int64_t r_map_len, int64_t l_bias,
                                   int64_t r_bias, int64_t* li, int64_t* ri,
                                   int64_t capacity, int32_t n_threads) {
  if (n < 0 || (n > 0 && (lo == nullptr || cnt == nullptr))) return -1;
  if (l_map != nullptr && l_map_len < n) return -1;
  if (n == 0) return capacity == 0 ? 0 : -1;
  if (n_threads < 1) n_threads = 1;
  try {
    // Serial prefix sum: out_off[i] = pairs emitted before row i.
    std::vector<int64_t> out_off(static_cast<size_t>(n) + 1);
    int64_t running = 0;
    for (int64_t i = 0; i < n; ++i) {
      if (cnt[i] < 0) return -1;
      out_off[i] = running;
      running += cnt[i];
    }
    out_off[n] = running;
    const int64_t total = running;
    if (total != capacity) return -1;
    if (total > 0 && (li == nullptr || ri == nullptr)) return -1;
    const int T =
        total < (1 << 16) ? 1 : std::min<int64_t>(n_threads, n);
    const int64_t chunk = (n + T - 1) / T;
    std::vector<uint8_t> bad(T, 0);
    auto expand = [&](int t) {
      int64_t lo_row = t * chunk;
      if (lo_row >= n) return;  // ceil-chunking can overshoot for tiny n
      int64_t hi_row = std::min<int64_t>(n, lo_row + chunk);
      int64_t out = out_off[lo_row];
      for (int64_t i = lo_row; i < hi_row; ++i) {
        const int64_t l = (l_map ? l_map[i] : i) + l_bias;
        const int64_t base = lo[i];
        if (r_map != nullptr &&
            cnt[i] > 0 && (base < 0 || base + cnt[i] > r_map_len)) {
          bad[t] = 1;
          return;
        }
        for (int64_t j = 0; j < cnt[i]; ++j) {
          li[out] = l;
          ri[out] = (r_map ? r_map[base + j] : base + j) + r_bias;
          ++out;
        }
      }
    };
    run_on_threads(T, expand);
    for (int t = 0; t < T; ++t)
      if (bad[t]) return -1;
    return total;
  } catch (...) {
    return -2;
  }
}

// Bounds-checked threaded gathers: out[i] = src[idx[i]]. numpy's fancy
// indexing is single-threaded and the serve join's assemble stage is a
// string of multi-million-row gathers (one per output column), so the
// random-access latency is worth spreading over cores. Any idx outside
// [0, n_src) returns 1 (the Python wrapper falls back to numpy, which
// preserves numpy's negative-index and IndexError semantics exactly).
// Returns 0 on success, 2 on resource exhaustion.
static int gather64(const uint64_t* src, int64_t n_src, const int64_t* idx,
                    int64_t n_idx, uint64_t* out, int32_t n_threads) {
  if (n_src < 0 || n_idx < 0 ||
      (n_idx > 0 && (src == nullptr || idx == nullptr || out == nullptr)))
    return 1;
  if (n_idx == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  const int T = static_cast<int>(std::min<int64_t>(n_threads, n_idx));
  try {
    const int64_t chunk = (n_idx + T - 1) / T;
    std::vector<uint8_t> bad(T, 0);
    auto work = [&](int t) {
      int64_t lo = t * chunk, hi = std::min<int64_t>(n_idx, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const int64_t j = idx[i];
        if (j < 0 || j >= n_src) {
          bad[t] = 1;
          return;
        }
        out[i] = src[j];
      }
    };
    run_on_threads(T, work);
    for (int t = 0; t < T; ++t)
      if (bad[t]) return 1;
  } catch (...) {
    return 2;
  }
  return 0;
}

int hs_gather_i64(const int64_t* src, int64_t n_src, const int64_t* idx,
                  int64_t n_idx, int64_t* out, int32_t n_threads) {
  return gather64(reinterpret_cast<const uint64_t*>(src), n_src, idx, n_idx,
                  reinterpret_cast<uint64_t*>(out), n_threads);
}

int hs_gather_f64(const double* src, int64_t n_src, const int64_t* idx,
                  int64_t n_idx, double* out, int32_t n_threads) {
  // same 8-byte move as the int64 gather; a distinct export keeps the
  // ctypes signatures honest (and the parity registry explicit per type)
  return gather64(reinterpret_cast<const uint64_t*>(src), n_src, idx, n_idx,
                  reinterpret_cast<uint64_t*>(out), n_threads);
}

// Fused range mask: out[r] = 1 iff row r passes EVERY term's bound
// checks and validity. A term is one numeric range/Eq conjunct of the
// serve-path residual predicate (ops/filter.py lower_range_terms): an
// int64 or float64 column, optional lo/hi bounds with strictness, and
// an optional validity byte mask. The numpy twin
// (ops/filter.range_mask_numpy) makes ~2 full-array passes per term
// plus the AND passes; this is one pass over the rows total, threaded
// by contiguous row chunks. Float compares are IEEE (NaN fails every
// bound — identical to the engine's mask semantics). Returns 0 on
// success, 1 on bad arguments, 2 on resource exhaustion.
int hs_range_mask(const void** cols, const uint8_t** valids,
                  const uint8_t* is_f64, const int64_t* lo_i,
                  const int64_t* hi_i, const double* lo_f,
                  const double* hi_f, const uint8_t* has_lo,
                  const uint8_t* has_hi, const uint8_t* lo_strict,
                  const uint8_t* hi_strict, int32_t k, int64_t n,
                  uint8_t* out, int32_t n_threads) {
  if (n < 0 || k <= 0 || (n > 0 && (cols == nullptr || out == nullptr)))
    return 1;
  for (int32_t t = 0; t < k; ++t)
    if (cols[t] == nullptr) return 1;
  if (n == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  const int T = static_cast<int>(
      std::min<int64_t>(n < (1 << 16) ? 1 : n_threads, n));
  const RangeTerms terms{cols,   valids, is_f64,    lo_i,      hi_i,
                         lo_f,   hi_f,   has_lo,    has_hi,    lo_strict,
                         hi_strict, k};
  try {
    const int64_t chunk = (n + T - 1) / T;
    auto work = [&](int th) {
      int64_t lo = th * chunk, hi = std::min<int64_t>(n, lo + chunk);
      for (int64_t r = lo; r < hi; ++r) out[r] = terms_pass(terms, r) ? 1 : 0;
    };
    run_on_threads(T, work);
  } catch (...) {
    return 2;
  }
  return 0;
}

// Fused filter-select: the passing ROW INDICES of the range-term
// conjunction, ascending, written into out_idx (capacity n). The first
// half of the Filter→Project lowering (docs/serve-compiler.md): one
// pass computing pass/fail AND compacting indices replaces the
// interpreted chain's materialized bool mask + np.nonzero; the caller
// gathers the projected columns through the indices (the existing
// threaded hs_gather kernels). Threaded two-phase (per-chunk count,
// then disjoint fills), so the output order is deterministic and equal
// to np.nonzero(mask). Returns the index count, -1 on bad arguments,
// -2 on resource exhaustion.
int64_t hs_fused_filter_select(const void** cols, const uint8_t** valids,
                               const uint8_t* is_f64, const int64_t* lo_i,
                               const int64_t* hi_i, const double* lo_f,
                               const double* hi_f, const uint8_t* has_lo,
                               const uint8_t* has_hi,
                               const uint8_t* lo_strict,
                               const uint8_t* hi_strict, int32_t k,
                               int64_t n, int64_t* out_idx,
                               int32_t n_threads) {
  if (n < 0 || k <= 0 || (n > 0 && (cols == nullptr || out_idx == nullptr)))
    return -1;
  for (int32_t t = 0; t < k; ++t)
    if (cols[t] == nullptr) return -1;
  if (n == 0) return 0;
  if (n_threads < 1) n_threads = 1;
  const int T = static_cast<int>(
      std::min<int64_t>(n < (1 << 16) ? 1 : n_threads, n));
  const RangeTerms terms{cols,   valids, is_f64,    lo_i,      hi_i,
                         lo_f,   hi_f,   has_lo,    has_hi,    lo_strict,
                         hi_strict, k};
  try {
    const int64_t chunk = (n + T - 1) / T;
    std::vector<int64_t> counts(T, 0);
    auto count = [&](int t) {
      int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
      int64_t c = 0;
      for (int64_t r = lo; r < hi; ++r) c += terms_pass(terms, r) ? 1 : 0;
      counts[t] = c;
    };
    run_on_threads(T, count);
    std::vector<int64_t> offsets(T);
    int64_t total = 0;
    for (int t = 0; t < T; ++t) {
      offsets[t] = total;
      total += counts[t];
    }
    auto fill = [&](int t) {
      int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
      int64_t out = offsets[t];
      for (int64_t r = lo; r < hi; ++r)
        if (terms_pass(terms, r)) out_idx[out++] = r;
    };
    run_on_threads(T, fill);
    return total;
  } catch (...) {
    return -2;
  }
}

// Fused filter-aggregate: the serve-pipeline compiler's inner pass
// (docs/serve-compiler.md). For every row passing the range-term
// conjunction, compute the group slot from the key columns' canonical
// int64 reps (NULL/NaN/-0.0 canonicalization identical to
// io/columnar.Column.key_rep) and fold the row into per-group partial
// aggregates — COUNT(*)/COUNT(col)/SUM/MIN/MAX over int64-view and
// float64 columns — without materializing the mask, the filtered batch,
// or any per-row intermediate. The Python driver streams row-group
// chunks through this kernel in file order with the SAME state arrays,
// so accumulation order equals the interpreted chain's row order
// (np.add.at / np.minimum.at are sequential; float sums are therefore
// bit-identical, and deliberately single-threaded here).
//
// State contract (all owned/allocated by the caller):
//   ht[ht_size]      open-addressing table (power of two, -1 = empty),
//                    always strictly larger than g_cap so a probe always
//                    finds an empty slot;
//   g_hash/g_reps/g_nulls[n_keys*g_cap]/g_kvals/g_kvalid  per-group key
//                    identity (hash, canonical rep, null flag) plus the
//                    FIRST-OCCURRENCE raw key value + validity (what the
//                    interpreted chain's batch.take(first) gathers);
//   acc_i/acc_f/acc_cnt/acc_aux[n_aggs*g_cap]  accumulators, caller-
//                    initialized per op (sum: 0, min: +sentinel, max:
//                    -sentinel; cnt/aux: 0);
//   rebuild != 0     re-insert the existing groups into a FRESH (all -1)
//                    ht from g_hash before processing — how the caller
//                    grows capacity without re-hashing in Python.
//
// Agg ops: 0 COUNT(*)  1 COUNT(col)  2 SUM i64  3 SUM f64
//          4 MIN i64   5 MAX i64     6 MIN f64  7 MAX f64
// Accumulation replicates the numpy twins exactly: int sums wrap mod
// 2^64 (accumulated as uint64), float sums add 0.0 for passing-but-null
// rows (np.add.at over zero-filled values), min/max use numpy's
// replace-on-equal rule (acc = acc<v ? acc : v), float min/max track
// has-clean / has-NaN flags for the Spark NaN ordering applied at
// finalize time.
//
// Returns the number of rows CONSUMED starting at row_start (< n - row_start
// when the group table fills mid-chunk: the caller grows the state and
// re-calls at the returned offset; no row is ever half-applied), or -1 on
// bad arguments.
int64_t hs_fused_filter_agg(
    const void** f_cols, const uint8_t** f_valids, const uint8_t* f_is_f64,
    const int64_t* f_lo_i, const int64_t* f_hi_i, const double* f_lo_f,
    const double* f_hi_f, const uint8_t* f_has_lo, const uint8_t* f_has_hi,
    const uint8_t* f_lo_strict, const uint8_t* f_hi_strict, int32_t n_terms,
    const void** k_cols, const uint8_t** k_valids, const uint8_t* k_is_f64,
    int32_t n_keys, const void** a_cols, const uint8_t** a_valids,
    const uint8_t* a_ops, int32_t n_aggs, int64_t n, int64_t row_start,
    int64_t* ht, int64_t ht_size, int64_t* g_hash, int64_t* g_reps,
    uint8_t* g_nulls, int64_t* g_kvals, uint8_t* g_kvalid, int64_t* acc_i,
    double* acc_f, int64_t* acc_cnt, int64_t* acc_aux, int64_t g_cap,
    int64_t* n_groups_io, int64_t* rows_passed_io, int32_t rebuild) {
  if (n < 0 || row_start < 0 || row_start > n || n_terms < 0 ||
      n_keys < 0 || n_keys > 16 || n_aggs < 0 || g_cap <= 0 ||
      n_groups_io == nullptr || rows_passed_io == nullptr)
    return -1;
  if (n_terms > 0 && f_cols == nullptr) return -1;
  if (n_keys > 0 &&
      (k_cols == nullptr || ht == nullptr || ht_size <= g_cap ||
       (ht_size & (ht_size - 1)) != 0 || g_hash == nullptr ||
       g_reps == nullptr || g_nulls == nullptr || g_kvals == nullptr ||
       g_kvalid == nullptr))
    return -1;
  if (n_aggs > 0 &&
      (a_cols == nullptr || a_ops == nullptr || acc_i == nullptr ||
       acc_f == nullptr || acc_cnt == nullptr || acc_aux == nullptr))
    return -1;
  int64_t n_groups = *n_groups_io;
  if (n_groups < 0 || n_groups > g_cap) return -1;
  if (n_keys == 0 && n_groups != 1) return -1;  // driver pre-seeds slot 0
  for (int32_t a = 0; a < n_aggs; ++a) {
    if (a_ops[a] > 7) return -1;
    // ops 2..7 read the column; COUNT(*) / COUNT(col) only count
    if (a_ops[a] >= 2 && a_cols[a] == nullptr) return -1;
  }
  const RangeTerms terms{f_cols,   f_valids, f_is_f64,    f_lo_i,
                         f_hi_i,   f_lo_f,   f_hi_f,      f_has_lo,
                         f_has_hi, f_lo_strict, f_hi_strict, n_terms};
  const int64_t NULL_REP = -0x7FFFFFFFFFFFFF13LL;  // columnar.NULL_KEY_REP
  const uint64_t hmask = n_keys > 0 ? static_cast<uint64_t>(ht_size) - 1 : 0;
  if (rebuild && n_keys > 0) {
    for (int64_t g = 0; g < n_groups; ++g) {
      uint64_t s = static_cast<uint64_t>(g_hash[g]) & hmask;
      while (ht[s] >= 0) s = (s + 1) & hmask;
      ht[s] = g;
    }
  }
  int64_t rep[16];
  uint8_t nul[16];
  int64_t passed = 0;
  for (int64_t r = row_start; r < n; ++r) {
    if (!terms_pass(terms, r)) continue;
    int64_t g = 0;
    if (n_keys > 0) {
      uint64_t h = 0x9E3779B97F4A7C15ull;
      for (int32_t j = 0; j < n_keys; ++j) {
        const bool valid =
            k_valids == nullptr || k_valids[j] == nullptr || k_valids[j][r];
        if (!valid) {
          rep[j] = NULL_REP;
          nul[j] = 1;
        } else {
          nul[j] = 0;
          if (k_is_f64[j]) {
            const double v = static_cast<const double*>(k_cols[j])[r];
            if (v != v) {
              rep[j] = 0x7FF8000000000000LL;  // canonical NaN (key_rep)
            } else if (v == 0.0) {
              rep[j] = 0;  // -0.0 and 0.0 group together (key_rep)
            } else {
              std::memcpy(&rep[j], &v, 8);
            }
          } else {
            rep[j] = static_cast<const int64_t*>(k_cols[j])[r];
          }
        }
        h = mix64(h ^ static_cast<uint64_t>(rep[j]));
        h = mix64(h ^ nul[j]);
      }
      uint64_t s = h & hmask;
      while (true) {
        const int64_t cand = ht[s];
        if (cand < 0) {
          if (n_groups >= g_cap) {
            // table full: stop BEFORE touching row r; the caller grows
            // the state and re-calls at this offset
            *n_groups_io = n_groups;
            *rows_passed_io += passed;
            return r - row_start;
          }
          g = n_groups++;
          ht[s] = g;
          g_hash[g] = static_cast<int64_t>(h);
          for (int32_t j = 0; j < n_keys; ++j) {
            g_reps[static_cast<size_t>(j) * g_cap + g] = rep[j];
            g_nulls[static_cast<size_t>(j) * g_cap + g] = nul[j];
            int64_t raw;
            std::memcpy(&raw,
                        static_cast<const char*>(k_cols[j]) +
                            static_cast<size_t>(r) * 8,
                        8);
            g_kvals[static_cast<size_t>(j) * g_cap + g] = raw;
            g_kvalid[static_cast<size_t>(j) * g_cap + g] = nul[j] ? 0 : 1;
          }
          break;
        }
        if (g_hash[cand] == static_cast<int64_t>(h)) {
          bool eq = true;
          for (int32_t j = 0; j < n_keys; ++j) {
            if (g_reps[static_cast<size_t>(j) * g_cap + cand] != rep[j] ||
                g_nulls[static_cast<size_t>(j) * g_cap + cand] != nul[j]) {
              eq = false;
              break;
            }
          }
          if (eq) {
            g = cand;
            break;
          }
        }
        s = (s + 1) & hmask;
      }
    }
    ++passed;
    for (int32_t a = 0; a < n_aggs; ++a) {
      const size_t slot = static_cast<size_t>(a) * g_cap + g;
      const bool av =
          a_valids == nullptr || a_valids[a] == nullptr || a_valids[a][r];
      switch (a_ops[a]) {
        case 0:  // COUNT(*)
          ++acc_cnt[slot];
          break;
        case 1:  // COUNT(col)
          acc_cnt[slot] += av ? 1 : 0;
          break;
        case 2: {  // SUM i64 (wraps mod 2^64, same as numpy int64 adds)
          const int64_t v =
              av ? static_cast<const int64_t*>(a_cols[a])[r] : 0;
          acc_i[slot] = static_cast<int64_t>(
              static_cast<uint64_t>(acc_i[slot]) + static_cast<uint64_t>(v));
          acc_cnt[slot] += av ? 1 : 0;
          break;
        }
        case 3: {  // SUM f64 (+0.0 for null rows, like np.add.at)
          const double v =
              av ? static_cast<const double*>(a_cols[a])[r] : 0.0;
          acc_f[slot] += v;
          acc_cnt[slot] += av ? 1 : 0;
          break;
        }
        case 4:  // MIN i64
          if (av) {
            const int64_t v = static_cast<const int64_t*>(a_cols[a])[r];
            ++acc_cnt[slot];
            acc_i[slot] = acc_i[slot] < v ? acc_i[slot] : v;
          }
          break;
        case 5:  // MAX i64
          if (av) {
            const int64_t v = static_cast<const int64_t*>(a_cols[a])[r];
            ++acc_cnt[slot];
            acc_i[slot] = acc_i[slot] > v ? acc_i[slot] : v;
          }
          break;
        case 6:  // MIN f64 (np.minimum replace-on-equal; NaN excluded,
                 // aux counts clean rows for the Spark NaN rule)
          if (av) {
            const double v = static_cast<const double*>(a_cols[a])[r];
            ++acc_cnt[slot];
            if (!(v != v)) {
              ++acc_aux[slot];
              acc_f[slot] = acc_f[slot] < v ? acc_f[slot] : v;
            }
          }
          break;
        case 7:  // MAX f64 (any valid NaN wins at finalize; aux counts NaNs)
          if (av) {
            const double v = static_cast<const double*>(a_cols[a])[r];
            ++acc_cnt[slot];
            if (v != v) {
              ++acc_aux[slot];
            } else {
              acc_f[slot] = acc_f[slot] > v ? acc_f[slot] : v;
            }
          }
          break;
        default:  // unreachable: ops validated before the row loop
          break;
      }
    }
  }
  *n_groups_io = n_groups;
  *rows_passed_io += passed;
  return n - row_start;
}

// MurmurHash3-32 bucket ids over k int64 key columns, one pass per row.
// Bit-exact twin of ops/hash.bucket_ids_host (numpy) and the XLA kernel:
// each key rep contributes its lo then hi uint32 word to the block
// stream, fmix length is 8*k bytes, bucket = h % num_buckets. The numpy
// twin makes ~10 full-array passes over the mix pipeline; this is one.
static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mm3_mix(uint32_t h, uint32_t w) {
  uint32_t k1 = w * 0xCC9E2D51u;
  k1 = rotl32(k1, 15);
  k1 *= 0x1B873593u;
  h ^= k1;
  h = rotl32(h, 13);
  return h * 5u + 0xE6546B64u;
}

int hs_bucket_ids_i64(const int64_t** keys, int32_t k, int64_t n,
                      uint32_t seed, uint32_t num_buckets, int32_t* out) {
  if (n < 0 || k <= 0 || num_buckets == 0) return 1;
  const uint32_t len = 8u * static_cast<uint32_t>(k);
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h = seed;
    for (int32_t j = 0; j < k; ++j) {
      const uint64_t v = static_cast<uint64_t>(keys[j][i]);
      h = mm3_mix(h, static_cast<uint32_t>(v));
      h = mm3_mix(h, static_cast<uint32_t>(v >> 32));
    }
    h ^= len;
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    out[i] = static_cast<int32_t>(h % num_buckets);
  }
  return 0;
}

}  // extern "C"
