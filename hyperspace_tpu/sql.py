"""SQL entry point: ``session.sql("SELECT ...")`` over registered views.

The reference exposes Hyperspace through Spark SQL by injecting its rule
via the session extension (``HyperspaceSparkSessionExtension.scala:44-69``)
— SQL queries get index rewrites for free because they flow through the
same optimizer. Same architecture here: this module only PARSES SQL into
the engine's logical IR (plan/nodes + plan/expressions); the resulting
DataFrame goes through ``session.execute`` → ``session.optimize``, so
FilterIndexRule/JoinIndexRule/data-skipping apply to SQL exactly as to the
DataFrame API.

Supported grammar (the subset the reference's examples/docs exercise):

    SELECT <*| item[, ...]> FROM <view>
      [JOIN <view> ON <col> = <col> [AND ...]]...
      [WHERE <boolean expr>]
      [GROUP BY col[, ...]]
      [ORDER BY col [ASC|DESC][, ...]]
      [LIMIT n]

    item := col | SUM|MIN|MAX|AVG|COUNT ( col | * ) [AS alias]
    expr := comparisons (= != <> < <= > >=), [NOT] IN (...),
            [NOT] BETWEEN a AND b, IS [NOT] NULL, AND / OR / NOT,
            parentheses; literals: numbers (incl. negative), 'strings',
            TRUE/FALSE/NULL, DATE 'YYYY-MM-DD'. ORDER BY may reference
            columns outside the select list (non-aggregate queries).
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E

_AGG_FUNCS = {"sum", "min", "max", "avg", "count", "mean"}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<number>\d+\.\d+|\.\d+|\d+)
  | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|-)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    """,
    re.VERBOSE,
)


def _tokenize(sql: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise HyperspaceException(
                f"SQL syntax error at {sql[pos:pos + 20]!r}"
            )
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    out.append(("end", ""))
    return out


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_keyword(self, *words: str) -> bool:
        kind, val = self.peek()
        return kind == "ident" and val.lower() in words

    def expect_keyword(self, word: str) -> None:
        if not self.at_keyword(word):
            raise HyperspaceException(
                f"Expected {word.upper()}, got {self.peek()[1]!r}"
            )
        self.next()

    def expect_op(self, op: str) -> None:
        kind, val = self.next()
        if kind != "op" or val != op:
            raise HyperspaceException(f"Expected {op!r}, got {val!r}")

    def ident(self) -> str:
        kind, val = self.next()
        if kind != "ident":
            raise HyperspaceException(f"Expected identifier, got {val!r}")
        return val

    # -- grammar ------------------------------------------------------------
    def parse(self, session, catalog) -> "Any":
        self.expect_keyword("select")
        items = self._select_list()
        self.expect_keyword("from")
        df = self._table(session, catalog)
        while self.at_keyword("join", "inner"):
            if self.at_keyword("inner"):
                self.next()
            self.expect_keyword("join")
            right = self._table(session, catalog)
            self.expect_keyword("on")
            cond = self._expr()
            df = df.join(right, on=cond)
        where = None
        if self.at_keyword("where"):
            self.next()
            where = self._expr()
        group_by: Optional[List[str]] = None
        if self.at_keyword("group"):
            self.next()
            self.expect_keyword("by")
            group_by = [self.ident()]
            while self._eat_comma():
                group_by.append(self.ident())
        order: List[Tuple[str, bool]] = []
        if self.at_keyword("order"):
            self.next()
            self.expect_keyword("by")
            order.append(self._order_item())
            while self._eat_comma():
                order.append(self._order_item())
        limit = None
        if self.at_keyword("limit"):
            self.next()
            kind, val = self.next()
            if kind != "number" or "." in val:
                raise HyperspaceException(f"LIMIT takes an integer, got {val!r}")
            limit = int(val)
        kind, val = self.peek()
        if kind != "end":
            raise HyperspaceException(f"Unexpected trailing SQL at {val!r}")

        if where is not None:
            df = df.filter(where)
        # standard SQL allows ORDER BY on columns outside the select list
        # (for non-aggregate queries): sort before projecting in that case
        sorted_early = False
        if order and group_by is None and not any(
            it[0] == "agg" for it in items
        ) and items != [("star",)]:
            selected = {it[1].lower() for it in items if it[0] == "col"}
            if any(c.lower() not in selected for c, _ in order):
                df = df.sort(*order)
                sorted_early = True
        df = self._apply_select(df, items, group_by)
        if order and not sorted_early:
            df = df.sort(*order)
        if limit is not None:
            df = df.limit(limit)
        return df

    def _table(self, session, catalog):
        name = self.ident()
        key = name.lower()
        if key not in catalog:
            raise HyperspaceException(
                f"Unknown table or view {name!r}; register with "
                f"df.create_or_replace_temp_view({name!r})"
            )
        return catalog[key]

    def _eat_comma(self) -> bool:
        kind, val = self.peek()
        if kind == "op" and val == ",":
            self.next()
            return True
        return False

    def _order_item(self) -> Tuple[str, bool]:
        col = self.ident()
        asc = True
        if self.at_keyword("asc"):
            self.next()
        elif self.at_keyword("desc"):
            self.next()
            asc = False
        return col, asc

    # select list: ("col", name, alias) | ("agg", func, col|None, alias)
    def _select_list(self):
        kind, val = self.peek()
        if kind == "op" and val == "*":
            self.next()
            return [("star",)]
        items = [self._select_item()]
        while self._eat_comma():
            items.append(self._select_item())
        return items

    def _select_item(self):
        name = self.ident()
        kind, val = self.peek()
        if name.lower() in _AGG_FUNCS and kind == "op" and val == "(":
            self.next()
            k2, v2 = self.peek()
            if k2 == "op" and v2 == "*":
                self.next()
                col = None
            else:
                col = self.ident()
            self.expect_op(")")
            alias = self._maybe_alias()
            return ("agg", name.lower(), col, alias)
        alias = self._maybe_alias()
        return ("col", name, alias)

    def _maybe_alias(self) -> Optional[str]:
        if self.at_keyword("as"):
            self.next()
            return self.ident()
        return None

    def _apply_select(self, df, items, group_by):
        from hyperspace_tpu import functions as F

        if items == [("star",)]:
            if group_by:
                raise HyperspaceException("SELECT * with GROUP BY")
            return df
        aggs = [it for it in items if it[0] == "agg"]
        cols = [it for it in items if it[0] == "col"]
        if aggs:
            plain = [it[1] for it in cols]
            if group_by is None:
                if plain:
                    raise HyperspaceException(
                        f"Non-aggregated columns {plain} without GROUP BY"
                    )
                group_by = []
            else:
                by_lower = {g.lower(): g for g in group_by}
                missing = [c for c in plain if c.lower() not in by_lower]
                if missing:
                    raise HyperspaceException(
                        f"Columns {missing} must appear in GROUP BY"
                    )
                # resolve select spellings to the GROUP BY spelling (the
                # aggregate's actual output column names)
                items = [
                    ("col", by_lower[it[1].lower()], it[2])
                    if it[0] == "col"
                    else it
                    for it in items
                ]
                cols = [it for it in items if it[0] == "col"]
            specs = []
            for _tag, func, col, alias in aggs:
                spec = (
                    F.count(col) if func == "count" else getattr(F, func)(col)
                )
                if alias:
                    spec = spec.alias(alias)
                specs.append(spec)
            gdf = df.group_by(group_by) if group_by else df.group_by([])
            out = gdf.agg(specs)
            if cols:  # order columns as written
                sel = []
                agg_names = [s.name for s in specs]
                ai = 0
                for it in items:
                    if it[0] == "col":
                        sel.append(it[1])
                    else:
                        sel.append(agg_names[ai])
                        ai += 1
                out = out.select(sel)
            return out
        if group_by:
            raise HyperspaceException("GROUP BY without aggregate functions")
        names = [it[1] for it in cols]
        aliases = [it[2] for it in cols]
        if any(aliases):
            raise HyperspaceException(
                "Column aliases are only supported on aggregates"
            )
        return df.select(names)

    # -- expressions --------------------------------------------------------
    def _expr(self) -> E.Expr:
        return self._or()

    def _or(self) -> E.Expr:
        left = self._and()
        while self.at_keyword("or"):
            self.next()
            left = E.Or(left, self._and())
        return left

    def _and(self) -> E.Expr:
        left = self._not()
        while self.at_keyword("and"):
            self.next()
            left = E.And(left, self._not())
        return left

    def _not(self) -> E.Expr:
        if self.at_keyword("not"):
            self.next()
            return E.Not(self._not())
        return self._primary()

    def _primary(self) -> E.Expr:
        kind, val = self.peek()
        if kind == "op" and val == "(":
            self.next()
            e = self._expr()
            self.expect_op(")")
            return e
        name = self.ident()
        if self.at_keyword("is"):
            self.next()
            negate = False
            if self.at_keyword("not"):
                self.next()
                negate = True
            self.expect_keyword("null")
            e: E.Expr = E.IsNull(E.Col(name))
            return E.Not(e) if negate else e
        if self.at_keyword("in", "not", "between"):
            negate = False
            if self.at_keyword("not"):
                self.next()
                negate = True
            if self.at_keyword("between"):
                self.next()
                lo = self._literal()
                self.expect_keyword("and")
                hi = self._literal()
                e: E.Expr = E.And(
                    E.Ge(E.Col(name), E.Lit(lo)), E.Le(E.Col(name), E.Lit(hi))
                )
                return E.Not(e) if negate else e
            self.expect_keyword("in")
            self.expect_op("(")
            vals = [self._literal()]
            while self._eat_comma():
                vals.append(self._literal())
            self.expect_op(")")
            e = E.Col(name).isin(*vals)
            return E.Not(e) if negate else e
        kind, op = self.next()
        if kind != "op" or op not in ("=", "!=", "<>", "<", "<=", ">", ">="):
            raise HyperspaceException(f"Expected comparison operator, got {op!r}")
        if op == "<>":
            op = "!="
        right = self._operand()
        node = {
            "=": E.Eq,
            "!=": E.Ne,
            "<": E.Lt,
            "<=": E.Le,
            ">": E.Gt,
            ">=": E.Ge,
        }[op]
        return node(E.Col(name), right)

    def _operand(self) -> E.Expr:
        """A comparison's right side: a column reference or a literal.

        ``TRUE``/``FALSE``/``NULL`` are reserved words (a column literally
        named one of them cannot appear as a bare operand — quote-free
        SQL has no way to disambiguate). ``DATE`` is only a keyword when
        a quoted string follows (``DATE '1994-01-01'``); otherwise it is
        an ordinary column name."""
        kind, val = self.peek()
        if kind == "ident":
            low = val.lower()
            is_date_literal = (
                low == "date"
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1][0] == "string"
            )
            if low not in ("true", "false", "null") and not is_date_literal:
                self.next()
                return E.Col(val)
        return E.Lit(self._literal())

    def _literal(self):
        kind, val = self.next()
        if kind == "op" and val == "-":
            k2, v2 = self.next()
            if k2 != "number":
                raise HyperspaceException(f"Expected number after '-', got {v2!r}")
            return -(float(v2) if "." in v2 else int(v2))
        if kind == "number":
            return float(val) if "." in val else int(val)
        if kind == "string":
            return val[1:-1].replace("''", "'")
        if kind == "ident":
            low = val.lower()
            if low == "true":
                return True
            if low == "false":
                return False
            if low == "null":
                return None
            if low == "date":
                k2, v2 = self.next()
                if k2 != "string":
                    raise HyperspaceException("DATE takes a quoted literal")
                import numpy as np

                # same doubled-quote unescape as plain string literals
                return np.datetime64(v2[1:-1].replace("''", "'"))
        raise HyperspaceException(f"Expected literal, got {val!r}")


def parse_sql(session, sql: str, catalog) -> "Any":
    """Parse one SELECT statement into a DataFrame over the catalog."""
    return _Parser(sql).parse(session, catalog)
