"""Structured tracing — one root span per query / lifecycle action.

Flare (PAPERS.md) makes the case bluntly: once pipelines compile into
fused native passes, only *built-in* instrumentation can explain where
time went — an external profiler sees one opaque sweep. This module is
that instrumentation for the serve and build planes:

* A **root span** wraps every query admitted by the serve frontend
  (``serve/frontend.py``) and every lifecycle action
  (``actions/base.py``). Child **stage spans** mirror the legacy
  breakdown keys exactly — they are recorded by the SAME
  ``_stage_add`` hooks that feed ``last_serve_breakdown`` /
  ``last_build_breakdown`` (now instruments of ``obs/metrics.py``), so
  a trace's stage timings are consistent with the breakdowns *by
  construction*, never by parallel bookkeeping.

* **Context propagation.** The current span rides a ``contextvars``
  ContextVar. Thread pools do not propagate context, so every pool
  boundary on the serve path (the shared ``io/scan.scan_pool``, the
  frontend executor, the per-bucket/per-shard prepare and match pools)
  wraps its submitted callables in :func:`carry` — identity when
  tracing is off, a parent-handoff when on. Cross-PROCESS propagation
  rides the fleet planes: the single-flight claim file and the fanout
  bus events carry the publishing trace's id, so a cross-process dedup
  links winner and losers to one trace (``serve/fleet.py``,
  ``serve/bus.py``).

* **Zero-cost off path.** Every entry point checks one module bool
  (``_enabled``); with ``hyperspace.obs.enabled`` off (the default),
  :func:`span` returns a shared no-op singleton, :func:`carry` returns
  the callable untouched, and :func:`stage` is a single comparison —
  the serve path's behavior and timing are the pre-obs tree's.

Completed traces land in a bounded in-memory ring (:func:`finished`)
for bench/test introspection and are counted in the metrics registry;
the durable per-query record is the query log's job
(``obs/querylog.py``). Scope doctrine: process-global, last-writer-wins
configuration, like every telemetry plane in this tree.

Every span/metric call site in the package is declared in
``obs/sites.py`` (``OBS_SITES``) with a one-line justification —
hslint HS9xx (``analysis/obs.py``) rejects undeclared instrumentation
and stage-span names that drift from the breakdown vocabulary.
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

from hyperspace_tpu import constants as C

# -- module state (SHARED_STATE-registered; hyperspace_tpu/concurrency.py) --

#: master switch — rebind-only bool; a racy read costs one span, never a
#: torn value
_enabled = False

#: per-trace child-span cap / finished-trace ring size (rebind-only ints,
#: re-published whole by configure())
_max_spans = C.OBS_TRACE_MAX_SPANS_DEFAULT

_rec_lock = threading.Lock()
#: finished ROOT spans, oldest-first (guarded by _rec_lock)
_finished: deque = deque(maxlen=C.OBS_TRACE_RETAIN_DEFAULT)

#: the active span of the calling context (set via activate()/span())
_current: contextvars.ContextVar = contextvars.ContextVar(
    "hs_obs_span", default=None
)


def _now_ms() -> int:
    return int(time.time() * 1000)


class Span:
    """One timed operation. Roots own the flat list of their trace's
    finished spans (appended under ``_rec_lock`` — children finish on
    arbitrary pool threads); child spans carry a reference to their
    root. Attributes are plain JSON-able values."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start_ms",
        "_t0",
        "duration_s",
        "attrs",
        "root",
        "spans",
        "events",
        "spans_dropped",
    )

    def __init__(
        self,
        name: str,
        parent: Optional["Span"] = None,
        attrs: Optional[dict] = None,
    ):
        self.name = name
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = (
            parent.trace_id if parent is not None else uuid.uuid4().hex[:32]
        )
        self.span_id = uuid.uuid4().hex[:16]
        self.start_ms = _now_ms()
        self._t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.attrs: Dict = dict(attrs) if attrs else {}
        self.root: "Span" = parent.root if parent is not None else self
        # root-only trace state
        self.spans: List["Span"] = []
        self.events: List[Dict] = []
        self.spans_dropped = 0

    # -- lifecycle ----------------------------------------------------------
    def set(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def add_event(self, name: str, **attrs) -> None:
        """Attach a point-in-time event (retry, degrade, shed, link) to
        the trace; recorded on the ROOT under the record lock — events
        fire from arbitrary worker threads."""
        ev = {"name": name, "ts_ms": _now_ms(), **attrs}
        with _rec_lock:
            self.root.events.append(ev)

    def finish(self) -> "Span":
        if self.duration_s is not None:
            return self  # idempotent — double-finish keeps the first
        self.duration_s = time.perf_counter() - self._t0
        root = self.root
        with _rec_lock:
            if len(root.spans) < _max_spans:
                root.spans.append(self)
            else:
                root.spans_dropped += 1
            if root is self:
                _finished.append(self)
        if root is self:
            from hyperspace_tpu.obs import metrics as _m

            _m.traces_total.inc()
            _m.spans_total.inc(len(self.spans))
        return self

    # -- context-manager protocol ------------------------------------------
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": self.start_ms,
            "duration_s": self.duration_s,
            "attrs": dict(self.attrs),
        }

    def stage_seconds(self) -> Dict[str, float]:
        """Root-only: child span busy-seconds keyed by span name, summed
        — the same shape as ``last_serve_breakdown`` (stages overlap
        under the pipelined serve, so values are busy time and may sum
        past wall time, exactly like the breakdown they mirror)."""
        out: Dict[str, float] = {}
        with _rec_lock:
            spans = list(self.spans)
        for s in spans:
            if s is self or s.duration_s is None:
                continue
            out[s.name] = out.get(s.name, 0.0) + s.duration_s
        return out


class _NoopSpan:
    """The shared disabled-path span: every method is a no-op, so call
    sites never branch beyond the module-bool check in span()/root()."""

    __slots__ = ()
    trace_id = None
    span_id = None
    name = ""
    duration_s = None

    def set(self, key, value):
        return self

    def add_event(self, name, **attrs):
        pass

    def finish(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def stage_seconds(self):
        return {}


NOOP = _NoopSpan()


class _Activation:
    """Context manager installing ``span`` as the calling context's
    current span (and restoring the previous one on exit). With
    ``owned=True`` the span is also finished on exit (the ``with
    trace.span(...)`` shape); a plain activation leaves it open —
    activation and lifetime are decoupled because a root span outlives
    several activations (admission thread, then the worker running the
    query)."""

    __slots__ = ("_span", "_token", "_owned")

    def __init__(self, span, owned: bool = False):
        self._span = span
        self._token = None
        self._owned = owned

    def __enter__(self):
        if not isinstance(self._span, _NoopSpan):
            self._token = _current.set(self._span)
        return self._span

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._owned:
            self._span.finish()


# ---------------------------------------------------------------------------
# Public surface
# ---------------------------------------------------------------------------


def set_enabled(on: bool) -> None:
    """Flip the process-global tracing switch (rebind-only publish)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def configure(conf) -> bool:
    """Adopt a session's ``hyperspace.obs.*`` trace settings (process-
    global, last-writer-wins — the telemetry doctrine). Returns the
    resulting enabled state."""
    global _max_spans, _finished
    set_enabled(conf.obs_enabled)
    _max_spans = conf.obs_trace_max_spans
    retain = conf.obs_trace_retain
    with _rec_lock:
        if retain != _finished.maxlen:
            _finished = deque(_finished, maxlen=retain)
    return _enabled


def root(name: str, **attrs) -> Span:
    """Start a ROOT span (a new trace). Returns :data:`NOOP` when
    tracing is off — callers hold and finish the result either way."""
    if not _enabled:
        return NOOP
    return Span(name, parent=None, attrs=attrs)


def activate(span) -> _Activation:
    """Install ``span`` as the current span for a ``with`` block (does
    not finish it on exit — see :class:`_Activation`)."""
    return _Activation(span)


def span(name: str, **attrs):
    """Start a CHILD span of the current span, as a context manager
    that finishes it on exit. No-op when tracing is off or no trace is
    active in this context (stage instrumentation outside a root —
    e.g. a bare ``collect()`` with obs off — must cost nothing)."""
    if not _enabled:
        return NOOP
    parent = _current.get()
    if parent is None:
        return NOOP
    return _Activation(Span(name, parent=parent, attrs=attrs), owned=True)


def stage(
    name: str,
    t0: Optional[float] = None,
    seconds: Optional[float] = None,
    attrs: Optional[dict] = None,
) -> None:
    """Record an already-timed stage as a child span of the current
    context — either ``[t0, now]`` on the perf_counter clock or an
    explicit ``seconds`` duration (the shuffle plane measures stage
    seconds itself). This is the hook ``_stage_add`` calls: the
    stage-span timing IS the breakdown increment, so trace and
    breakdown can never disagree."""
    if not _enabled:
        return
    parent = _current.get()
    if parent is None:
        return
    s = Span(name, parent=parent, attrs=attrs)
    if seconds is not None:
        s.duration_s = None  # keep finish() running once, below
        s._t0 = time.perf_counter() - max(0.0, seconds)
    elif t0 is not None:
        s._t0 = t0
    s.start_ms = parent.root.start_ms + int(
        max(0.0, s._t0 - parent.root._t0) * 1000
    )
    s.finish()


def event(name: str, **attrs) -> None:
    """Attach a point event to the current trace (retry, degrade,
    shed, cross-process link); dropped when no trace is active."""
    if not _enabled:
        return
    cur = _current.get()
    if cur is not None:
        cur.add_event(name, **attrs)


def accumulate(key: str, value) -> None:
    """Add ``value`` into the ROOT span's ``attrs[key]`` (numeric
    accumulator, taken under the record lock — hooks fire from
    arbitrary pool threads). This is how per-execution counters that
    are produced deep inside the engine (e.g. zone-map pruning's
    rows-pruned count) attribute to the query that caused them instead
    of to a process-global last-writer cell: each execution's root
    carries exactly its own deltas, so concurrent queries never
    cross-attribute. Dropped when tracing is off or no trace is
    active."""
    if not _enabled:
        return
    cur = _current.get()
    if cur is None:
        return
    root_span = cur.root
    with _rec_lock:
        root_span.attrs[key] = root_span.attrs.get(key, 0) + value


def current() -> Optional[Span]:
    if not _enabled:
        return None
    return _current.get()


def current_trace_id() -> Optional[str]:
    """The active trace id, for cross-process propagation (claim files,
    bus events) — None when tracing is off or no trace is active."""
    cur = current()
    return cur.trace_id if cur is not None else None


def carry(fn: Callable) -> Callable:
    """Capture the calling context's current span and re-install it
    around every invocation of ``fn`` — the pool-boundary propagation
    shim (``ThreadPoolExecutor`` does not propagate contextvars).
    Identity when tracing is off or no span is active, so wrapped
    submit sites cost nothing on the disabled path. Safe for
    ``pool.map``: each invocation sets/resets independently."""
    if not _enabled:
        return fn
    parent = _current.get()
    if parent is None:
        return fn

    def run(*args, **kwargs):
        token = _current.set(parent)
        try:
            return fn(*args, **kwargs)
        finally:
            _current.reset(token)

    return run


def finished(name: Optional[str] = None) -> List[Span]:
    """Completed root spans, oldest first (optionally filtered by root
    name) — the bench/test introspection surface."""
    with _rec_lock:
        roots = list(_finished)
    if name is not None:
        roots = [r for r in roots if r.name == name]
    return roots


def reset() -> None:
    """Drop the finished-trace ring (test isolation)."""
    with _rec_lock:
        _finished.clear()
