"""Durable query log — the workload profile the advisor loop mines.

ROADMAP item 5 states the gap: the serve frontend sees every plan
fingerprint, predicate, latency and cache hit, and *nothing reads that
stream*. This module persists it: one JSONL record per served query,
appended to a bounded, rotated sidecar directory next to the lake
(``<hyperspace.system.path>/_hyperspace_obs/``) — machine-readable
input for a ``ScoreBasedIndexPlanOptimizer``-style advisor (PAPER.md
L5) and for post-hoc "why was this query slow" replay
(docs/observability.md has a worked example).

Record schema (one JSON object per line; schema_v bumps on change)::

    ts_ms            admission wall-clock ms
    trace_id         the query's root span (None with tracing off)
    fingerprint      sha256[:16] of the plan fingerprint — stable across
                     processes for identical (plan, snapshot, conf)
    predicate        structural predicate shape (operators + columns,
                     no literals — profile-safe)
    slo_class        admission class or None
    indexes          index names serving the rewritten plan ([] = source)
    rule             rewrite flavor ("join"/"filter"/"agg"/… or None)
    duration_s       client-observed serve seconds
    stages           {stage: busy_seconds} from the root span's children
                     (mirrors last_serve_breakdown keys)
    rows_returned    result rows
    rows_pruned      row groups pruned by the range plane during THIS
                     execution — the pruning pass accumulates its delta
                     onto the query's root span (obs/trace.accumulate),
                     so concurrent queries never cross-attribute (the
                     old last_prune_stats module read blurred exactly
                     that way)
    replay           optional re-executable plan spec (obs/planspec.py)
                     — present only when the operator opted into
                     ``hyperspace.obs.querylog.recordPlans`` (specs
                     carry literals, unlike ``predicate``)
    cache_hits       ServeCache hit counters delta is NOT tracked here;
                     the registry's cache view carries totals
    retries/degraded/deduped_into  per-query fault-path events
    status           "ok" | "failed"

Fleet-safety: every process appends to its OWN files
(``querylog.<pid>.<nonce>.jsonl``); the reader unions all files of all
processes, so no cross-process write coordination exists at all.

Rotation: the active file rotates once it exceeds ``maxBytes`` —
flush+fsync the active file, then atomically RENAME it to a sealed
segment name, then open a fresh active file; at most ``maxFiles``
sealed segments are kept per process (oldest pruned). The
``mid_querylog_rotate`` crash point (``testing/faults.py``) fires
between the fsync and the rename: a writer dying there leaves the
active file fsynced but un-sealed — the next writer (or reader) simply
keeps reading it, so a crash can tear at most the in-flight LINE (the
reader skips torn tails), never a sealed segment.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from typing import Dict, List, Optional

from hyperspace_tpu import constants as C
from hyperspace_tpu.obs import metrics as _metrics
from hyperspace_tpu.testing import faults
from hyperspace_tpu.utils import files as file_utils

SCHEMA_V = 1


def obs_root(conf) -> str:
    """``<hyperspace.system.path>/_hyperspace_obs`` — the lake-level
    observability sidecar directory."""
    system_path = conf.get_str(
        C.INDEX_SYSTEM_PATH, C.INDEX_SYSTEM_PATH_DEFAULT
    )
    return os.path.join(system_path, C.HYPERSPACE_OBS_DIR)


class QueryLog:
    """One process's append handle on a query-log directory.

    Thread model: ``append`` may be called from any serve worker; one
    lock serializes the write+rotate critical section (file I/O runs
    under it deliberately — this is a diagnostics plane, its lock is
    shared with nothing else and its latency is one buffered line
    write; rotation is rare and bounded)."""

    def __init__(
        self,
        directory: str,
        max_bytes: int = C.OBS_QUERYLOG_MAX_BYTES_DEFAULT,
        max_files: int = C.OBS_QUERYLOG_MAX_FILES_DEFAULT,
    ):
        self.directory = directory
        self.max_bytes = max(1, int(max_bytes))
        self.max_files = max(1, int(max_files))
        # pid + nonce: a recycled pid (or two logs in one test process)
        # must never append to a previous incarnation's active file
        self._tag = f"{os.getpid()}.{uuid.uuid4().hex[:8]}"
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self._seq = 0
        self.records = 0
        self.rotations = 0
        self.errors = 0

    # -- paths ---------------------------------------------------------------
    def _active_path(self) -> str:
        return os.path.join(self.directory, f"querylog.{self._tag}.jsonl")

    def _sealed_path(self, seq: int) -> str:
        return os.path.join(
            self.directory, f"querylog.{self._tag}.{seq:06d}.sealed.jsonl"
        )

    # -- append --------------------------------------------------------------
    def append(self, record: Dict) -> bool:
        """Write one record (adds ``schema_v``). Returns False — never
        raises — when the sidecar is unwritable: the query log is a
        diagnostics plane and must not fail the query it describes."""
        record = dict(record)
        record.setdefault("schema_v", SCHEMA_V)
        record.setdefault("ts_ms", int(time.time() * 1000))
        try:
            line = json.dumps(record, default=str, sort_keys=True) + "\n"
        except (TypeError, ValueError):
            self.errors += 1
            _metrics.querylog_errors_total.inc()
            return False
        # lock-held file I/O is this plane's documented design (class
        # docstring): the lock is private, shared with nothing else,
        # and one buffered line write is the hot cost
        with self._lock:  # hslint: disable=HS502
            try:
                if self._fh is None:
                    os.makedirs(self.directory, exist_ok=True)
                    self._fh = open(self._active_path(), "a", encoding="utf-8")
                    self._size = self._fh.tell()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line.encode("utf-8"))
                self.records += 1
                _metrics.querylog_records_total.inc()
                if self._size >= self.max_bytes:
                    self._rotate_locked()
            except OSError:
                self.errors += 1
                _metrics.querylog_errors_total.inc()
                return False
        return True

    def _rotate_locked(self) -> None:
        """Seal the active file (fsync → crash point → atomic rename →
        dir fsync), open a fresh one, prune old segments. A crash at
        ``mid_querylog_rotate`` leaves the fsynced active file in place
        under its active name — readers union it like any segment, the
        next process uses its own tag, nothing is lost or doubled."""
        fh, self._fh = self._fh, None
        try:
            fh.flush()
            os.fsync(fh.fileno())
        finally:
            fh.close()
        # the crash window the recovery matrix exercises: data durable,
        # segment not yet sealed
        faults.crash("mid_querylog_rotate", self._active_path())
        self._seq += 1
        os.replace(self._active_path(), self._sealed_path(self._seq))
        file_utils.fsync_dir(self.directory)
        self.rotations += 1
        _metrics.querylog_rotations_total.inc()
        self._size = 0
        self._fh = open(self._active_path(), "a", encoding="utf-8")
        self._prune_locked()

    def _prune_locked(self) -> None:
        """Keep at most ``max_files`` sealed segments of THIS process
        (other processes prune their own — no cross-process races)."""
        prefix = f"querylog.{self._tag}."
        try:
            names = sorted(
                n
                for n in os.listdir(self.directory)
                if n.startswith(prefix) and n.endswith(".sealed.jsonl")
            )
        except OSError:
            return
        for name in names[: max(0, len(names) - self.max_files)]:
            file_utils.delete(os.path.join(self.directory, name))

    def close(self) -> None:
        # same private-lock I/O contract as append()
        with self._lock:  # hslint: disable=HS502
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "QueryLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_records(directory: str) -> List[Dict]:
    """Union every process's records under ``directory`` (active files
    AND sealed segments), oldest-file-first, torn trailing lines
    skipped — the reader side of the fleet-safe contract."""
    try:
        names = sorted(
            n
            for n in os.listdir(directory)
            if n.startswith("querylog.") and n.endswith(".jsonl")
        )
    except OSError:
        return []
    out: List[Dict] = []
    for name in names:
        out.extend(_metrics.read_jsonl(os.path.join(directory, name)))
    return out


def read_valid_records(directory: str) -> List[Dict]:
    """:func:`read_records` plus the forward-compat filter every
    CONSUMER (advisor, replay, bench gates) must apply: records whose
    ``schema_v`` is missing, non-int, or NEWER than this reader
    understands are skipped and counted
    (``hs_obs_querylog_skipped_total``), never raised on — a fleet mid
    rolling-upgrade has old readers and new writers sharing one
    directory, and an old advisor choking on a new record shape would
    turn a diagnostics plane into an outage."""
    out: List[Dict] = []
    for rec in read_records(directory):
        v = rec.get("schema_v")
        if not isinstance(v, int) or isinstance(v, bool) or v > SCHEMA_V:
            _metrics.querylog_skipped_total.inc()
            continue
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Plan summaries (profile-safe: structure, never literals)
# ---------------------------------------------------------------------------

_LITERAL_STR = re.compile(r"'[^']*'|\"[^\"]*\"")
_LITERAL_NUM = re.compile(r"(?<![\w.])\d+(?:\.\d+)?(?![\w.])")


def predicate_shape(plan) -> str:
    """The plan's structural shape with every literal scrubbed to ``?``
    — stable across parameter values, so the advisor can group records
    by query TEMPLATE (the unit index recommendations apply to) without
    the log ever retaining user data."""
    try:
        shape = repr(plan)
    except Exception:  # hslint: disable=HS402
        # a summary helper must never fail the query it describes
        return ""
    shape = _LITERAL_STR.sub("'?'", shape)
    shape = _LITERAL_NUM.sub("?", shape)
    return shape[:2048]


def indexes_in_plan(plan) -> List[str]:
    """Index names serving a REWRITTEN plan: leaf relations reading
    from a ``v__=N`` index version directory name the index one path
    component up. Empty list = the source plan (no rewrite)."""
    names: List[str] = []
    try:
        for leaf in plan.collect_leaves():
            for f in leaf.relation.files[:1]:
                parts = str(f).replace("\\", "/").split("/")
                for i, part in enumerate(parts):
                    if part.startswith(C.INDEX_VERSION_DIR_PREFIX + "=") and i:
                        if parts[i - 1] not in names:
                            names.append(parts[i - 1])
                        break
    except Exception:  # hslint: disable=HS402
        return names
    return names


def rule_flavor(plan) -> Optional[str]:
    """Coarse rewrite flavor from the ORIGINAL plan's shape — the
    advisor's grouping key, not a precise rule name. The dominant
    operator wins: any Join anywhere makes it a join plan, else an
    Aggregate makes it agg, else filter/scan by the top shape."""
    try:
        kinds = set()
        stack = [plan]
        while stack:
            node = stack.pop()
            kinds.add(type(node).__name__)
            for attr in ("child", "left", "right"):
                sub = getattr(node, attr, None)
                if sub is not None:
                    stack.append(sub)
        if "Join" in kinds:
            return "join"
        if "Aggregate" in kinds:
            return "agg"
        if "Filter" in kinds or "Project" in kinds:
            return "filter"
        if "Union" in kinds:
            return "union"
        return "scan"
    except Exception:  # hslint: disable=HS402
        return None


def validate_record(record: Dict) -> Optional[str]:
    """Schema check for one record (the bench_smoke replay gate):
    returns an error string or None. Required fields must exist with
    the right JSON types; unknown fields are allowed (forward
    compatibility)."""
    required = {
        "schema_v": int,
        "ts_ms": int,
        "fingerprint": str,
        "duration_s": (int, float),
        "status": str,
        "stages": dict,
        "rows_returned": int,
    }
    for field, typ in required.items():
        if field not in record:
            return f"missing field {field!r}"
        if not isinstance(record[field], typ):
            return (
                f"field {field!r} has type "
                f"{type(record[field]).__name__}, want {typ}"
            )
    if record["status"] not in ("ok", "failed"):
        return f"bad status {record['status']!r}"
    for stage, v in record["stages"].items():
        if not isinstance(v, (int, float)):
            return f"stage {stage!r} timing is not numeric"
    return None
