"""OBS_SITES — the registry of observability instrumentation sites.

The SHARED_STATE / KERNEL_TWINS / COLLECTIVE_SITES doctrine applied to
the observability plane: every call site that CREATES spans
(``trace.root`` / ``trace.span`` / ``trace.stage``) or REGISTERS
metrics (``registry.counter`` / ``gauge`` / ``labeled_counter`` /
``stage_timer`` / ``register_view`` / ``register_weak_view``) declares
itself HERE with a
one-line justification — so "what is instrumented, and why?" is a
mechanical question (``hslint`` HS9xx, ``analysis/obs.py``), not an
archaeology project, and a hot loop cannot silently grow a span per
row. Propagation shims (``trace.carry``/``activate``) and point events
(``trace.event``) are deliberately exempt: they create no spans.

Entry shape::

    "<dotted path of the function, method, or module>": (
        "<kind: span | metric | view>",
        "<one-line justification — why this site is instrumented>",
    )

Paths name a module-level function
(``hyperspace_tpu.execution.join_exec._stage_add``), a method
(``hyperspace_tpu.serve.frontend.ServeFrontend.submit``), or a whole
module (``hyperspace_tpu.execution.join_exec`` — module-level
instrument registration). Calls in nested defs/lambdas attribute to
their outermost enclosing def, like the collective registry.

Stage-span VOCABULARY: HS902 rejects any constant stage/span name that
is not listed below — stage spans exist to mirror the legacy breakdown
keys, and a misspelled span name would silently fork the taxonomy the
querylog, the bench gates and docs/observability.md all key on.

Keep this module stdlib-only and import-cheap: the analyzer only ever
parses it, and the obs plane imports it for the vocabulary.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: site kinds (HS903 rejects anything else)
KINDS = ("span", "metric", "view")

#: serve-side stage spans — the last_serve_breakdown keys plus the
#: frontend's admission stages (docs/observability.md "Span taxonomy")
SERVE_STAGES = (
    "queue_wait",
    "pin",
    "rewrite",
    "prune",
    "scan",
    "prepare",
    "match",
    "expand",
    "verify",
    "assemble",
    "delta",
    "agg",
    "finalize",
    "execute",
    # out-of-core serve (docs/out-of-core.md): one span per streaming
    # join wave, and the spill tier's demote/restore I/O
    "stream_wave",
    "spill_write",
    "spill_restore",
)

#: build/lifecycle stage spans — the last_build_breakdown keys plus the
#: shuffle stage seconds and the metadata-plane seams
BUILD_STAGES = (
    "scan",
    "hash_shuffle",
    "pack",
    "exchange",
    "unpack",
    "sort",
    "write",
    "sidecar_capture",
    "log_commit",
)

#: advisor-side stage spans (advisor/: query-log mining and what-if
#: scoring under one "advisor.run" root — docs/advisor.md)
ADVISOR_STAGES = (
    "advisor.scan",
    "advisor.score",
)

#: root span names (constant ones; action roots are "action.<Class>")
ROOT_NAMES = ("serve.query", "advisor.run")

#: the full constant-name vocabulary HS902 checks against
STAGE_NAMES = tuple(
    sorted(set(SERVE_STAGES) | set(BUILD_STAGES) | set(ADVISOR_STAGES))
)

OBS_SITES: Dict[str, Tuple[str, str]] = {
    # -- serve plane ---------------------------------------------------------
    "hyperspace_tpu.serve.frontend.ServeFrontend.submit": (
        "span",
        "the query ROOT span starts at admission so queue-wait is "
        "attributable; one root per admitted query is the bench gate",
    ),
    "hyperspace_tpu.serve.frontend.ServeFrontend._pin": (
        "span",
        "snapshot pinning is a metadata read with its own retry loop — "
        "a slow pin must be distinguishable from a slow execute",
    ),
    "hyperspace_tpu.serve.frontend.ServeFrontend._run": (
        "span",
        "queue_wait closes when a worker picks the query up; the root "
        "span finishes (and the querylog row lands) here",
    ),
    "hyperspace_tpu.serve.frontend.ServeFrontend._execute_pinned": (
        "span",
        "rewrite vs execute split: index selection time must never be "
        "conflated with data-plane time",
    ),
    "hyperspace_tpu.serve.frontend.ServeFrontend.__init__": (
        "view",
        "the frontend's stats() counters export live through the "
        "registry (one owner, one lock — no counter forking)",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache.__init__": (
        "view",
        "the memory governor's stats() export live through the "
        "registry, same single-owner discipline as the frontend",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._spill_demote": (
        "span",
        "spill_write is pickle + fsync'd publish outside every "
        "breakdown stage — unexplained serve tail time under memory "
        "pressure must be attributable to the spill tier",
    ),
    "hyperspace_tpu.execution.serve_cache.ServeCache._restore_from_spill": (
        "span",
        "spill_restore makes the cost of serving from the disk tier "
        "visible next to the scan/prepare stages it displaces",
    ),
    "hyperspace_tpu.serve.fleet": (
        "metric",
        "cross-process single-flight election attempts/wins/losses as "
        "process-global counters: election health is fleet-level "
        "telemetry every sink must export, not one frontend's stats()",
    ),
    "hyperspace_tpu.execution.join_exec": (
        "metric",
        "last_serve_breakdown IS this stage_timer's backing dict — the "
        "scattered serve snapshot absorbed as a registered instrument",
    ),
    "hyperspace_tpu.execution.join_exec._stage_add": (
        "span",
        "the ONE serve stage hook: the stage span and the breakdown "
        "increment are the same measurement, so they cannot disagree",
    ),
    "hyperspace_tpu.execution.executor._exec": (
        "span",
        "the agg stage (metadata lowering + fused pass + interpreted "
        "chain) is invisible to the join breakdown; its span closes the "
        "serve taxonomy",
    ),
    # -- build / lifecycle plane ---------------------------------------------
    "hyperspace_tpu.indexes.covering_build": (
        "metric",
        "last_build_breakdown IS this stage_timer's backing dict — the "
        "build snapshot absorbed as a registered instrument",
    ),
    "hyperspace_tpu.indexes.covering_build._stage_add": (
        "span",
        "the ONE build stage hook, mirroring the serve-side discipline",
    ),
    "hyperspace_tpu.parallel.shuffle._publish_stats": (
        "span",
        "pack/exchange/unpack stage spans from the exchange's own "
        "measured seconds — the fused-native-pass visibility Flare "
        "argues for, applied to the shuffle",
    ),
    "hyperspace_tpu.indexes.aggindex.capture_index_dir": (
        "span",
        "sidecar capture is build-tail I/O outside every breakdown "
        "stage; unexplained build tail time lands here",
    ),
    "hyperspace_tpu.actions.base.Action.run": (
        "span",
        "the lifecycle-action ROOT span — every action is explainable "
        "after the fact, whatever the outcome",
    ),
    "hyperspace_tpu.actions.base.Action._run_protocol": (
        "span",
        "log_commit stage: metadata-plane publish time must be "
        "separable from data-plane op() time",
    ),
    "hyperspace_tpu.actions.base.Action._run_coordinated": (
        "span",
        "the coordinator-side log_commit stage on multi-process jobs "
        "(the same seam, behind the rendezvous protocol)",
    ),
    # -- workload advisor (advisor/, docs/advisor.md) ------------------------
    "hyperspace_tpu.advisor.recommend.advise": (
        "span",
        "the advisor.run ROOT span — one trace per advise() pass, so "
        "mining + what-if time is explainable in the same plane it "
        "consumes",
    ),
    "hyperspace_tpu.advisor.profile.build_profile": (
        "span",
        "advisor.scan stage: query-log union + shape aggregation time, "
        "separable from scoring (a huge log must be visible as a scan "
        "cost, not a mystery)",
    ),
    "hyperspace_tpu.advisor.whatif.score_workload": (
        "span",
        "advisor.score stage: one span per candidate's workload pass — "
        "what-if cost scales with candidates x shapes and must be "
        "attributable",
    ),
    "hyperspace_tpu.advisor.profile": (
        "metric",
        "advisor health counters (profiles built, shape-cap overflows) "
        "— the convergence loop's own telemetry rides the registry",
    ),
    "hyperspace_tpu.testing.replay": (
        "metric",
        "replay harness instruments (queries replayed/skipped/failed) — "
        "the bench replay gate asserts on these, same plane as the "
        "querylog counters",
    ),
}
