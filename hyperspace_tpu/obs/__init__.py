"""hyperspace_tpu.obs — the unified observability plane.

Three legs (docs/observability.md):

* :mod:`obs.trace` — structured tracing: one root span per frontend
  query and per lifecycle action, child stage spans mirroring the
  legacy breakdown keys, context propagated across every serve-path
  thread pool and (via the fleet claim/spool plane and bus events)
  across processes. Zero-cost no-op path when ``hyperspace.obs.enabled``
  is off.
* :mod:`obs.metrics` — the typed counter/gauge/stage-timer registry
  that absorbed the scattered telemetry snapshots
  (``last_serve_breakdown`` / ``last_build_breakdown`` are views over
  registered instruments; frontend/cache ``stats()`` export as live
  views), with a Prometheus text exporter and a JSONL sink.
* :mod:`obs.querylog` — the durable per-query JSONL log next to the
  lake (bounded, rotated, fleet-safe) — the workload profile the
  advisor loop (ROADMAP item 5) mines.

Every instrumentation site is declared in :mod:`obs.sites`
(``OBS_SITES``); hslint HS9xx (``analysis/obs.py``) enforces it.
"""

from __future__ import annotations

from hyperspace_tpu.obs import metrics, querylog, sites, trace
from hyperspace_tpu.obs.metrics import merge_snapshots, registry
from hyperspace_tpu.obs.querylog import QueryLog, read_records

__all__ = [
    "trace",
    "metrics",
    "querylog",
    "sites",
    "registry",
    "merge_snapshots",
    "QueryLog",
    "read_records",
]
