"""Typed metrics registry — counters, gauges, stage timers, views.

The telemetry this tree accumulated lives in scattered module-level
snapshots (``join_exec.last_serve_breakdown``,
``covering_build.last_build_breakdown``, ``ServeFrontend.stats()``,
``ServeCache.stats()``, ``shuffle.last_shuffle_stats``). This module is
the one place they all surface:

* **Instruments.** :class:`Counter` / :class:`Gauge` /
  :class:`LabeledCounter` / :class:`StageTimer` are typed, individually
  locked, and registered by name in the process-global
  :data:`registry`. The two breakdown dicts are now *views over
  registry instruments*: ``last_serve_breakdown`` /
  ``last_build_breakdown`` ARE the backing dicts of registered
  :class:`StageTimer` instruments (same dict object, same lock — the
  SHARED_STATE entries and every legacy reader keep working
  unchanged), so absorbing them cost no bookkeeping fork.

* **Views.** Live ``stats()`` providers (the serve frontend, the serve
  cache) register a zero-copy snapshot callable; :func:`MetricsRegistry.
  snapshot` and the Prometheus exporter read through them, so the
  registry never duplicates counter state that already has one owner
  and one lock.

* **Exporters.** :meth:`MetricsRegistry.render_prometheus` renders the
  whole registry (instruments + flattened numeric view leaves) in
  Prometheus text exposition format; :class:`JsonlSink` appends
  records as JSON lines (fsync on close) — the in-tree sink that
  finally gives ``telemetry.EventLogging`` a real logger
  (``telemetry.JsonlEventLogger``).

* **merge_snapshots.** The one documented way to combine counter
  snapshots from several frontends/processes (bench.py and the fleet
  harness used to hand-merge in three places): numeric values sum,
  ``snapshot_at_ms`` / ``*high_water*`` / ``max_*`` take the max,
  percentile keys (``p50*``/``p99*``) are dropped (percentiles do not
  merge), nested dicts merge recursively.

Stdlib-only and import-cheap: ``join_exec`` and ``covering_build``
import this at module load, and the analyzer's fixture trees parse it.
All registry state is declared in ``SHARED_STATE``
(``hyperspace_tpu/concurrency.py``).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional


def _now_ms() -> int:
    return int(time.time() * 1000)


class Counter:
    """Monotonic counter. ``inc`` is the only mutator (own lock)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self):
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-written value (set/add under the lock)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self):
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class LabeledCounter:
    """Counter family keyed by one label value (event types, fired
    points). ``data`` is the backing dict — mutate only through
    :meth:`inc` (the lock), read via :meth:`snapshot`."""

    __slots__ = ("name", "help", "lock", "data")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.lock = threading.Lock()
        self.data: Dict[str, int] = {}

    def inc(self, label: str, n: int = 1) -> None:
        with self.lock:
            self.data[label] = self.data.get(label, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self.lock:
            return dict(self.data)

    def reset(self) -> None:
        with self.lock:
            self.data.clear()


class StageTimer:
    """Per-stage busy-seconds accumulator — the instrument the legacy
    breakdown dicts became. A module that already owns a breakdown
    dict + lock (``last_serve_breakdown``/``_serve_bd_lock``,
    ``last_build_breakdown``/``_build_bd_lock``) passes them in: the
    instrument ADOPTS that exact storage, so the registry exports the
    same dict the legacy readers, SHARED_STATE entries and the lock
    witness already know — one storage, now registered."""

    __slots__ = ("name", "help", "lock", "data")

    def __init__(
        self,
        name: str,
        help_: str = "",
        data: Optional[Dict[str, float]] = None,
        lock: Optional[threading.Lock] = None,
    ):
        self.name = name
        self.help = help_
        self.lock = lock if lock is not None else threading.Lock()
        self.data: Dict[str, float] = data if data is not None else {}

    def add(self, stage: str, dt: float) -> None:
        with self.lock:
            self.data[stage] = self.data.get(stage, 0.0) + dt

    def snapshot(self) -> Dict[str, float]:
        with self.lock:
            return dict(self.data)

    def reset(self) -> None:
        with self.lock:
            self.data.clear()


_INSTRUMENT_TYPES = (Counter, Gauge, LabeledCounter, StageTimer)


class MetricsRegistry:
    """Name -> instrument/view map. One lock guards the maps; every
    instrument guards its own state — snapshotting acquires registry
    lock first, instrument locks second (one direction, no cycle), and
    no I/O ever runs under either."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self._views: Dict[str, Callable[[], dict]] = {}

    # -- registration --------------------------------------------------------
    def _get_or_create(self, cls, name: str, help_: str):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help_)
                self._instruments[name] = inst
            elif type(inst) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_)

    def labeled_counter(self, name: str, help_: str = "") -> LabeledCounter:
        return self._get_or_create(LabeledCounter, name, help_)

    def stage_timer(
        self,
        name: str,
        help_: str = "",
        data: Optional[Dict[str, float]] = None,
        lock: Optional[threading.Lock] = None,
    ) -> StageTimer:
        """Get-or-create a stage timer; pass ``data``/``lock`` to adopt
        a pre-existing breakdown dict + its declared lock (see
        :class:`StageTimer`)."""
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = StageTimer(name, help_, data=data, lock=lock)
                self._instruments[name] = inst
            elif type(inst) is not StageTimer:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not StageTimer"
                )
            return inst

    def register_view(self, name: str, provider: Callable[[], dict]) -> None:
        """Register a live snapshot provider (``stats()`` of a frontend
        or cache). Last registration wins — the process-global
        last-writer-wins telemetry doctrine; a dead provider (raises)
        renders as an empty view, never fails the snapshot."""
        with self._lock:
            self._views[name] = provider

    def register_weak_view(self, name: str, obj) -> Callable[[], dict]:
        """Register ``obj.stats()`` as the view named ``name``, weakly
        bound so the registry never keeps a replaced instance (and its
        memory) alive. Returns the provider — pass it back to
        :meth:`unregister_view` so only the CURRENT registrant can
        remove the view. ``is not None``, never truthiness: ``__len__``
        makes an empty container falsy, which would blank the view
        exactly when it matters."""
        import weakref

        ref = weakref.ref(obj)

        def provider() -> dict:
            live = ref()
            return live.stats() if live is not None else {}

        self.register_view(name, provider)
        return provider

    def unregister_view(self, name: str, provider=None) -> None:
        """Remove the view — but with ``provider`` given, only when it
        is still the registered one (a closing instance must not tear
        down a NEWER instance's live view under last-wins)."""
        with self._lock:
            if provider is None or self._views.get(name) is provider:
                self._views.pop(name, None)

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """One coherent-enough dict of everything registered:
        per-instrument snapshots plus each view's current ``stats()``.
        Cross-instrument consistency is NOT promised (each instrument
        snapshots under its own lock) — the same contract as reading
        two ``last_*`` dicts was."""
        with self._lock:
            instruments = dict(self._instruments)
            views = dict(self._views)
        out: dict = {"snapshot_at_ms": _now_ms(), "instruments": {}, "views": {}}
        for name, inst in sorted(instruments.items()):
            out["instruments"][name] = inst.snapshot()
        for name, provider in sorted(views.items()):
            try:
                out["views"][name] = provider()
            except Exception:  # hslint: disable=HS402
                # a closed frontend's view must not fail the exporter
                out["views"][name] = {}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the registry: instruments as
        their natural types, views flattened to numeric leaves as
        gauges (``hs_view_<view>_<path>``)."""
        snap = self.snapshot()
        with self._lock:
            instruments = dict(self._instruments)
        lines: List[str] = []

        def emit(name, kind, help_, samples):
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(samples)

        for name in sorted(instruments):
            inst = instruments[name]
            metric = _prom_name(name)
            val = snap["instruments"][name]
            if isinstance(inst, Counter):
                emit(metric, "counter", inst.help, [f"{metric} {val}"])
            elif isinstance(inst, Gauge):
                emit(metric, "gauge", inst.help, [f"{metric} {_prom_num(val)}"])
            elif isinstance(inst, LabeledCounter):
                emit(
                    metric,
                    "counter",
                    inst.help,
                    [
                        f'{metric}{{label="{k}"}} {v}'
                        for k, v in sorted(val.items())
                    ],
                )
            elif isinstance(inst, StageTimer):
                emit(
                    metric,
                    "counter",
                    inst.help,
                    [
                        f'{metric}{{stage="{k}"}} {_prom_num(v)}'
                        for k, v in sorted(val.items())
                    ],
                )
        for view_name in sorted(snap["views"]):
            flat = _flatten_numeric(snap["views"][view_name])
            if not flat:
                continue
            metric = _prom_name(f"hs_view_{view_name}")
            lines.append(f"# TYPE {metric} gauge")
            lines.extend(
                f'{metric}{{key="{k}"}} {_prom_num(v)}'
                for k, v in sorted(flat.items())
            )
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every instrument and drop the views (test isolation;
        instruments stay registered — module-level handles keep
        working)."""
        with self._lock:
            instruments = list(self._instruments.values())
            self._views.clear()
        for inst in instruments:
            inst.reset()


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _PROM_BAD.sub("_", name)


def _prom_num(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _flatten_numeric(d: dict, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if not isinstance(d, dict):
        return out
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            out[key] = float(v)
        elif isinstance(v, (int, float)):
            out[key] = v
        elif isinstance(v, dict):
            out.update(_flatten_numeric(v, prefix=f"{key}_"))
    return out


#: the process-global registry (SHARED_STATE: its maps mutate only
#: under its lock; instruments carry their own locks)
registry = MetricsRegistry()

#: trace-plane counters (obs/trace.py increments these at root finish)
traces_total = registry.counter(
    "hs_obs_traces_total", "completed root spans (queries + actions)"
)
spans_total = registry.counter(
    "hs_obs_spans_total", "completed spans across all traces"
)
#: telemetry events routed through EventLogging (labeled by event class)
events_total = registry.labeled_counter(
    "hs_events_total", "telemetry events by event class"
)
#: querylog plumbing health (obs/querylog.py)
querylog_records_total = registry.counter(
    "hs_querylog_records_total", "query-log records appended"
)
querylog_rotations_total = registry.counter(
    "hs_querylog_rotations_total", "query-log segment rotations"
)
querylog_errors_total = registry.counter(
    "hs_querylog_errors_total", "query-log append/rotate failures (dropped)"
)
querylog_skipped_total = registry.counter(
    "hs_obs_querylog_skipped_total",
    "query-log records skipped by readers (unknown/newer schema_v)",
)


# ---------------------------------------------------------------------------
# Snapshot merging (the three hand-merge sites this replaces:
# testing/fleet_harness.py per-worker fleet sums x3; bench.py reads the
# merged dict)
# ---------------------------------------------------------------------------

#: keys combined by max, not sum (watermarks and snapshot stamps)
_MAX_KEYS = re.compile(r"(^|_)(high_water|max)(_|$)|snapshot_at_ms")
#: keys that do not merge at all (percentiles of disjoint populations)
_DROP_KEYS = re.compile(r"^p\d+(_|$)")


def merge_snapshots(*snaps: dict) -> dict:
    """Merge counter snapshots (``stats()`` dicts) from several
    frontends/processes into one: numeric values SUM, watermark-style
    keys (``*high_water*``, ``max_*``/``*_max``, ``snapshot_at_ms``)
    take the MAX, percentile keys (``p50_ms``…) are dropped
    (percentiles of disjoint populations do not merge), nested dicts
    merge recursively, and non-numeric leaves keep the first value
    seen. The one documented way to combine fleet counters —
    bench.py/fleet_harness hand-rolled this thrice before."""
    out: dict = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for k, v in snap.items():
            if _DROP_KEYS.search(str(k)):
                continue
            if isinstance(v, dict):
                prev = out.get(k)
                out[k] = merge_snapshots(
                    prev if isinstance(prev, dict) else {}, v
                )
            elif isinstance(v, bool) or not isinstance(v, (int, float)):
                out.setdefault(k, v)
            elif k not in out or not isinstance(out[k], (int, float)):
                out[k] = v
            elif _MAX_KEYS.search(str(k)):
                out[k] = max(out[k], v)
            else:
                out[k] = out[k] + v
    return out


# ---------------------------------------------------------------------------
# JSONL sink
# ---------------------------------------------------------------------------


class JsonlSink:
    """Append-only JSON-lines sink (one record per line, flushed per
    write so a crash loses at most the in-flight line; the reader side
    skips torn trailing lines). Thread-safe; ``close`` fsyncs."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, record: dict) -> None:
        line = json.dumps(record, default=str, sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        # lock-held I/O is this sink's deliberate design: the lock is
        # private to the sink, shared with nothing else, and serializes
        # writers against a once-per-process close
        with self._lock:  # hslint: disable=HS502
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass
            self._fh.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str) -> List[dict]:
    """Parse a JSONL file, skipping torn/partial lines (the crash
    contract of :class:`JsonlSink` and the query log)."""
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return out
    return out
