"""Replayable plan specs — the literal-bearing twin of predicate_shape.

The query log's ``predicate`` field deliberately scrubs literals
(``querylog.predicate_shape``), which makes records profile-safe but
NOT re-executable: the advisor's what-if scorer and the replay harness
(``testing/replay.py``) both need the recorded plan back as a live
``LogicalPlan``. This module is that bridge: :func:`to_spec` serializes
a plan into a small JSON-able dict (operators, columns, join keys,
aggregate specs — and, unlike the shape, the literals), and
:func:`from_spec` rebuilds it against a session, re-reading the source
at the CURRENT snapshot (replay serves today's lake, which is exactly
what a what-if comparison wants).

Recording is opt-in (``hyperspace.obs.querylog.recordPlans``) because
specs carry literals: the default query log stays literal-free, and an
operator turns plan recording on only where replay/advisor fidelity is
worth it. Scenario generators (``testing/replay.py``) always emit
specs — canned workloads have nothing to leak.

Both directions are strictly best-effort: :func:`to_spec` returns None
for any plan (or literal) outside the supported subset — the record
then simply has no ``replay`` field — and :func:`from_spec` raises
:class:`~hyperspace_tpu.exceptions.HyperspaceException` with the
offending op so a replay reports the skip instead of crashing.

Supported subset: Scan (parquet/csv/json/orc/avro/text over root
paths), Filter, Project, inner equi-Join, Aggregate, Sort, Limit, with
comparison/boolean/In/IsNull predicates over int/float/str/bool/None
literals. ``SPEC_V`` bumps on change; readers skip unknown versions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
)

SPEC_V = 1

#: relation formats from_spec can re-read via session.read.<fmt>()
_FORMATS = ("parquet", "csv", "json", "orc", "avro", "text")

_BINARY_OPS = {
    E.Eq: "eq",
    E.Ne: "ne",
    E.Lt: "lt",
    E.Le: "le",
    E.Gt: "gt",
    E.Ge: "ge",
    E.And: "and",
    E.Or: "or",
}
_OP_CLASSES = {v: k for k, v in _BINARY_OPS.items()}


def _lit_ok(v: Any) -> bool:
    return v is None or isinstance(v, (bool, int, float, str))


def expr_to_spec(expr: E.Expr) -> Optional[Dict]:
    """Expression tree -> JSON-able dict; None outside the subset."""
    if isinstance(expr, E.Col):
        return {"op": "col", "name": expr.name}
    if isinstance(expr, E.Lit):
        return {"op": "lit", "value": expr.value} if _lit_ok(expr.value) else None
    if type(expr) in _BINARY_OPS:
        left = expr_to_spec(expr.left)
        right = expr_to_spec(expr.right)
        if left is None or right is None:
            return None
        return {"op": _BINARY_OPS[type(expr)], "left": left, "right": right}
    if isinstance(expr, E.Not):
        child = expr_to_spec(expr.child)
        return None if child is None else {"op": "not", "child": child}
    if isinstance(expr, E.In):
        child = expr_to_spec(expr.child)
        if child is None or not all(_lit_ok(v) for v in expr.values):
            return None
        return {"op": "in", "child": child, "values": list(expr.values)}
    if isinstance(expr, E.IsNull):
        child = expr_to_spec(expr.child)
        return None if child is None else {"op": "isnull", "child": child}
    return None


def expr_from_spec(spec: Dict) -> E.Expr:
    op = spec.get("op")
    if op == "col":
        return E.Col(spec["name"])
    if op == "lit":
        return E.Lit(spec["value"])
    if op in _OP_CLASSES:
        return _OP_CLASSES[op](
            expr_from_spec(spec["left"]), expr_from_spec(spec["right"])
        )
    if op == "not":
        return E.Not(expr_from_spec(spec["child"]))
    if op == "in":
        return E.In(expr_from_spec(spec["child"]), tuple(spec["values"]))
    if op == "isnull":
        return E.IsNull(expr_from_spec(spec["child"]))
    raise HyperspaceException(f"Unknown expression spec op {op!r}")


def to_spec(plan: LogicalPlan) -> Optional[Dict]:
    """Plan -> JSON-able spec dict, or None when the plan (or any
    literal in it) falls outside the replayable subset. Never raises —
    this runs on the serve path's querylog append."""
    try:
        node = _node_to_spec(plan)
    except Exception:  # hslint: disable=HS402
        # a recording helper must never fail the query it describes
        return None
    if node is None:
        return None
    node["spec_v"] = SPEC_V
    return node


def _node_to_spec(plan: LogicalPlan) -> Optional[Dict]:
    if isinstance(plan, Scan):
        rel = plan.relation
        if rel.fmt not in _FORMATS or not rel.root_paths:
            return None
        return {"op": "scan", "fmt": rel.fmt, "paths": list(rel.root_paths)}
    if isinstance(plan, Filter):
        child = _node_to_spec(plan.child)
        cond = expr_to_spec(plan.condition)
        if child is None or cond is None:
            return None
        return {"op": "filter", "cond": cond, "child": child}
    if isinstance(plan, Project):
        child = _node_to_spec(plan.child)
        if child is None:
            return None
        return {"op": "project", "cols": list(plan.columns), "child": child}
    if isinstance(plan, Join):
        left, right = _node_to_spec(plan.left), _node_to_spec(plan.right)
        cond = expr_to_spec(plan.condition)
        if left is None or right is None or cond is None:
            return None
        return {
            "op": "join",
            "how": plan.how,
            "cond": cond,
            "left": left,
            "right": right,
        }
    if isinstance(plan, Aggregate):
        child = _node_to_spec(plan.child)
        if child is None:
            return None
        return {
            "op": "aggregate",
            "group_by": list(plan.group_by),
            "aggs": [
                {"func": s.func, "column": s.column, "name": s.name}
                for s in plan.aggs
            ],
            "child": child,
        }
    if isinstance(plan, Sort):
        child = _node_to_spec(plan.child)
        if child is None:
            return None
        return {
            "op": "sort",
            "keys": [[name, bool(asc)] for name, asc in plan.keys],
            "child": child,
        }
    if isinstance(plan, Limit):
        child = _node_to_spec(plan.child)
        if child is None:
            return None
        return {"op": "limit", "n": int(plan.n), "child": child}
    return None


def from_spec(session, spec: Dict) -> LogicalPlan:
    """Spec dict -> live LogicalPlan against ``session`` (scans re-read
    the source paths at the CURRENT snapshot). Raises
    HyperspaceException for unknown spec versions or ops."""
    v = spec.get("spec_v", SPEC_V)
    if not isinstance(v, int) or v > SPEC_V:
        raise HyperspaceException(f"Unknown plan-spec version {v!r}")
    return _node_from_spec(session, spec)


def _node_from_spec(session, spec: Dict) -> LogicalPlan:
    op = spec.get("op")
    if op == "scan":
        fmt = spec.get("fmt", "parquet")
        if fmt not in _FORMATS:
            raise HyperspaceException(f"Unknown scan format {fmt!r}")
        reader = getattr(session.read, fmt)
        return reader(*spec["paths"]).logical_plan
    if op == "filter":
        return Filter(
            expr_from_spec(spec["cond"]),
            _node_from_spec(session, spec["child"]),
        )
    if op == "project":
        return Project(
            list(spec["cols"]), _node_from_spec(session, spec["child"])
        )
    if op == "join":
        return Join(
            _node_from_spec(session, spec["left"]),
            _node_from_spec(session, spec["right"]),
            expr_from_spec(spec["cond"]),
            spec.get("how", "inner"),
        )
    if op == "aggregate":
        return Aggregate(
            list(spec["group_by"]),
            [
                AggSpec(a["func"], a.get("column"), a["name"])
                for a in spec["aggs"]
            ],
            _node_from_spec(session, spec["child"]),
        )
    if op == "sort":
        return Sort(
            [(name, bool(asc)) for name, asc in spec["keys"]],
            _node_from_spec(session, spec["child"]),
        )
    if op == "limit":
        return Limit(int(spec["n"]), _node_from_spec(session, spec["child"]))
    raise HyperspaceException(f"Unknown plan spec op {op!r}")


def spec_scan_paths(spec: Dict) -> List[List[str]]:
    """Every scan's root paths in the spec, left-to-right — the
    advisor's source-identification helper."""
    out: List[List[str]] = []

    def walk(node: Dict) -> None:
        if not isinstance(node, dict):
            return
        if node.get("op") == "scan":
            out.append(list(node.get("paths", [])))
        for key in ("child", "left", "right"):
            sub = node.get(key)
            if sub is not None:
                walk(sub)

    walk(spec)
    return out
