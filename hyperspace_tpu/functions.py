"""Aggregate function builders for ``DataFrame.agg`` / ``GroupedData.agg``.

The Spark-shaped surface (``F.sum("x").alias("total")``) over the engine's
:class:`~hyperspace_tpu.plan.nodes.AggSpec`.
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.plan.nodes import AggSpec


def _spec(func: str, column: Optional[str]) -> AggSpec:
    arg = "*" if column is None else column
    return AggSpec(func, column, f"{func}({arg})")


def alias(spec: AggSpec, name: str) -> AggSpec:
    return spec.alias(name)


def sum(column: str) -> AggSpec:  # noqa: A001 - Spark-shaped API
    return _spec("sum", column)


def count(column: Optional[str] = None) -> AggSpec:
    return _spec("count", column)


def min(column: str) -> AggSpec:  # noqa: A001
    return _spec("min", column)


def max(column: str) -> AggSpec:  # noqa: A001
    return _spec("max", column)


def avg(column: str) -> AggSpec:
    return _spec("avg", column)


mean = avg
