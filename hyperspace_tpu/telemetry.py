"""Typed telemetry events + pluggable event logger.

Reference: ``telemetry/HyperspaceEvent.scala:28-166`` (event case classes),
``telemetry/HyperspaceEventLogging.scala:30-68`` (pluggable logger via
``spark.hyperspace.eventLoggerClass``, default no-op).
"""

from __future__ import annotations

import dataclasses
import importlib
import time
from typing import List, Optional

from hyperspace_tpu import constants as C


@dataclasses.dataclass
class AppInfo:
    """Reference: telemetry/HyperspaceEvent.scala AppInfo(sparkUser, appId, appName)."""

    user: str = ""
    app_id: str = ""
    app_name: str = "hyperspace_tpu"


@dataclasses.dataclass
class HyperspaceEvent:
    app_info: AppInfo = dataclasses.field(default_factory=AppInfo)
    message: str = ""
    timestamp_ms: int = dataclasses.field(
        default_factory=lambda: int(time.time() * 1000)
    )


@dataclasses.dataclass
class HyperspaceIndexCRUDEvent(HyperspaceEvent):
    index_name: str = ""


@dataclasses.dataclass
class CreateActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class DeleteActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RestoreActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class VacuumActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class VacuumOutdatedActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RefreshActionEvent(HyperspaceIndexCRUDEvent):
    mode: str = C.REFRESH_MODE_FULL


@dataclasses.dataclass
class RefreshIncrementalActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RefreshQuickActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class OptimizeActionEvent(HyperspaceIndexCRUDEvent):
    mode: str = C.OPTIMIZE_MODE_QUICK


@dataclasses.dataclass
class CancelActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when the planner picks index(es) for a query.

    Reference: covering/JoinIndexRule.scala:678-684.
    """

    index_names: List[str] = dataclasses.field(default_factory=list)
    plan: str = ""


class EventLogger:
    """Pluggable sink. Default = no-op (telemetry/HyperspaceEventLogging.scala:66)."""

    def log_event(self, event: HyperspaceEvent) -> None:  # pragma: no cover
        pass


class EventLogging:
    """Dispatches events to the logger class named in config."""

    def __init__(self, conf):
        self._conf = conf
        self._logger: Optional[EventLogger] = None
        self._logger_cls_name: Optional[str] = None

    def _resolve(self) -> EventLogger:
        name = self._conf.get_str(
            C.EVENT_LOGGER_CLASS, C.EVENT_LOGGER_CLASS_DEFAULT
        )
        if self._logger is None or name != self._logger_cls_name:
            if name:
                mod, _, cls = name.rpartition(".")
                self._logger = getattr(importlib.import_module(mod), cls)()
            else:
                self._logger = EventLogger()
            self._logger_cls_name = name
        return self._logger

    def log_event(self, event: HyperspaceEvent) -> None:
        self._resolve().log_event(event)
