"""Typed telemetry events + pluggable event logger.

Reference: ``telemetry/HyperspaceEvent.scala:28-166`` (event case classes),
``telemetry/HyperspaceEventLogging.scala:30-68`` (pluggable logger via
``spark.hyperspace.eventLoggerClass``, default no-op).

The obs plane (docs/observability.md) gives this port a real in-tree
sink at last: :class:`JsonlEventLogger` (select it with
``hyperspace.eventLoggerClass =
hyperspace_tpu.telemetry.JsonlEventLogger``; default stays the no-op)
appends one JSON line per event, and EVERY event — whatever the logger —
counts into the metrics registry (``hs_events_total`` by event class)
and carries the active trace id, so lifecycle events join the same
stream queries trace through.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import time
from typing import List, Optional

from hyperspace_tpu import constants as C


@dataclasses.dataclass
class AppInfo:
    """Reference: telemetry/HyperspaceEvent.scala AppInfo(sparkUser, appId, appName)."""

    user: str = ""
    app_id: str = ""
    app_name: str = "hyperspace_tpu"


@dataclasses.dataclass
class HyperspaceEvent:
    app_info: AppInfo = dataclasses.field(default_factory=AppInfo)
    message: str = ""
    # 0 = "not yet emitted": EventLogging.log_event stamps the EMIT
    # time. A dataclass default_factory stamped CONSTRUCTION time, so a
    # batch of events built up front all shared one timestamp — the
    # log's timeline lied about when things actually happened.
    timestamp_ms: int = 0


@dataclasses.dataclass
class HyperspaceIndexCRUDEvent(HyperspaceEvent):
    index_name: str = ""


@dataclasses.dataclass
class CreateActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class DeleteActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RestoreActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class VacuumActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class VacuumOutdatedActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RefreshActionEvent(HyperspaceIndexCRUDEvent):
    mode: str = C.REFRESH_MODE_FULL


@dataclasses.dataclass
class RefreshIncrementalActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class RefreshQuickActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class OptimizeActionEvent(HyperspaceIndexCRUDEvent):
    mode: str = C.OPTIMIZE_MODE_QUICK


@dataclasses.dataclass
class CancelActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclasses.dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when the planner picks index(es) for a query.

    Reference: covering/JoinIndexRule.scala:678-684.
    """

    index_names: List[str] = dataclasses.field(default_factory=list)
    plan: str = ""


class EventLogger:
    """Pluggable sink. Default = no-op (telemetry/HyperspaceEventLogging.scala:66)."""

    def log_event(self, event: HyperspaceEvent) -> None:  # pragma: no cover
        pass


class JsonlEventLogger(EventLogger):
    """The real in-tree sink (default-OFF — select it via
    ``hyperspace.eventLoggerClass``): one JSON line per event, appended
    to ``hyperspace.obs.eventlog.path`` or, when that is empty, to
    ``<hyperspace.system.path>/_hyperspace_obs/events.<pid>.jsonl``
    (per-process file — fleet-safe like the query log; readers union).
    Write failures are swallowed after the first warning: an event log
    must never fail the action it describes."""

    def __init__(self, conf=None):
        self._conf = conf
        self._sink = None
        self._dead = False

    def _resolve_sink(self):
        from hyperspace_tpu.obs import metrics as obs_metrics
        from hyperspace_tpu.obs import querylog as obs_querylog

        path = ""
        if self._conf is not None:
            path = self._conf.get_str(
                C.OBS_EVENTLOG_PATH, C.OBS_EVENTLOG_PATH_DEFAULT
            )
            if not path:
                path = os.path.join(
                    obs_querylog.obs_root(self._conf),
                    f"events.{os.getpid()}.jsonl",
                )
        else:
            path = os.path.join(
                C.INDEX_SYSTEM_PATH_DEFAULT,
                C.HYPERSPACE_OBS_DIR,
                f"events.{os.getpid()}.jsonl",
            )
        return obs_metrics.JsonlSink(path)

    def log_event(self, event: HyperspaceEvent) -> None:
        if self._dead:
            return
        try:
            if self._sink is None:
                self._sink = self._resolve_sink()
            record = dataclasses.asdict(event)
            record["event"] = type(event).__name__
            self._sink.emit(record)
        except OSError:
            # an unwritable sidecar downgrades to the no-op logger for
            # the rest of the process — same never-fail-the-caller
            # stance as the query log
            self._dead = True

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


class EventLogging:
    """Dispatches events to the logger class named in config — and,
    since the obs plane, stamps every event's ``timestamp_ms`` at EMIT
    time, attaches the active trace id, and counts it into the metrics
    registry (``hs_events_total`` by event class): action events ride
    the same observability path queries do, whatever sink is
    configured."""

    def __init__(self, conf):
        self._conf = conf
        self._logger: Optional[EventLogger] = None
        self._logger_cls_name: Optional[str] = None

    def _resolve(self) -> EventLogger:
        name = self._conf.get_str(
            C.EVENT_LOGGER_CLASS, C.EVENT_LOGGER_CLASS_DEFAULT
        )
        if self._logger is None or name != self._logger_cls_name:
            if name:
                mod, _, cls = name.rpartition(".")
                logger_cls = getattr(importlib.import_module(mod), cls)
                try:
                    # in-tree loggers take the session conf (the Jsonl
                    # sink resolves its path from it); third-party ones
                    # keep the reference's zero-arg contract
                    self._logger = logger_cls(self._conf)
                except TypeError:
                    self._logger = logger_cls()
            else:
                self._logger = EventLogger()
            self._logger_cls_name = name
        return self._logger

    def log_event(self, event: HyperspaceEvent) -> None:
        from hyperspace_tpu.obs import metrics as obs_metrics
        from hyperspace_tpu.obs import trace as obs_trace

        if not event.timestamp_ms:
            event.timestamp_ms = int(time.time() * 1000)
        obs_metrics.events_total.inc(type(event).__name__)
        trace_id = obs_trace.current_trace_id()
        if trace_id is not None:
            obs_trace.event(
                "telemetry", event=type(event).__name__, message=event.message
            )
        self._resolve().log_event(event)
