"""Source providers (L4): pluggable adapters from scan relations to
indexable metadata.

Reference: ``index/sources/`` — the SPI (``interfaces.scala:43-277``), the
manager that loads builders from config and requires exactly one provider
to answer (``FileBasedSourceProviderManager.scala:38-174``), and the three
built-ins: default file-based (parquet/csv/json dirs), Delta Lake, Iceberg.
"""

from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedSourceProvider,
)
from hyperspace_tpu.sources.manager import SourceProviderManager

__all__ = [
    "FileBasedRelation",
    "FileBasedSourceProvider",
    "SourceProviderManager",
]
