"""Source provider SPI.

Reference: ``index/sources/interfaces.scala:43-277`` (``SourceRelation`` /
``FileBasedRelation`` / ``FileBasedSourceProvider`` / builder). A provider
adapts one kind of lake layout (plain format dirs, Delta log, Iceberg
snapshots) to the operations the actions and rules need: file snapshot,
plan-fingerprint signature, metadata Relation construction, refresh
re-listing, and (for time-travel sources) picking the closest index
version.
"""

from __future__ import annotations

import abc
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import Content, FileIdTracker, FileInfo
from hyperspace_tpu.metadata.entry import Relation as MetaRelation
from hyperspace_tpu.plan.nodes import Relation as PlanRelation


class FileBasedRelation(abc.ABC):
    """Wraps one Scan relation for indexing/metadata purposes."""

    def __init__(self, session, plan_relation: PlanRelation):
        self.session = session
        self.plan_relation = plan_relation

    # -- identity / fingerprints -------------------------------------------
    @abc.abstractmethod
    def signature(self) -> str:
        """Deterministic fingerprint of the data snapshot this relation
        reads (DefaultFileBasedRelation.scala:45-53: md5 fold over
        (len, mtime, path); DeltaLakeRelation.scala:40-44: version+path)."""

    # -- file snapshot ------------------------------------------------------
    @abc.abstractmethod
    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        """(path, size, mtime_ms) of every data file in the snapshot."""

    # -- metadata construction ---------------------------------------------
    @abc.abstractmethod
    def create_metadata_relation(self, tracker: FileIdTracker) -> MetaRelation:
        """Build the metadata Relation (source snapshot incl. tracked file
        ids) stored in the IndexLogEntry
        (DefaultFileBasedRelation.createRelationMetadata:129-191)."""

    # -- lifecycle hooks ----------------------------------------------------
    def refresh(self) -> "FileBasedRelation":
        """Re-list the current state of the source (used by refresh
        actions; DeltaLakeRelationMetadata.refresh drops versionAsOf)."""
        return self

    def enrich_index_properties(
        self, properties: Dict[str, str], log_version: Optional[int] = None
    ) -> Dict[str, str]:
        """Provider-specific properties recorded on the index
        (DeltaLakeRelationMetadata.enrichIndexProperties:45-58).
        ``log_version`` is the log id the enclosing action will commit."""
        return dict(properties)

    def closest_index(self, entry):
        """For time-travel sources: the historical index log entry whose
        recorded source version is closest to this relation's queried
        version (DeltaLakeRelation.closestIndex:179-251). Default: the
        given (latest) entry."""
        return entry


class FileBasedSourceProvider(abc.ABC):
    """Answers whether it supports a given scan relation and builds the
    FileBasedRelation wrapper (FileBasedSourceProvider trait)."""

    name: str = "provider"

    @abc.abstractmethod
    def is_supported(self, session, plan_relation: PlanRelation) -> Optional[bool]:
        """True/False when this provider can decide; None to abstain."""

    @abc.abstractmethod
    def get_relation(self, session, plan_relation: PlanRelation) -> FileBasedRelation:
        ...


def content_from_file_infos(
    infos: List[Tuple[str, int, int]], tracker: Optional[FileIdTracker]
) -> Content:
    """Content tree from (path,size,mtime) triples, assigning tracked file
    ids (CreateActionBase.updateFileIdTracker:85-93)."""
    return Content.from_leaf_files(infos, tracker)
