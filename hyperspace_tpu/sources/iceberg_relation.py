"""Iceberg relation: snapshot-id signatures, snapshot-pinned scans.

Reference: ``sources/iceberg/IcebergRelation.scala`` — signature = snapshot
id + location (`:65-66`), scans pinned to a snapshot (`:222-223`).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import FileIdTracker
from hyperspace_tpu.metadata.entry import Relation as MetaRelation
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources import iceberg_meta
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    content_from_file_infos,
)
from hyperspace_tpu.utils.hashing import md5_hex


class IcebergRelation(FileBasedRelation):
    def __init__(self, session, plan_relation: PlanRelation):
        super().__init__(session, plan_relation)
        self._snapshot: Optional[iceberg_meta.IcebergSnapshot] = None

    @property
    def table_path(self) -> str:
        return self.plan_relation.root_paths[0]

    @property
    def snapshot_as_of(self) -> Optional[int]:
        v = dict(self.plan_relation.options).get("snapshotAsOf")
        return int(v) if v is not None else None

    def snapshot(self) -> iceberg_meta.IcebergSnapshot:
        if self._snapshot is None:
            self._snapshot = iceberg_meta.read_snapshot(
                self.table_path, self.snapshot_as_of
            )
        return self._snapshot

    def signature(self) -> str:
        """Snapshot id + location (IcebergRelation.scala:65-66)."""
        snap = self.snapshot()
        return md5_hex(f"{snap.snapshot_id}{os.path.abspath(self.table_path)}")

    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        snap = self.snapshot()
        return [
            (p, size, mtime) for p, (size, mtime) in sorted(snap.files.items())
        ]

    def create_metadata_relation(self, tracker: FileIdTracker) -> MetaRelation:
        snap = self.snapshot()
        content = content_from_file_infos(self.all_file_infos(), tracker)
        schema_json = json.dumps([[n, str(t)] for n, t in snap.schema_fields])
        return MetaRelation(
            root_paths=[os.path.abspath(self.table_path)],
            content=content,
            schema_json=schema_json,
            file_format="iceberg",
            options={"snapshotId": str(snap.snapshot_id)},
        )

    def refresh(self) -> "IcebergRelation":
        snap = iceberg_meta.read_snapshot(self.table_path, None)
        options = tuple(
            (k, v)
            for k, v in self.plan_relation.options
            if k not in ("snapshotAsOf", "snapshotId")
        ) + (("snapshotId", str(snap.snapshot_id)),)
        rel = dataclasses.replace(
            self.plan_relation,
            files=tuple(snap.file_paths),
            options=options,
            schema_fields=tuple(snap.schema_fields),
        )
        return IcebergRelation(self.session, rel)
