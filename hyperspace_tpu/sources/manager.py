"""Source provider manager.

Reference: ``index/sources/FileBasedSourceProviderManager.scala:38-174`` —
builders are loaded from the config key
``hyperspace.index.sources.fileBasedBuilders`` (cached, invalidated when
the conf value changes, via ``CacheWithTransform``), and every dispatch
requires **exactly one** provider to answer (``runWithDefault:126-146``).
"""

from __future__ import annotations

import importlib
from typing import List, Optional

from hyperspace_tpu.config import CacheWithTransform
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedSourceProvider,
)


def _load_builders(conf) -> List[FileBasedSourceProvider]:
    providers = []
    for qualname in conf.source_provider_builders:
        qualname = qualname.strip()
        if not qualname:
            continue
        mod_name, _, attr = qualname.rpartition(".")
        builder = getattr(importlib.import_module(mod_name), attr)
        providers.append(builder())
    if not providers:
        raise HyperspaceException("No source providers configured")
    return providers


class SourceProviderManager:
    def __init__(self, session):
        self.session = session
        self._providers = CacheWithTransform(session.conf, _load_builders)

    @property
    def providers(self) -> List[FileBasedSourceProvider]:
        return self._providers.load()

    def is_supported(self, plan_relation: PlanRelation) -> bool:
        try:
            self._single(plan_relation)
            return True
        except HyperspaceException:
            return False

    def get_relation(self, plan_relation: PlanRelation) -> FileBasedRelation:
        return self._single(plan_relation).get_relation(self.session, plan_relation)

    def _single(self, plan_relation: PlanRelation) -> FileBasedSourceProvider:
        """Exactly one provider must answer True (manager `:126-146`)."""
        answered = [
            p
            for p in self.providers
            if p.is_supported(self.session, plan_relation) is True
        ]
        if len(answered) != 1:
            raise HyperspaceException(
                f"Expected exactly one source provider for relation "
                f"{plan_relation.root_paths} (format {plan_relation.fmt!r}); "
                f"got {[p.name for p in answered]}"
            )
        return answered[0]
