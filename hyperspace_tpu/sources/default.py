"""Default file-based source provider: plain file-format directories.

Reference: ``sources/default/DefaultFileBasedSource.scala:37-124`` (formats
from conf, default avro,csv,json,orc,parquet,text — same set here),
``DefaultFileBasedRelation.scala:38-242`` (signature = md5 fold over
(len, mtime, path) of all files; globbed roots re-expanded on every
listing), ``DefaultFileBasedRelationMetadata.scala``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import FileIdTracker
from hyperspace_tpu.metadata.entry import Relation as MetaRelation
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedSourceProvider,
    content_from_file_infos,
)
from hyperspace_tpu.utils.hashing import md5_hex


class DefaultFileBasedRelation(FileBasedRelation):
    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        out = []
        for f in self.plan_relation.files:
            st = os.stat(f)
            out.append((f, st.st_size, int(st.st_mtime * 1000)))
        return out

    def signature(self) -> str:
        # md5 fold over (len, mtime, path) of all files, order-independent
        # sum like the reference's fold (DefaultFileBasedRelation.scala:45-53
        # concatenates per-file fingerprints; we sort for determinism).
        parts = [
            md5_hex(f"{size}{mtime}{path}")
            for path, size, mtime in sorted(self.all_file_infos())
        ]
        return md5_hex("".join(parts))

    def create_metadata_relation(self, tracker: FileIdTracker) -> MetaRelation:
        import json

        from hyperspace_tpu.io.columnar import ColumnarBatch  # noqa: F401

        content = content_from_file_infos(self.all_file_infos(), tracker)
        schema_json = json.dumps(
            [[n, str(t)] for n, t in self.plan_relation.schema_fields]
        )
        return MetaRelation(
            root_paths=list(self.plan_relation.root_paths),
            content=content,
            schema_json=schema_json,
            file_format=self.plan_relation.fmt,
            options=dict(self.plan_relation.options),
        )

    def refresh(self) -> "DefaultFileBasedRelation":
        from hyperspace_tpu.io.parquet import expand_path

        files: List[str] = []
        for p in self.plan_relation.root_paths:
            files.extend(expand_path(p, self.plan_relation.fmt))
        import dataclasses

        rel = dataclasses.replace(self.plan_relation, files=tuple(files))
        return DefaultFileBasedRelation(self.session, rel)


class DefaultFileBasedSource(FileBasedSourceProvider):
    name = "default"

    def is_supported(self, session, plan_relation: PlanRelation) -> Optional[bool]:
        fmt = plan_relation.fmt
        if fmt in session.conf.default_supported_formats:
            return True
        return None

    def get_relation(self, session, plan_relation: PlanRelation) -> FileBasedRelation:
        return DefaultFileBasedRelation(session, plan_relation)


def DefaultFileBasedSourceBuilder():  # noqa: N802  (builder entry in conf list)
    return DefaultFileBasedSource()
