"""Delta Lake source provider (full implementation arrives with the Delta
log reader; see package docstring).

Reference: ``sources/delta/DeltaLakeFileBasedSource.scala``,
``DeltaLakeRelation.scala:34-252`` (signature = table version + path,
closest-index time travel), ``DeltaLakeRelationMetadata.scala:25-71``.
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources.interfaces import FileBasedSourceProvider


class DeltaLakeSource(FileBasedSourceProvider):
    name = "delta"

    def is_supported(self, session, plan_relation: PlanRelation) -> Optional[bool]:
        if plan_relation.fmt == "delta":
            return True
        return None

    def get_relation(self, session, plan_relation: PlanRelation):
        from hyperspace_tpu.sources.delta_relation import DeltaLakeRelation

        return DeltaLakeRelation(session, plan_relation)


def DeltaLakeSourceBuilder():  # noqa: N802
    return DeltaLakeSource()
