"""Delta Lake relation: snapshot-pinned scans, version signatures, index
version history + closest-index time travel.

Reference: ``sources/delta/DeltaLakeRelation.scala:34-252`` (signature =
table version + path `:40-44`; files from the Delta log `:49-56`;
``versionAsOf`` recorded in options `:96-99`; ``closestIndex`` picks the
index log version whose recorded Delta version is closest to the queried
one via the DELTA_VERSION_HISTORY property `:179-251`) and
``DeltaLakeRelationMetadata.scala:25-71`` (refresh drops versionAsOf;
enrichIndexProperties appends ``indexLogVersion:deltaVersion`` history).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.constants import DELTA_VERSION_HISTORY_PROPERTY
from hyperspace_tpu.metadata.entry import FileIdTracker
from hyperspace_tpu.metadata.entry import Relation as MetaRelation
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources import delta_log
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    content_from_file_infos,
)
from hyperspace_tpu.utils.hashing import md5_hex


class DeltaLakeRelation(FileBasedRelation):
    def __init__(self, session, plan_relation: PlanRelation):
        super().__init__(session, plan_relation)
        self._snapshot: Optional[delta_log.DeltaSnapshot] = None

    # -- snapshot -----------------------------------------------------------
    @property
    def table_path(self) -> str:
        return self.plan_relation.root_paths[0]

    @property
    def version_as_of(self) -> Optional[int]:
        v = dict(self.plan_relation.options).get("versionAsOf")
        return int(v) if v is not None else None

    def snapshot(self) -> delta_log.DeltaSnapshot:
        if self._snapshot is None:
            self._snapshot = delta_log.read_snapshot(
                self.table_path, self.version_as_of
            )
        return self._snapshot

    # -- SPI ---------------------------------------------------------------
    def signature(self) -> str:
        """Table version + path (DeltaLakeRelation.scala:40-44)."""
        snap = self.snapshot()
        return md5_hex(f"{snap.version}{os.path.abspath(self.table_path)}")

    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        snap = self.snapshot()
        return [
            (p, size, mtime) for p, (size, mtime) in sorted(snap.files.items())
        ]

    def create_metadata_relation(self, tracker: FileIdTracker) -> MetaRelation:
        snap = self.snapshot()
        content = content_from_file_infos(self.all_file_infos(), tracker)
        schema_json = json.dumps([[n, str(t)] for n, t in snap.schema_fields])
        options = {"deltaVersion": str(snap.version)}
        if self.version_as_of is not None:
            options["versionAsOf"] = str(self.version_as_of)
        return MetaRelation(
            root_paths=[os.path.abspath(self.table_path)],
            content=content,
            schema_json=schema_json,
            file_format="delta",
            options=options,
        )

    def refresh(self) -> "DeltaLakeRelation":
        """Latest snapshot, versionAsOf dropped
        (DeltaLakeRelationMetadata.refresh)."""
        snap = delta_log.read_snapshot(self.table_path, None)
        options = tuple(
            (k, v)
            for k, v in self.plan_relation.options
            if k not in ("versionAsOf", "deltaVersion")
        ) + (("deltaVersion", str(snap.version)),)
        rel = dataclasses.replace(
            self.plan_relation,
            files=tuple(snap.file_paths),
            options=options,
            schema_fields=tuple(snap.schema_fields),
        )
        return DeltaLakeRelation(self.session, rel)

    def enrich_index_properties(
        self, properties: Dict[str, str], log_version: Optional[int] = None
    ) -> Dict[str, str]:
        """Append ``indexLogVersion:deltaVersion`` to the history
        (DeltaLakeRelationMetadata.enrichIndexProperties:45-58)."""
        props = dict(properties)
        snap = self.snapshot()
        prev = props.get(DELTA_VERSION_HISTORY_PROPERTY, "")
        pair = f"{log_version if log_version is not None else ''}:{snap.version}"
        if prev.split(",")[-1] == pair:  # idempotent: entry built twice per action
            return props
        props[DELTA_VERSION_HISTORY_PROPERTY] = f"{prev},{pair}" if prev else pair
        return props

    def closest_index(self, entry):
        """For a versionAsOf query, the historical index log entry whose
        recorded Delta version is closest (DeltaLakeRelation.closestIndex
        :179-251); the current entry otherwise."""
        queried = self.version_as_of
        if queried is None:
            return entry
        history = entry.derived_dataset.properties.get(
            DELTA_VERSION_HISTORY_PROPERTY, ""
        )
        pairs: List[Tuple[int, int]] = []
        for piece in history.split(","):
            if ":" not in piece:
                continue
            log_v, delta_v = piece.split(":", 1)
            if log_v.strip().isdigit() and delta_v.strip().isdigit():
                pairs.append((int(log_v), int(delta_v)))
        if not pairs:
            return entry
        best_log, _best_delta = min(
            pairs, key=lambda lv_dv: (abs(lv_dv[1] - queried), -lv_dv[0])
        )
        if best_log == entry.id:
            return entry
        from hyperspace_tpu import factories
        from hyperspace_tpu.metadata.path_resolver import PathResolver

        path = PathResolver(self.session.conf).get_index_path(entry.name)
        hist = factories.create_log_manager(path).get_log(best_log)
        return hist if hist is not None else entry
