"""Delta Lake transaction log reader (no Spark, no delta-rs).

Reads the ``_delta_log/`` protocol directly: numbered JSON commits with
``add``/``remove``/``metaData`` actions, plus parquet checkpoints (classic
single-part and multi-part) discovered by directory listing. Snapshot
reconstruction = latest readable checkpoint ≤ target version, then replay
JSON commits. v2 (uuid-named) checkpoints are detected and rejected with a
clear error when required. This replaces the reference's
dependency on the Delta Lake Spark library
(``sources/delta/DeltaLakeShims``); the log format itself is an open spec.
"""

from __future__ import annotations

import dataclasses
import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException

DELTA_LOG_DIR = "_delta_log"

_SPARK_TO_ARROW = {
    "string": pa.string(),
    "long": pa.int64(),
    "integer": pa.int32(),
    "short": pa.int16(),
    "byte": pa.int8(),
    "float": pa.float32(),
    "double": pa.float64(),
    "boolean": pa.bool_(),
    "binary": pa.binary(),
    "date": pa.date32(),
    "timestamp": pa.timestamp("us"),
}


def spark_type_to_arrow(t) -> pa.DataType:
    if isinstance(t, str):
        if t in _SPARK_TO_ARROW:
            return _SPARK_TO_ARROW[t]
        if t.startswith("decimal(") and t.endswith(")"):
            p, s = t[len("decimal(") : -1].split(",")
            return pa.decimal128(int(p), int(s))
    raise HyperspaceException(f"Unsupported Delta type: {t!r}")


def parse_schema_string(schema_string: str) -> List[Tuple[str, pa.DataType]]:
    """Spark StructType JSON -> [(name, arrow type)]."""
    doc = json.loads(schema_string)
    return [
        (f["name"], spark_type_to_arrow(f["type"])) for f in doc.get("fields", [])
    ]


@dataclasses.dataclass
class DeltaSnapshot:
    table_path: str
    version: int
    # path -> (size, modification_time_ms)
    files: Dict[str, Tuple[int, int]]
    schema_fields: List[Tuple[str, pa.DataType]]
    partition_columns: List[str]

    @property
    def file_paths(self) -> List[str]:
        return sorted(self.files)


def _log_dir(table_path: str) -> str:
    return os.path.join(table_path, DELTA_LOG_DIR)


def is_delta_table(path: str) -> bool:
    return os.path.isdir(_log_dir(path))


def _commit_versions(log_dir: str) -> List[int]:
    out = []
    for name in os.listdir(log_dir):
        stem, ext = os.path.splitext(name)
        if ext == ".json" and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def _checkpoint_groups(log_dir: str) -> Tuple[Dict[int, List[str]], List[int]]:
    """Discover checkpoints: ``{version: [file names]}`` for readable ones
    (classic single-part ``NNN.checkpoint.parquet`` and complete multi-part
    ``NNN.checkpoint.MMM.PPP.parquet`` groups), plus versions that exist only
    as v2/uuid-named checkpoints we cannot read."""
    singles: Dict[int, List[str]] = {}
    multi: Dict[int, Dict[int, Dict[int, str]]] = {}
    v2_only: List[int] = []
    for name in os.listdir(log_dir):
        parts = name.split(".")
        if len(parts) < 3 or parts[1] != "checkpoint" or not parts[0].isdigit():
            continue
        version = int(parts[0])
        if len(parts) == 3 and parts[2] == "parquet":
            singles[version] = [name]
        elif (
            len(parts) == 5
            and parts[4] == "parquet"
            and parts[2].isdigit()
            and parts[3].isdigit()
        ):
            part, num_parts = int(parts[2]), int(parts[3])
            multi.setdefault(version, {}).setdefault(num_parts, {})[part] = name
        elif parts[-1] in ("parquet", "json"):
            # v2 checkpoint (uuid-named) — recognizable but unreadable here
            v2_only.append(version)
    groups = dict(singles)
    for version, by_n in multi.items():
        if version in groups:
            continue
        for num_parts, names in sorted(by_n.items()):
            if all(i in names for i in range(1, num_parts + 1)):
                groups[version] = [names[i] for i in range(1, num_parts + 1)]
                break
    v2_only = sorted(v for v in set(v2_only) if v not in groups)
    return groups, v2_only


def latest_version(table_path: str) -> int:
    log_dir = _log_dir(table_path)
    groups, v2_only = _checkpoint_groups(log_dir)
    # v2-only checkpoint versions count as existing state (read_snapshot will
    # then fail with the clear v2-unsupported error rather than "empty log").
    versions = _commit_versions(log_dir) + sorted(groups) + v2_only
    if not versions:
        raise HyperspaceException(f"Not a Delta table (empty log): {table_path}")
    return max(versions)


def _abs_data_path(table_path: str, rel: str) -> str:
    rel = urllib.parse.unquote(rel)
    if rel.startswith("file:"):
        # Hadoop renders local URIs as file:/x, file:///x, or file://host/x
        import re as _re

        return _re.sub(r"^file:/+", "/", rel)
    if rel.startswith("/") or "://" in rel:
        return rel
    return os.path.join(table_path, rel)


def _apply_action(state: dict, action: dict, table_path: str) -> None:
    if "add" in action and action["add"]:
        a = action["add"]
        p = _abs_data_path(table_path, a["path"])
        state["files"][p] = (
            int(a.get("size", 0)),
            int(a.get("modificationTime", 0)),
        )
    elif "remove" in action and action["remove"]:
        p = _abs_data_path(table_path, action["remove"]["path"])
        state["files"].pop(p, None)
    elif "metaData" in action and action["metaData"]:
        md = action["metaData"]
        if md.get("schemaString"):
            state["schema"] = parse_schema_string(md["schemaString"])
        state["partition_columns"] = list(md.get("partitionColumns", []))


def _read_checkpoint(
    state: dict, log_dir: str, names: List[str], table_path: str
):
    import pyarrow.parquet as pq

    for name in names:
        table = pq.read_table(os.path.join(log_dir, name))
        # The v2 checkpoint spec allows v2 content under classic naming:
        # data files then live in sidecar files which plain replay would
        # silently drop — detect and refuse rather than truncate the state.
        v2_cols = {"checkpointMetadata", "sidecar"} & set(table.column_names)
        for col in v2_cols:
            if table.column(col).null_count < table.num_rows:
                raise HyperspaceException(
                    f"Delta checkpoint {name} of {table_path} carries v2 "
                    f"checkpoint actions ({col}); v2 checkpoints are not "
                    "supported"
                )
        for row in table.to_pylist():
            _apply_action(
                state, {k: v for k, v in row.items() if v is not None}, table_path
            )


def read_snapshot(table_path: str, version: Optional[int] = None) -> DeltaSnapshot:
    log_dir = _log_dir(table_path)
    if not os.path.isdir(log_dir):
        raise HyperspaceException(f"Not a Delta table: {table_path}")
    target = latest_version(table_path) if version is None else int(version)
    commits = [v for v in _commit_versions(log_dir) if v <= target]
    groups, v2_only = _checkpoint_groups(log_dir)
    ckpts = [v for v in groups if v <= target]
    state = {"files": {}, "schema": None, "partition_columns": []}
    start = 0
    if ckpts:
        # Any complete checkpoint <= target is state-equivalent; the newest
        # one minimizes replay and tolerates stale `_last_checkpoint` hints.
        ckpt = max(ckpts)
        _read_checkpoint(state, log_dir, groups[ckpt], table_path)
        start = ckpt + 1
    replay = [v for v in commits if v >= start]
    expected = list(range(start, target + 1))
    if replay != expected and not (ckpts and max(ckpts) == target and not replay):
        missing = sorted(set(expected) - set(replay))
        if missing:
            newer_v2 = [v for v in v2_only if start <= v <= target]
            # only blame the v2 checkpoint when reading it would actually
            # cover the gap; otherwise the log is genuinely incomplete
            if newer_v2 and max(missing) <= max(newer_v2):
                raise HyperspaceException(
                    f"Delta log of {table_path} requires v2 (uuid-named) "
                    f"checkpoint at version {max(newer_v2)}, which is not "
                    "supported"
                )
            raise HyperspaceException(
                f"Delta log is missing commits {missing} for version {target} "
                f"of {table_path}"
            )
    for v in replay:
        with open(os.path.join(log_dir, f"{v:020d}.json")) as f:
            for line in f:
                line = line.strip()
                if line:
                    _apply_action(state, json.loads(line), table_path)
    if state["schema"] is None:
        raise HyperspaceException(
            f"Delta log has no metaData action up to version {target}"
        )
    return DeltaSnapshot(
        table_path=os.path.abspath(table_path),
        version=target,
        files=state["files"],
        schema_fields=state["schema"],
        partition_columns=state["partition_columns"],
    )
