"""Delta Lake transaction log reader (no Spark, no delta-rs).

Reads the ``_delta_log/`` protocol directly: numbered JSON commits with
``add``/``remove``/``metaData`` actions, plus parquet checkpoints pointed
at by ``_last_checkpoint``. Snapshot reconstruction = latest checkpoint ≤
target version, then replay JSON commits. This replaces the reference's
dependency on the Delta Lake Spark library
(``sources/delta/DeltaLakeShims``); the log format itself is an open spec.
"""

from __future__ import annotations

import dataclasses
import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException

DELTA_LOG_DIR = "_delta_log"

_SPARK_TO_ARROW = {
    "string": pa.string(),
    "long": pa.int64(),
    "integer": pa.int32(),
    "short": pa.int16(),
    "byte": pa.int8(),
    "float": pa.float32(),
    "double": pa.float64(),
    "boolean": pa.bool_(),
    "binary": pa.binary(),
    "date": pa.date32(),
    "timestamp": pa.timestamp("us"),
}


def spark_type_to_arrow(t) -> pa.DataType:
    if isinstance(t, str):
        if t in _SPARK_TO_ARROW:
            return _SPARK_TO_ARROW[t]
        if t.startswith("decimal(") and t.endswith(")"):
            p, s = t[len("decimal(") : -1].split(",")
            return pa.decimal128(int(p), int(s))
    raise HyperspaceException(f"Unsupported Delta type: {t!r}")


def parse_schema_string(schema_string: str) -> List[Tuple[str, pa.DataType]]:
    """Spark StructType JSON -> [(name, arrow type)]."""
    doc = json.loads(schema_string)
    return [
        (f["name"], spark_type_to_arrow(f["type"])) for f in doc.get("fields", [])
    ]


@dataclasses.dataclass
class DeltaSnapshot:
    table_path: str
    version: int
    # path -> (size, modification_time_ms)
    files: Dict[str, Tuple[int, int]]
    schema_fields: List[Tuple[str, pa.DataType]]
    partition_columns: List[str]

    @property
    def file_paths(self) -> List[str]:
        return sorted(self.files)


def _log_dir(table_path: str) -> str:
    return os.path.join(table_path, DELTA_LOG_DIR)


def is_delta_table(path: str) -> bool:
    return os.path.isdir(_log_dir(path))


def _commit_versions(log_dir: str) -> List[int]:
    out = []
    for name in os.listdir(log_dir):
        stem, ext = os.path.splitext(name)
        if ext == ".json" and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def _checkpoint_versions(log_dir: str) -> List[int]:
    out = []
    for name in os.listdir(log_dir):
        if name.endswith(".checkpoint.parquet"):
            stem = name.split(".", 1)[0]
            if stem.isdigit():
                out.append(int(stem))
    return sorted(out)


def latest_version(table_path: str) -> int:
    log_dir = _log_dir(table_path)
    versions = _commit_versions(log_dir) + _checkpoint_versions(log_dir)
    if not versions:
        raise HyperspaceException(f"Not a Delta table (empty log): {table_path}")
    return max(versions)


def _abs_data_path(table_path: str, rel: str) -> str:
    rel = urllib.parse.unquote(rel)
    if rel.startswith("file:"):
        # Hadoop renders local URIs as file:/x, file:///x, or file://host/x
        import re as _re

        return _re.sub(r"^file:/+", "/", rel)
    if rel.startswith("/") or "://" in rel:
        return rel
    return os.path.join(table_path, rel)


def _apply_action(state: dict, action: dict, table_path: str) -> None:
    if "add" in action and action["add"]:
        a = action["add"]
        p = _abs_data_path(table_path, a["path"])
        state["files"][p] = (
            int(a.get("size", 0)),
            int(a.get("modificationTime", 0)),
        )
    elif "remove" in action and action["remove"]:
        p = _abs_data_path(table_path, action["remove"]["path"])
        state["files"].pop(p, None)
    elif "metaData" in action and action["metaData"]:
        md = action["metaData"]
        if md.get("schemaString"):
            state["schema"] = parse_schema_string(md["schemaString"])
        state["partition_columns"] = list(md.get("partitionColumns", []))


def _read_checkpoint(state: dict, log_dir: str, version: int, table_path: str):
    import pyarrow.parquet as pq

    path = os.path.join(log_dir, f"{version:020d}.checkpoint.parquet")
    table = pq.read_table(path)
    for row in table.to_pylist():
        _apply_action(state, {k: v for k, v in row.items() if v is not None},
                      table_path)


def read_snapshot(table_path: str, version: Optional[int] = None) -> DeltaSnapshot:
    log_dir = _log_dir(table_path)
    if not os.path.isdir(log_dir):
        raise HyperspaceException(f"Not a Delta table: {table_path}")
    target = latest_version(table_path) if version is None else int(version)
    commits = [v for v in _commit_versions(log_dir) if v <= target]
    ckpts = [v for v in _checkpoint_versions(log_dir) if v <= target]
    state = {"files": {}, "schema": None, "partition_columns": []}
    start = 0
    if ckpts:
        ckpt = max(ckpts)
        _read_checkpoint(state, log_dir, ckpt, table_path)
        start = ckpt + 1
    replay = [v for v in commits if v >= start]
    expected = list(range(start, target + 1))
    if replay != expected and not (ckpts and max(ckpts) == target and not replay):
        missing = sorted(set(expected) - set(replay))
        if missing:
            raise HyperspaceException(
                f"Delta log is missing commits {missing} for version {target} "
                f"of {table_path}"
            )
    for v in replay:
        with open(os.path.join(log_dir, f"{v:020d}.json")) as f:
            for line in f:
                line = line.strip()
                if line:
                    _apply_action(state, json.loads(line), table_path)
    if state["schema"] is None:
        raise HyperspaceException(
            f"Delta log has no metaData action up to version {target}"
        )
    return DeltaSnapshot(
        table_path=os.path.abspath(table_path),
        version=target,
        files=state["files"],
        schema_fields=state["schema"],
        partition_columns=state["partition_columns"],
    )
