"""Iceberg table metadata reader (no Iceberg library).

Reads ``metadata/v*.metadata.json`` (+ ``version-hint.text``) for the
snapshot catalog and schema, then follows the manifest list → manifest
Avro files (``utils/avro.py``) to the data-file set of a snapshot. This
replaces the reference's dependency on the Iceberg Spark runtime
(``sources/iceberg/IcebergShims``); the table format is an open spec.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.utils.avro import read_avro

_ICEBERG_TO_ARROW = {
    "boolean": pa.bool_(),
    "int": pa.int32(),
    "long": pa.int64(),
    "float": pa.float32(),
    "double": pa.float64(),
    "date": pa.date32(),
    "time": pa.time64("us"),
    "timestamp": pa.timestamp("us"),
    "timestamptz": pa.timestamp("us", "UTC"),
    "string": pa.string(),
    "uuid": pa.binary(16),
    "binary": pa.binary(),
}


def iceberg_type_to_arrow(t) -> pa.DataType:
    if isinstance(t, str):
        if t in _ICEBERG_TO_ARROW:
            return _ICEBERG_TO_ARROW[t]
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
        if m:
            return pa.decimal128(int(m.group(1)), int(m.group(2)))
        m = re.match(r"fixed\[(\d+)\]", t)
        if m:
            return pa.binary(int(m.group(1)))
    raise HyperspaceException(f"Unsupported Iceberg type: {t!r}")


@dataclasses.dataclass
class IcebergSnapshot:
    table_path: str
    snapshot_id: int
    # path -> (size, mtime_ms); mtime is always 0 — Iceberg data files are
    # immutable by contract, so (path, size) identifies content and a
    # stable mtime keeps file-diffing (refresh/Hybrid Scan) correct across
    # snapshots
    files: Dict[str, Tuple[int, int]]
    schema_fields: List[Tuple[str, pa.DataType]]
    location: str

    @property
    def file_paths(self) -> List[str]:
        return sorted(self.files)


def is_iceberg_table(path: str) -> bool:
    return os.path.isdir(os.path.join(path, "metadata"))


def _latest_metadata_file(table_path: str) -> str:
    meta_dir = os.path.join(table_path, "metadata")
    hint = os.path.join(meta_dir, "version-hint.text")
    if os.path.isfile(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(meta_dir, f"v{v}.metadata.json")
        if os.path.isfile(cand):
            return cand
    best, best_v = None, -1
    for name in os.listdir(meta_dir):
        m = re.match(r"v(\d+)\.metadata\.json$", name)
        if m and int(m.group(1)) > best_v:
            best, best_v = os.path.join(meta_dir, name), int(m.group(1))
    if best is None:
        raise HyperspaceException(f"Not an Iceberg table: {table_path}")
    return best


def _resolve_path(table_path: str, location: str, p: str) -> str:
    if p.startswith("file:"):
        # Hadoop renders local URIs as file:/x, file:///x, or file://host/x
        p = re.sub(r"^file:/+", "/", p)
    if location.startswith("file:"):
        location = re.sub(r"^file:/+", "/", location)
    if os.path.isabs(p) and os.path.exists(p):
        return p
    if location and p.startswith(location):
        rel = p[len(location) :].lstrip("/")
        return os.path.join(table_path, rel)
    return os.path.join(table_path, p.lstrip("/"))


def _schema_fields(doc: dict) -> List[Tuple[str, pa.DataType]]:
    schema = None
    if "schemas" in doc and doc.get("current-schema-id") is not None:
        for s in doc["schemas"]:
            if s.get("schema-id") == doc["current-schema-id"]:
                schema = s
                break
    if schema is None:
        schema = doc.get("schema")
    if schema is None:
        raise HyperspaceException("Iceberg metadata has no schema")
    return [
        (f["name"], iceberg_type_to_arrow(f["type"]))
        for f in schema.get("fields", [])
    ]


def read_snapshot(
    table_path: str, snapshot_id: Optional[int] = None
) -> IcebergSnapshot:
    meta_file = _latest_metadata_file(table_path)
    with open(meta_file) as f:
        doc = json.load(f)
    location = doc.get("location", "")
    snapshots = doc.get("snapshots", [])
    if not snapshots:
        raise HyperspaceException(f"Iceberg table has no snapshots: {table_path}")
    if snapshot_id is None:
        snapshot_id = doc.get("current-snapshot-id")
        if snapshot_id in (None, -1):
            snapshot_id = snapshots[-1]["snapshot-id"]
    snap = next(
        (s for s in snapshots if s["snapshot-id"] == snapshot_id), None
    )
    if snap is None:
        raise HyperspaceException(
            f"Snapshot {snapshot_id} not found in {table_path}"
        )
    files: Dict[str, Tuple[int, int]] = {}
    manifests: List[str] = []
    if "manifest-list" in snap:  # format v2 (and v1 with manifest lists)
        mlist_path = _resolve_path(table_path, location, snap["manifest-list"])
        for entry in read_avro(mlist_path):
            # v2 manifest-list entries carry `content`: 0 = data manifest,
            # 1 = delete manifest (position/equality deletes, merge-on-read).
            # Row-level delete application is not implemented, so a snapshot
            # with LIVE delete files cannot be scanned correctly — refuse it
            # rather than silently reading delete files as data parquet.
            # (A delete manifest whose entries are all status=2/removed —
            # e.g. after compaction applied the deletes — is harmless.)
            if int(entry.get("content") or 0) != 0:
                dpath = _resolve_path(table_path, location, entry["manifest_path"])
                live = [
                    d for d in read_avro(dpath) if d.get("status", 1) != 2
                ]
                if live:
                    raise HyperspaceException(
                        f"Iceberg snapshot {snapshot_id} of {table_path} "
                        "contains live delete files (merge-on-read); "
                        "row-level deletes are not supported"
                    )
                continue
            manifests.append(
                _resolve_path(table_path, location, entry["manifest_path"])
            )
    else:  # format v1 inline manifests
        manifests = [
            _resolve_path(table_path, location, p) for p in snap.get("manifests", [])
        ]
    for mpath in manifests:
        for entry in read_avro(mpath):
            status = entry.get("status", 1)
            if status == 2:  # DELETED
                continue
            df = entry.get("data_file") or {}
            # data_file.content (v2): 0 = data, 1/2 = position/equality deletes
            if int(df.get("content") or 0) != 0:
                raise HyperspaceException(
                    f"Iceberg snapshot {snapshot_id} of {table_path} contains "
                    "row-level delete files; merge-on-read is not supported"
                )
            p = _resolve_path(table_path, location, df["file_path"])
            files[p] = (int(df.get("file_size_in_bytes", 0)), 0)
    return IcebergSnapshot(
        table_path=os.path.abspath(table_path),
        snapshot_id=int(snapshot_id),
        files=files,
        schema_fields=_schema_fields(doc),
        location=location,
    )
