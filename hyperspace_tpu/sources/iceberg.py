"""Iceberg source provider (full implementation arrives with the snapshot
reader; see package docstring).

Reference: ``sources/iceberg/IcebergFileBasedSource.scala``,
``IcebergRelation.scala`` (signature = snapshot id + location,
snapshot-pinned scans).
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.sources.interfaces import FileBasedSourceProvider


class IcebergSource(FileBasedSourceProvider):
    name = "iceberg"

    def is_supported(self, session, plan_relation: PlanRelation) -> Optional[bool]:
        if plan_relation.fmt == "iceberg":
            return True
        return None

    def get_relation(self, session, plan_relation: PlanRelation):
        from hyperspace_tpu.sources.iceberg_relation import IcebergRelation

        return IcebergRelation(session, plan_relation)


def IcebergSourceBuilder():  # noqa: N802
    return IcebergSource()
