"""Dependency-injection seams for the metadata plane.

Reference: ``index/factories.scala:26-50`` — the reference routes every
log/data/FS manager construction through factory objects so tests can
swap in mocks and exercise failure paths (mid-action crashes, flaky
storage) without real faults. Same seam here: the collection manager
builds all per-index managers through these module-level factories;
tests reassign them (and restore afterwards, e.g. via pytest
monkeypatch.setattr).
"""

from __future__ import annotations

from typing import Callable

from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager

# callable(index_path) -> log manager
log_manager_factory: Callable[[str], IndexLogManager] = IndexLogManager
# callable(index_path) -> data manager
data_manager_factory: Callable[[str], IndexDataManager] = IndexDataManager


def create_log_manager(index_path: str) -> IndexLogManager:
    return log_manager_factory(index_path)


def create_data_manager(index_path: str) -> IndexDataManager:
    return data_manager_factory(index_path)
