"""Column-name resolution (case-insensitive by default, nested fields).

Reference: ``util/ResolverUtils.scala`` — resolves requested column names
against a plan's schema, optionally case-sensitively; nested struct fields
are flattened into top-level index columns with the ``__hs_nested.``
prefix (``ResolvedColumn``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence

from hyperspace_tpu.constants import NESTED_FIELD_PREFIX
from hyperspace_tpu.exceptions import HyperspaceException


@dataclasses.dataclass(frozen=True)
class ResolvedColumn:
    """A resolved column; ``is_nested`` marks a struct-path column.

    ``normalized_name`` is the name used inside index data (nested paths get
    the ``__hs_nested.`` prefix so they become legal flat column names —
    reference ResolverUtils.ResolvedColumn).
    """

    name: str
    is_nested: bool = False

    @property
    def normalized_name(self) -> str:
        return (NESTED_FIELD_PREFIX + self.name) if self.is_nested else self.name

    @staticmethod
    def from_normalized(name: str) -> "ResolvedColumn":
        if name.startswith(NESTED_FIELD_PREFIX):
            return ResolvedColumn(name[len(NESTED_FIELD_PREFIX):], True)
        return ResolvedColumn(name, False)


def nested_available_from(column_names: Iterable[str]) -> List[str]:
    """The dotted struct paths a relation surfaces, derived from its
    flattened ``__hs_nested.``-prefixed columns (io/columnar.py
    ``flatten_schema_fields``) — the ``nested_available`` input to
    :func:`resolve`."""
    return [
        c[len(NESTED_FIELD_PREFIX):]
        for c in column_names
        if c.startswith(NESTED_FIELD_PREFIX)
    ]


def resolve_one(
    requested: str, available: Sequence[str], case_sensitive: bool = False
) -> Optional[str]:
    """Return the matching available name, or None."""
    if case_sensitive:
        return requested if requested in available else None
    low = requested.lower()
    for a in available:
        if a.lower() == low:
            return a
    return None


def resolve(
    requested: Iterable[str],
    available: Sequence[str],
    case_sensitive: bool = False,
    nested_available: Sequence[str] = (),
) -> Optional[List[ResolvedColumn]]:
    """Resolve all names or return None (ResolverUtils.resolve).

    ``nested_available`` lists dotted struct paths (e.g. ``a.b.c``) that the
    relation can surface as nested index columns.
    """
    out: List[ResolvedColumn] = []
    for r in requested:
        m = resolve_one(r, available, case_sensitive)
        if m is not None:
            out.append(ResolvedColumn(m, False))
            continue
        m = resolve_one(r, nested_available, case_sensitive)
        if m is not None:
            out.append(ResolvedColumn(m, True))
            continue
        return None
    return out


def require_resolve(
    requested: Iterable[str],
    available: Sequence[str],
    case_sensitive: bool = False,
    nested_available: Sequence[str] = (),
) -> List[ResolvedColumn]:
    resolved = resolve(requested, available, case_sensitive, nested_available)
    if resolved is None:
        raise HyperspaceException(
            f"Columns {list(requested)} could not be resolved against "
            f"available columns {list(available)}"
        )
    return resolved
