"""JSON (de)serialization helpers.

Reference: ``util/JsonUtils.scala`` (Jackson wrapper). Polymorphism (the
reference's ``@JsonTypeInfo`` on ``Index``/``Sketch``) is handled by a
``"type"`` discriminator key written/read by the registries in
:mod:`hyperspace_tpu.indexes` and the sketch registry.
"""

from __future__ import annotations

import json
from typing import Any


def to_json(obj: Any, indent: int | None = None) -> str:
    return json.dumps(obj, sort_keys=True, indent=indent)


def from_json(text: str) -> Any:
    return json.loads(text)
