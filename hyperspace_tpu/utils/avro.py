"""Minimal Apache Avro object-container codec (reader + writer).

Iceberg manifest lists and manifest files are Avro; no Avro library is
available in this environment, so this implements the (small, stable) spec
directly: header magic ``Obj\\x01`` + metadata map (``avro.schema`` JSON,
``avro.codec``) + sync marker, then blocks of ``(count, size, data)``.
Binary encoding: zigzag varints for int/long, little-endian IEEE for
float/double, length-prefixed bytes/string, index-prefixed unions,
block-encoded arrays/maps. Codecs: ``null`` and ``deflate``.

Reader is schema-driven and generic; the writer exists for synthesizing
test fixtures and writing manifests of our own (the reference leans on the
Iceberg library for this; ``sources/iceberg/``).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterable, List, Tuple

from hyperspace_tpu.exceptions import HyperspaceException

MAGIC = b"Obj\x01"
SYNC = b"\x00\x01\x02\x03\x04\x05\x06\x07\x08\x09\x0a\x0b\x0c\x0d\x0e\x0f"


# ---------------------------------------------------------------------------
# primitive binary encoding
# ---------------------------------------------------------------------------


def _read_long(buf: io.BytesIO) -> int:
    shift, acc = 0, 0
    while True:
        b = buf.read(1)
        if not b:
            raise HyperspaceException("Truncated Avro varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag


def _write_long(out: io.BytesIO, value: int) -> None:
    u = (value << 1) ^ (value >> 63)  # zigzag (python ints are unbounded)
    u &= (1 << 70) - 1
    while True:
        if u < 0x80:
            out.write(bytes([u]))
            return
        out.write(bytes([(u & 0x7F) | 0x80]))
        u >>= 7


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise HyperspaceException("Truncated Avro bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# schema-driven value codec
# ---------------------------------------------------------------------------


def _decode(schema, buf: io.BytesIO):
    if isinstance(schema, str):
        t = schema
    elif isinstance(schema, list):  # union: index then value
        idx = _read_long(buf)
        return _decode(schema[idx], buf)
    else:
        t = schema["type"]
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) != b"\x00"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "array":
        out = []
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                _read_long(buf)  # block byte size, unused
                count = -count
            for _ in range(count):
                out.append(_decode(schema["items"], buf))
        return out
    if t == "map":
        out = {}
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                _read_long(buf)
                count = -count
            for _ in range(count):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = _decode(schema["values"], buf)
        return out
    if t == "record":
        return {
            f["name"]: _decode(f["type"], buf) for f in schema["fields"]
        }
    if isinstance(schema, dict) and t not in (
        "null", "boolean", "int", "long", "float", "double", "bytes",
        "string", "fixed", "enum", "array", "map", "record",
    ):
        # named-type reference or logical type wrapper
        return _decode(t, buf)
    raise HyperspaceException(f"Unsupported Avro type: {t!r}")


def _encode(schema, value, out: io.BytesIO) -> None:
    if isinstance(schema, str):
        t = schema
    elif isinstance(schema, list):  # union: pick the branch by value
        for i, branch in enumerate(schema):
            bt = branch if isinstance(branch, str) else branch.get("type")
            if value is None and bt == "null":
                _write_long(out, i)
                return
            if value is not None and bt != "null":
                _write_long(out, i)
                _encode(branch, value, out)
                return
        raise HyperspaceException(f"No union branch for {value!r} in {schema}")
    else:
        t = schema["type"]
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", value))
    elif t == "double":
        out.write(struct.pack("<d", value))
    elif t == "bytes":
        _write_bytes(out, value)
    elif t == "string":
        _write_bytes(out, value.encode("utf-8"))
    elif t == "fixed":
        out.write(value)
    elif t == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif t == "array":
        if value:
            _write_long(out, len(value))
            for v in value:
                _encode(schema["items"], v, out)
        _write_long(out, 0)
    elif t == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                _write_bytes(out, k.encode("utf-8"))
                _encode(schema["values"], v, out)
        _write_long(out, 0)
    elif t == "record":
        for f in schema["fields"]:
            _encode(f["type"], value.get(f["name"]), out)
    else:
        raise HyperspaceException(f"Unsupported Avro type: {t!r}")


# ---------------------------------------------------------------------------
# container files
# ---------------------------------------------------------------------------


def read_avro(path: str) -> List[Any]:
    """All records of an Avro object-container file."""
    return read_avro_with_schema(path)[1]


def read_avro_with_schema(path: str):
    """(avro_schema_dict, records) of an Avro object-container file —
    the embedded schema drives Arrow typing for empty/all-null files
    where value inference has nothing to go on."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise HyperspaceException(f"Not an Avro file: {path}")
    meta_schema = {"type": "map", "values": "bytes"}
    meta = _decode(meta_schema, buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = buf.read(16)
    records: List[Any] = []
    while buf.tell() < len(data):
        count = _read_long(buf)
        size = _read_long(buf)
        block = buf.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise HyperspaceException(f"Unsupported Avro codec: {codec!r}")
        bbuf = io.BytesIO(block)
        for _ in range(count):
            records.append(_decode(schema, bbuf))
        if buf.read(16) != sync:
            raise HyperspaceException(f"Avro sync marker mismatch in {path}")
    return schema, records


def write_avro(path: str, schema: dict, records: Iterable[Any]) -> None:
    records = list(records)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {
        "avro.schema": json.dumps(schema).encode("utf-8"),
        "avro.codec": b"null",
    }
    _encode({"type": "map", "values": "bytes"}, meta, out)
    out.write(SYNC)
    block = io.BytesIO()
    for r in records:
        _encode(schema, r, block)
    _write_long(out, len(records))
    _write_long(out, block.tell())
    out.write(block.getvalue())
    out.write(SYNC)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(out.getvalue())
