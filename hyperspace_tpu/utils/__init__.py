"""Cross-cutting utilities (reference: ``util/*.scala``)."""
