"""Path normalization and data-path filtering.

Reference: ``util/PathUtils.scala`` (path normalization, ``DataPathFilter``
skipping hidden files — names starting with '_' or '.').
"""

from __future__ import annotations

import os


def normalize(path: str) -> str:
    """Absolute path with scheme-less local paths resolved.

    The reference normalizes to fully-qualified Hadoop paths
    (``PathUtils.makeAbsolute``); on a local/posix filesystem this is
    ``os.path.abspath`` with trailing separators stripped.
    """
    if "://" in path:
        return path.rstrip("/")
    return os.path.abspath(path)


def is_data_path(name: str) -> bool:
    """DataPathFilter: ignore metadata/hidden files (PathUtils.scala)."""
    base = os.path.basename(name)
    return not (base.startswith("_") or base.startswith("."))
