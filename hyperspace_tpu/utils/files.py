"""Filesystem helpers used by the metadata plane.

Reference: ``util/FileUtils.scala`` (create/delete/read through the Hadoop
``FileSystem`` API). This build targets a POSIX filesystem (and, by
extension, FUSE-mounted object stores); the one primitive whose semantics
matter is *atomic create-if-absent*, used by the operation log's optimistic
concurrency (``index/IndexLogManager.scala:178-194``).
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, List, Tuple


def write_text(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)


def read_text(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: durably record its entries.

    File fsync alone does not survive a dirent-loss crash on ext4 — the
    journal can commit the file's data while the directory entry that
    names it is still only in memory, so a crash right after an atomic
    publish can un-publish the name. Called after every link/replace
    that publishes a log entry. Best-effort: some filesystems (FUSE
    object-store mounts) reject directory fsync — there the rename
    itself is the durability point and this is a no-op."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_if_absent(path: str, text: str) -> bool:
    """Create ``path`` with ``text`` iff it does not exist; atomic.

    Mirrors the reference's temp-file + rename-without-overwrite protocol
    (``IndexLogManagerImpl.writeLog:178-194``): write to a temp file in the
    same directory, then ``os.link`` it to the final name. ``link`` fails
    with EEXIST if another writer won the race — the optimistic-concurrency
    conflict signal. Returns True on success, False on conflict.

    On object stores this maps to a generation-match precondition
    (if-generation-match=0 on GCS); the boolean contract is identical.
    FUSE mounts that don't support hard links fall back to exclusive
    create (O_EXCL), which those mounts do honor.
    """
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_log_")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            # fsync BEFORE the link publishes the name (the
            # calibrate._store_cache pattern, docs/static-analysis.md):
            # on a journaled filesystem a crash between write and
            # publish must never leave a torn/empty log entry visible
            # under its final name — readers treat an existing entry as
            # complete JSON (get_log has no partial-read recovery).
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)
            fsync_dir(d)
            return True
        except FileExistsError:
            return False
        except OSError:
            # Hard links unsupported (FUSE object-store mounts): O_EXCL path.
            # No atomic-content guarantee exists here at all (the name is
            # visible while the content streams); fsync at least bounds
            # the crash window to the write itself on those mounts.
            try:
                with open(path, "x", encoding="utf-8") as f:
                    f.write(text)
                    f.flush()
                    os.fsync(f.fileno())
                fsync_dir(d)
                return True
            except FileExistsError:
                return False
    finally:
        os.unlink(tmp)


def atomic_overwrite(path: str, text: str) -> None:
    """Atomically replace ``path`` with ``text`` (latestStable pointer).

    fsync-before-replace, like :func:`atomic_write_if_absent`: a crash
    right after the rename must not publish an empty pointer file (the
    rename can be journaled before the data on ext4/xfs without it).
    """
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_log_")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise


def atomic_overwrite_bytes(path: str, data: bytes) -> None:
    """:func:`atomic_overwrite` for binary payloads (the fleet result
    spool's Arrow IPC files) — same fsync-before-replace discipline, so
    a reader either sees the complete payload under the final name or
    no file at all, never a torn one."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_spool_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise


def delete(path: str) -> None:
    """Recursive delete, ignore-missing (FileUtils.delete)."""
    if os.path.isdir(path) and not os.path.islink(path):
        shutil.rmtree(path, ignore_errors=True)
    else:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass


def list_leaf_files(
    root: str, suffix: str = "", data_only: bool = False
) -> List[Tuple[str, int, int]]:
    """Recursive listing of (path, size, mtime_ms) for all regular files.

    Equivalent to the recursive ``listStatus`` in
    ``Content.fromDirectory`` (IndexLogEntry.scala:86-96). With
    ``data_only`` the walk skips hidden/metadata paths the way Spark's
    ``DataPathFilter`` does (``util/PathUtils.scala``); ``suffix`` filters
    by file extension. This is the single walker — callers must not grow
    their own ``os.walk`` so the hidden-path policy stays in one place.
    """
    from hyperspace_tpu.utils.paths import is_data_path

    out: List[Tuple[str, int, int]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        if data_only:
            dirnames[:] = [d for d in dirnames if is_data_path(d)]
        for name in sorted(filenames):
            if suffix and not name.endswith(suffix):
                continue
            if data_only and not is_data_path(name):
                continue
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p)
            except FileNotFoundError:
                continue
            out.append((p, st.st_size, int(st.st_mtime * 1000)))
    return out


def dir_size(root: str) -> int:
    return sum(size for _p, size, _m in list_leaf_files(root))
