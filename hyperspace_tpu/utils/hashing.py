"""Host-side hashing utilities.

Reference: ``util/HashingUtils.scala`` (md5 for plan/file fingerprints).
Device-side hashing (bucket assignment) lives in
:mod:`hyperspace_tpu.ops.hash` — it must be an XLA-compilable function, not
a host hash. The murmur3 implementations here are the *host twins* of that
device code: string dictionary entries are hashed host-side once per unique
value (O(unique), not O(rows)) and gathered through dictionary codes on
device (see ``io/columnar.py`` key-rep contract).
"""

from __future__ import annotations

import hashlib
from typing import Any

_M32 = 0xFFFFFFFF


def md5_hex(value: Any) -> str:
    """md5 of ``str(value)`` as hex — mirrors HashingUtils.md5Hex."""
    return hashlib.md5(str(value).encode("utf-8")).hexdigest()


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32_bytes(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit of raw bytes (standard reference algorithm).

    The device kernel (``ops/hash.py``) applies the same block/mix/fmix
    arithmetic to int64 key reps; this host version handles the
    variable-width inputs (strings) that never reach the device raw.
    """
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k1 = int.from_bytes(data[4 * i : 4 * i + 4], "little")
        k1 = (k1 * c1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _M32
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4 :]
    k1 = 0
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = (k1 * c1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _M32
        h1 ^= k1
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    h1 ^= h1 >> 16
    return h1


def murmur3_64_bytes(data: bytes) -> int:
    """Stable signed 64-bit hash of bytes: two seeded murmur3-32 words.

    Used as the key rep of string values (``io/columnar.py``). Signed so it
    fits np.int64 directly.
    """
    lo = murmur3_32_bytes(data, seed=0)
    hi = murmur3_32_bytes(data, seed=0x9747B28C)
    u = (hi << 32) | lo
    return u - (1 << 64) if u >= (1 << 63) else u
