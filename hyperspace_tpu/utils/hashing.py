"""Host-side hashing utilities.

Reference: ``util/HashingUtils.scala`` (md5 for plan/file fingerprints).
Device-side hashing (bucket assignment) lives in
:mod:`hyperspace_tpu.ops.hash` — it must be an XLA-compilable function, not
a host hash.
"""

from __future__ import annotations

import hashlib
from typing import Any


def md5_hex(value: Any) -> str:
    """md5 of ``str(value)`` as hex — mirrors HashingUtils.md5Hex."""
    return hashlib.md5(str(value).encode("utf-8")).hexdigest()
