"""Logical plan IR.

The reference injects rules into Spark's Catalyst optimizer
(``rules/ApplyHyperspace.scala``); here we own the whole planner: a small
relational IR (Scan/Filter/Project/Join — :mod:`hyperspace_tpu.plan.nodes`)
with typed expressions (:mod:`hyperspace_tpu.plan.expressions`). Queries are
built through the DataFrame API (:mod:`hyperspace_tpu.dataframe`), optimized
by the rules in :mod:`hyperspace_tpu.rules`, and executed by
:mod:`hyperspace_tpu.execution`.
"""

from hyperspace_tpu.plan import expressions as E  # noqa: F401
from hyperspace_tpu.plan.nodes import (  # noqa: F401
    Filter,
    Join,
    LogicalPlan,
    Project,
    Scan,
)
