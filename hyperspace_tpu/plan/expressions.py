"""Typed expression tree + null-aware columnar evaluation.

Plays the role of Catalyst expressions in the reference (predicates reach
its rules as Spark ``Expression`` trees, e.g.
``covering/FilterIndexRule.scala:62-103`` walks them for column coverage).
Nodes are frozen dataclasses: hashable (planner memoization, jit static
args) and comparable structurally.

Evaluation is SQL three-valued logic over :class:`ColumnarBatch` columns:
``evaluate`` returns ``(values, valid)`` numpy arrays; a filter keeps rows
where ``values & valid``. String comparisons never touch bytes row-wise —
equality/In compare dictionary codes, ordering comparisons compare
per-batch *rank* arrays (dictionary sorted host-side once, O(unique)), so
the same arithmetic runs on device codes (see ``ops/filter.py``, the
XLA-compiled twin of this evaluator).
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Any, FrozenSet, List, Optional, Set, Tuple, Union

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException

# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Expr:
    def __and__(self, other: "Expr") -> "Expr":
        return And(self, _lit(other))

    def __or__(self, other: "Expr") -> "Expr":
        return Or(self, _lit(other))

    def __invert__(self) -> "Expr":
        return Not(self)

    def __bool__(self):
        # Col.__eq__ builds an Eq expression (DataFrame API), so Python
        # equality on expression trees is NOT structural equality. Fail
        # loudly instead of silently treating every comparison as truthy.
        raise TypeError(
            "Expression has no truth value; use semantic_equals() or repr()"
        )


def semantic_equals(a: Optional["Expr"], b: Optional["Expr"]) -> bool:
    """Structural equality (repr is canonical for these frozen trees)."""
    return repr(a) == repr(b)


def _lit(v: Union["Expr", Any]) -> "Expr":
    return v if isinstance(v, Expr) else Lit(v)


@dataclasses.dataclass(frozen=True)
class Col(Expr):
    name: str

    def __repr__(self):
        return self.name

    # comparison builders (DataFrame API surface)
    def __eq__(self, other):  # type: ignore[override]
        return Eq(self, _lit(other))

    def __ne__(self, other):  # type: ignore[override]
        return Ne(self, _lit(other))

    def __lt__(self, other):
        return Lt(self, _lit(other))

    def __le__(self, other):
        return Le(self, _lit(other))

    def __gt__(self, other):
        return Gt(self, _lit(other))

    def __ge__(self, other):
        return Ge(self, _lit(other))

    def __hash__(self):
        return hash(("Col", self.name))

    def isin(self, *values) -> "In":
        vals = values[0] if len(values) == 1 and isinstance(
            values[0], (list, tuple, set)
        ) else values
        return In(self, tuple(sorted(set(vals), key=repr)))

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "Not":
        return Not(IsNull(self))


@dataclasses.dataclass(frozen=True)
class Lit(Expr):
    value: Any

    def __repr__(self):
        return repr(self.value)


@dataclasses.dataclass(frozen=True, repr=False)
class _Binary(Expr):
    left: Expr
    right: Expr

    op = "?"

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


class Eq(_Binary):
    op = "="


class Ne(_Binary):
    op = "!="


class Lt(_Binary):
    op = "<"


class Le(_Binary):
    op = "<="


class Gt(_Binary):
    op = ">"


class Ge(_Binary):
    op = ">="


class And(_Binary):
    op = "AND"


class Or(_Binary):
    op = "OR"


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    child: Expr

    def __repr__(self):
        return f"NOT {self.child!r}"


@dataclasses.dataclass(frozen=True)
class In(Expr):
    child: Expr
    values: Tuple[Any, ...]

    def __repr__(self):
        return f"{self.child!r} IN {list(self.values)}"


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    child: Expr

    def __repr__(self):
        return f"{self.child!r} IS NULL"


# ---------------------------------------------------------------------------
# Tree utilities (planner surface)
# ---------------------------------------------------------------------------


def references(expr: Expr) -> Set[str]:
    """Column names referenced by the expression
    (Catalyst ``Expression.references``)."""
    if isinstance(expr, Col):
        return {expr.name}
    if isinstance(expr, Lit):
        return set()
    if isinstance(expr, _Binary):
        return references(expr.left) | references(expr.right)
    if isinstance(expr, (Not, IsNull)):
        return references(expr.child)
    if isinstance(expr, In):
        return references(expr.child)
    raise HyperspaceException(f"Unknown expression: {expr!r}")


def split_conjuncts(expr: Optional[Expr]) -> List[Expr]:
    """CNF top level: flatten nested ANDs
    (``JoinIndexRule`` CNF handling, JoinIndexRule.scala:164-170)."""
    if expr is None:
        return []
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def conjunction(exprs: List[Expr]) -> Optional[Expr]:
    out: Optional[Expr] = None
    for e in exprs:
        out = e if out is None else And(out, e)
    return out


def lower_literal(value, arrow_type, op: Optional[str] = None):
    """Engine-internal image of a literal for a column of ``arrow_type``.

    Temporal columns are stored as int64 epoch units (io/columnar ingest
    views datetime64 as int64), so temporal literals — np.datetime64,
    datetime.date/datetime, ISO strings — are lowered through the same
    arrow ingestion path the data took, landing in the column's exact
    unit. Non-temporal types pass through unchanged. Returns None when
    the literal cannot represent a value of the column's type (a
    comparison against it can then never be true).
    """
    import pyarrow as pa

    if arrow_type is None or not pa.types.is_temporal(arrow_type):
        return value
    unit = _temporal_storage_unit(arrow_type)
    if unit is None:
        if pa.types.is_time(arrow_type):
            return _lower_time_literal(value, arrow_type, op)
        if pa.types.is_duration(arrow_type):
            return _lower_duration_literal(value, arrow_type, op)
        return value  # interval types beyond duration: untouched
    dt64 = _as_datetime64(value)
    if dt64 is None:
        return None
    # exact python-int arithmetic: NEVER let numpy overflow silently.
    # A literal beyond the column unit's representable range still has a
    # definite ordering answer, so it clamps to ±inf (int64-vs-float
    # comparisons give the right result; equality against ±inf is False).
    src_unit = np.datetime_data(dt64.dtype)[0]
    if src_unit in ("Y", "M", "W"):
        dt64 = dt64.astype("datetime64[D]")  # exact calendar conversion
        src_unit = "D"
    if src_unit not in _NS_PER:
        return None  # sub-ns units (ps/fs/as): beyond engine precision
    v_ns = int(dt64.view("int64")) * _NS_PER[src_unit]
    return _clamp_ticks(_snap_between_tick(*divmod(v_ns, _NS_PER[unit]), op))


def _snap_between_tick(q, r, op):
    """Boundary snap for a literal BETWEEN column ticks q and q+1 (divmod
    floors): col < lit ⟺ col <= q ⟺ col < q+1 and col >= lit ⟺
    col >= q+1; col <= lit ⟺ col <= q, col > lit ⟺ col > q. Equality
    can never hold — op None / = / != return None (callers treat that as
    never-true, != as true-for-valid). Shared by the timestamp/date and
    time-of-day lowering paths so their semantics can't diverge."""
    if r == 0:
        return q
    if op in ("<", ">="):
        return q + 1
    if op in ("<=", ">"):
        return q
    return None


# Nanoseconds per fixed-length unit — ONE table shared by every temporal
# lowering path (datetime, time-of-day, duration). Calendar units (Y/M)
# are deliberately absent: they have no fixed length.
_NS_PER = {
    "W": 604_800_000_000_000,
    "D": 86_400_000_000_000,
    "h": 3_600_000_000_000,
    "m": 60_000_000_000,
    "s": 1_000_000_000,
    "ms": 1_000_000,
    "us": 1_000,
    "ns": 1,
}


def _clamp_ticks(q):
    """Snap-result -> engine literal: int64 ticks, or ±inf when the exact
    tick count overflows int64 (ordering against ±inf stays correct;
    equality is False). Shared by the datetime and duration paths."""
    if q is None:
        return None
    if q > np.iinfo(np.int64).max:
        return np.float64("inf")
    if q < np.iinfo(np.int64).min:
        return np.float64("-inf")
    return np.int64(q)


def _lower_time_literal(value, arrow_type, op):
    """datetime.time / ISO string -> int64 in the time column's unit
    (time-of-day columns ingest as their integer representation)."""
    import datetime as _dt

    if isinstance(value, str):
        try:
            value = _dt.time.fromisoformat(value)
        except ValueError:
            return None
    if not isinstance(value, _dt.time):
        return None
    if value.tzinfo is not None:
        # a zoned time-of-day cannot be compared to naive column values
        # (the timestamp path CONVERTS offsets; here there is no date to
        # anchor the conversion) — unrepresentable, never matches
        return None
    ns = (
        ((value.hour * 60 + value.minute) * 60 + value.second) * 10**9
        + value.microsecond * 1000
    )
    q = _snap_between_tick(*divmod(ns, _NS_PER[arrow_type.unit]), op)
    return None if q is None else np.int64(q)


def _temporal_storage_unit(arrow_type):
    """numpy datetime64 unit matching io/columnar's int64 storage of the
    arrow type (date32→days, date64→ms, timestamp→its own unit)."""
    import pyarrow as pa

    if pa.types.is_date32(arrow_type):
        return "D"
    if pa.types.is_date64(arrow_type):
        return "ms"
    if pa.types.is_timestamp(arrow_type):
        return arrow_type.unit
    return None


def _as_datetime64(value):
    """np.datetime64 image of a literal at its OWN precision (so lossy
    conversions are detectable), or None."""
    import datetime as _dt

    if isinstance(value, np.datetime64):
        return value
    if isinstance(value, str):
        try:
            return np.datetime64(value)
        except ValueError:
            return None
    if isinstance(value, _dt.datetime):
        return np.datetime64(value, "us")
    if isinstance(value, _dt.date):
        return np.datetime64(value, "D")
    return None


def _duration_ns(value):
    """Exact nanosecond count of a duration literal as a python int
    (arbitrary precision — overflow must clamp, never wrap), or None for
    anything that is not a fixed-length duration. Calendar-length numpy
    units (Y/M) have no fixed nanosecond value and return None, matching
    numpy's own refusal to compare them against fixed units."""
    import datetime as _dt

    if isinstance(value, np.timedelta64):
        if np.isnat(value):
            return None  # NaT comparisons are never true (numpy/pyarrow)
        unit = np.datetime_data(value.dtype)[0]
        if unit not in _NS_PER:
            return None  # Y/M (calendar) or sub-ns precision
        return int(value.view("int64")) * _NS_PER[unit]
    if isinstance(value, _dt.timedelta):
        # python timedelta is exact at microsecond resolution
        return (
            (value.days * 86_400_000_000 + value.seconds * 1_000_000)
            + value.microseconds
        ) * 1_000
    return None


def _lower_duration_literal(value, arrow_type, op):
    """int64 ticks of the duration column's storage unit (io/columnar
    views timedelta64 as int64), with the same between-tick snapping and
    ±inf overflow clamping as datetime lowering. The reference gets
    interval casts from Catalyst; here the literal is lowered through
    exact python-int arithmetic."""
    ns = _duration_ns(value)
    if ns is None:
        return None
    q = _snap_between_tick(*divmod(ns, _NS_PER[arrow_type.unit]), op)
    return _clamp_ticks(q)


def normalize_temporal_literal(value, arrow_type):
    """Python date/datetime image of a temporal literal, or None when
    unrepresentable — for consumers comparing against python-object cells
    (the min/max sketch probe). A sub-day instant can never represent a
    date; sub-microsecond precision cannot round-trip through python
    datetime, so such literals return None (callers fall back to no
    pruning, which is sound)."""
    import datetime as _dt

    import pyarrow as pa

    dt64 = _as_datetime64(value)
    if dt64 is None:
        return None
    us = dt64.astype("datetime64[us]")
    if us.astype(dt64.dtype) != dt64:
        return None
    value = us.item()  # datetime.datetime
    if pa.types.is_date(arrow_type):
        if value.time() != _dt.time(0):
            return None
        value = value.date()
    return value


def lower_in_literals(values, arrow_type) -> List[Any]:
    """IN-list literals in engine-internal form for a numeric column:
    temporal literals lower to the column's int64 units (unrepresentable
    ones can never match and are dropped); otherwise only type-compatible
    plain literals survive. Shared by the host evaluator and the device
    filter so both paths agree."""
    import pyarrow as pa

    if arrow_type is not None and pa.types.is_temporal(arrow_type):
        out = []
        for v in values:
            if v is None:
                continue
            lv = lower_literal(v, arrow_type)
            # only exact column ticks can match equality: drop ±inf
            # (out-of-range) and x.5 (between ticks) — a float in the
            # list would also upcast the whole array and break int64
            # equality beyond 2^53
            if lv is not None and isinstance(lv, np.int64):
                out.append(lv)
        return out
    out = []
    for v in values:
        # numpy scalars are first-class literals (df['k'].isin(arr[0]))
        if isinstance(v, (np.integer, np.floating, np.bool_)):
            v = v.item()
        if isinstance(v, (int, float, bool)):
            out.append(v)
    return out


def normalize_comparison(expr: Expr) -> Optional[Tuple[str, str, Any]]:
    """-> (op, column_name, literal) for Col-vs-Lit comparisons (either
    operand order; never a None literal), else None. The single home of
    the operand-swap rule (shared by sketch predicate translation and
    executor bucket pruning)."""
    if not isinstance(expr, (Eq, Ne, Lt, Le, Gt, Ge)):
        return None
    left, right, op = expr.left, expr.right, expr.op
    flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
    if isinstance(left, Lit) and isinstance(right, Col):
        left, right, op = right, left, flipped[op]
    if isinstance(left, Col) and isinstance(right, Lit):
        if right.value is None:
            return None
        return op, left.name, right.value
    return None


def equi_join_pairs(cond: Expr) -> Optional[List[Tuple[str, str]]]:
    """If cond is a conjunction of Col == Col, the (left, right) name pairs;
    else None (JoinIndexRule CNF equi-condition check :164-170)."""
    pairs = []
    for c in split_conjuncts(cond):
        if isinstance(c, Eq) and isinstance(c.left, Col) and isinstance(c.right, Col):
            pairs.append((c.left.name, c.right.name))
        else:
            return None
    return pairs


# ---------------------------------------------------------------------------
# Evaluation (host numpy; the device twin lives in ops/filter.py)
# ---------------------------------------------------------------------------


class _StringRef:
    """A string column's evaluation view: codes + dictionary rank tables."""

    __slots__ = ("codes", "dictionary", "sorted_dict", "rank")

    def __init__(self, codes: np.ndarray, dictionary: List[str]):
        self.codes = codes
        self.dictionary = dictionary
        order = sorted(range(len(dictionary)), key=lambda i: dictionary[i])
        self.sorted_dict = [dictionary[i] for i in order]
        rank = np.empty(max(len(dictionary), 1), dtype=np.int64)
        for r, i in enumerate(order):
            rank[i] = r
        self.rank = rank

    @property
    def valid(self) -> np.ndarray:
        return self.codes >= 0

    def code_of(self, value: str) -> int:
        """Dictionary code of value, or -2 if absent (never matches)."""
        try:
            return self.dictionary.index(value)
        except ValueError:
            return -2

    def rank_values(self) -> np.ndarray:
        return self.rank[np.maximum(self.codes, 0)]

    def rank_bounds(self, value: str) -> Tuple[int, int]:
        """(bisect_left, bisect_right) of value in the sorted dictionary —
        turns string ordering comparisons into integer rank comparisons."""
        return (
            bisect.bisect_left(self.sorted_dict, value),
            bisect.bisect_right(self.sorted_dict, value),
        )


_Val = Tuple[Any, Optional[np.ndarray]]  # (values-or-_StringRef, valid|None)


def _column_ref(batch, name: str) -> _Val:
    col = batch.column(name)
    if col.kind == "string":
        ref = _StringRef(col.codes, col.dictionary)
        v = ref.valid
        return ref, None if v.all() else v
    if col.validity is not None:
        return col.values, col.validity
    return col.values, None


def _both_valid(a: Optional[np.ndarray], b: Optional[np.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _cmp(expr: Expr, batch, op_name: str) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    left, right = expr.left, expr.right
    # Normalize Lit-on-left
    flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
    if isinstance(left, Lit) and not isinstance(right, Lit):
        left, right = right, left
        op_name = flipped[op_name]
    if isinstance(left, Col) and isinstance(right, Lit):
        vref, valid = _column_ref(batch, left.name)
        lit = right.value
        if lit is None:
            n = batch.num_rows
            return np.zeros(n, bool), np.zeros(n, bool)
        if isinstance(vref, _StringRef):
            if op_name in ("=", "!="):
                code = vref.code_of(str(lit))
                vals = vref.codes == code
                if op_name == "!=":
                    vals = ~vals & vref.valid
                valid = _both_valid(valid, None)
                return vals, vref.valid if valid is None else valid
            lo, hi = vref.rank_bounds(str(lit))
            r = vref.rank_values()
            vals = {"<": r < lo, "<=": r < hi, ">": r >= hi, ">=": r >= lo}[op_name]
            return vals, vref.valid
        lit = lower_literal(lit, batch.column(left.name).arrow_type, op_name)
        if lit is None:
            # literal unrepresentable in the column's type: equality and
            # orderings can never hold; != holds for every non-null row
            n = batch.num_rows
            return np.full(n, op_name == "!="), valid
        v = vref
        with np.errstate(invalid="ignore"):
            vals = {
                "=": v == lit,
                "!=": v != lit,
                "<": v < lit,
                "<=": v <= lit,
                ">": v > lit,
                ">=": v >= lit,
            }[op_name]
        return np.asarray(vals, dtype=bool), valid
    if isinstance(left, Col) and isinstance(right, Col):
        lv, lvalid = _column_ref(batch, left.name)
        rv, rvalid = _column_ref(batch, right.name)
        if isinstance(lv, _StringRef) or isinstance(rv, _StringRef):
            if not (isinstance(lv, _StringRef) and isinstance(rv, _StringRef)):
                raise HyperspaceException(
                    f"Type mismatch comparing {left!r} and {right!r}"
                )
            # col-col string compare: remap right codes into left dictionary
            from hyperspace_tpu.io.columnar import Column as _C
            from hyperspace_tpu.io.columnar import remap_codes

            rcol = _C("string", None, codes=rv.codes, dictionary=rv.dictionary)
            rcodes = remap_codes(lv.dictionary, rcol)
            if op_name == "=":
                vals = lv.codes == rcodes
            elif op_name == "!=":
                vals = lv.codes != rcodes
            else:
                raise HyperspaceException(
                    "Ordering comparison between two string columns is not supported"
                )
            return vals, _both_valid(lv.valid, rv.valid)
        with np.errstate(invalid="ignore"):
            vals = {
                "=": lv == rv,
                "!=": lv != rv,
                "<": lv < rv,
                "<=": lv <= rv,
                ">": lv > rv,
                ">=": lv >= rv,
            }[op_name]
        return np.asarray(vals, dtype=bool), _both_valid(lvalid, rvalid)
    raise HyperspaceException(f"Unsupported comparison operands: {expr!r}")


def evaluate(expr: Expr, batch) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Null-aware evaluation -> (bool values, valid mask|None).

    A row passes a filter iff values & (valid if not None else True).
    """
    n = batch.num_rows
    if isinstance(expr, Lit):
        if expr.value is None:
            return np.zeros(n, bool), np.zeros(n, bool)
        return np.full(n, bool(expr.value)), None
    if isinstance(expr, (Eq, Ne, Lt, Le, Gt, Ge)):
        return _cmp(expr, batch, expr.op)
    if isinstance(expr, And):
        lv, lk = evaluate(expr.left, batch)
        rv, rk = evaluate(expr.right, batch)
        vals = lv & rv
        if lk is None and rk is None:
            return vals, None
        lk = np.ones(n, bool) if lk is None else lk
        rk = np.ones(n, bool) if rk is None else rk
        # Kleene: known if both known, or either side is known-false
        known = (lk & rk) | (lk & ~lv) | (rk & ~rv)
        return vals & lk & rk, known
    if isinstance(expr, Or):
        lv, lk = evaluate(expr.left, batch)
        rv, rk = evaluate(expr.right, batch)
        lk = np.ones(n, bool) if lk is None else lk
        rk = np.ones(n, bool) if rk is None else rk
        vals = (lv & lk) | (rv & rk)
        known = (lk & rk) | (lk & lv) | (rk & rv)
        return vals, known
    if isinstance(expr, Not):
        v, k = evaluate(expr.child, batch)
        return ~v, k
    if isinstance(expr, IsNull):
        if isinstance(expr.child, Col):
            _vref, valid = _column_ref(batch, expr.child.name)
            if isinstance(_vref, _StringRef):
                return ~_vref.valid, None
            if valid is None:
                return np.zeros(n, bool), None
            return ~valid, None
        v, k = evaluate(expr.child, batch)
        return (np.zeros(n, bool) if k is None else ~k), None
    if isinstance(expr, In):
        if not isinstance(expr.child, Col):
            raise HyperspaceException("IN requires a column operand")
        vref, valid = _column_ref(batch, expr.child.name)
        # SQL: a NULL in the list makes non-matching rows UNKNOWN (x IN
        # (1, NULL) is TRUE iff x=1, else NULL) — so NOT IN with a NULL
        # returns no rows
        has_null = any(v is None for v in expr.values)

        def with_null(vals, valid):
            if not has_null:
                return vals, valid
            valid = np.ones(n, bool) if valid is None else valid
            return vals, valid & vals

        if isinstance(vref, _StringRef):
            codes = {
                vref.code_of(v) for v in expr.values if isinstance(v, str)
            }
            codes.discard(-2)
            vals = np.isin(vref.codes, np.array(sorted(codes), dtype=np.int64))
            return with_null(vals, vref.valid)
        # type-compatible literals only: 5 matches isin(5, "a") on an int
        # column, the string can never match and must not poison the
        # comparison dtype; temporal literals lower to int64 units
        lits = lower_in_literals(
            expr.values, batch.column(expr.child.name).arrow_type
        )
        if not lits:
            return with_null(np.zeros(n, bool), valid)
        vals = np.isin(vref, np.array(lits))
        return with_null(vals, valid)
    raise HyperspaceException(f"Cannot evaluate expression: {expr!r}")


def filter_mask(expr: Expr, batch) -> np.ndarray:
    vals, valid = evaluate(expr, batch)
    return vals if valid is None else (vals & valid)
