"""Logical plan nodes: Scan / Filter / Project / Join / Aggregate / Sort / Limit.

The relational IR the rules need (SURVEY §7 Phase 3). In the reference
these are Catalyst's ``LogicalRelation``, ``Filter``, ``Project``,
``Join``, ``Aggregate``, ``Sort``, ``GlobalLimit`` — matched against in
e.g. ``covering/FilterIndexRule.scala:33-55`` (Filter[→Project] over a
relation) and ``covering/JoinIndexRule.scala:150-151`` ("linear"
children). The reference delegates aggregate/sort/limit execution to
Spark; here the engine is the serve path, so they are first-class plan
nodes. Plans are immutable; rewrites build new trees.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expressions as E


class LogicalPlan:
    """Base node. ``output`` is the ordered list of column names; ``schema``
    maps name -> pyarrow type."""

    @property
    def children(self) -> List["LogicalPlan"]:
        return []

    @property
    def output(self) -> List[str]:
        raise NotImplementedError

    def schema(self) -> Dict[str, pa.DataType]:
        raise NotImplementedError

    # -- traversal ----------------------------------------------------------
    def collect_leaves(self) -> List["Scan"]:
        if isinstance(self, Scan):
            return [self]
        out: List[Scan] = []
        for c in self.children:
            out.extend(c.collect_leaves())
        return out

    def transform_up(self, fn) -> "LogicalPlan":
        """Bottom-up rewrite: fn(node_with_new_children) -> node."""
        node = self.with_children([c.transform_up(fn) for c in self.children])
        return fn(node)

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        if not children:
            return self
        raise NotImplementedError

    def pretty(self, indent: int = 0) -> str:
        s = "  " * indent + self._node_string()
        for c in self.children:
            s += "\n" + c.pretty(indent + 1)
        return s

    def _node_string(self) -> str:
        return type(self).__name__

    def __repr__(self):
        return self.pretty()


@dataclasses.dataclass(frozen=True)
class Relation:
    """A file-based source snapshot a Scan reads.

    The planner-side analogue of the reference's ``FileBasedRelation``
    (``sources/interfaces.scala:43-277``): root paths + concrete data files
    + schema + format. ``index_info`` is set when this relation *is* an
    index's data (the rewrite target state, like ``IndexHadoopFsRelation``,
    ``plans/logical/IndexHadoopFsRelation.scala:29-53``).
    """

    root_paths: Tuple[str, ...]
    files: Tuple[str, ...]
    fmt: str
    schema_fields: Tuple[Tuple[str, pa.DataType], ...]
    options: Tuple[Tuple[str, str], ...] = ()
    index_info: Optional[Tuple[str, int, str]] = None  # (name, log_version, abbr)
    # query-time row-level compensation (Hybrid Scan deletes):
    # lineage ids to exclude, None if not needed
    excluded_file_ids: Optional[Tuple[int, ...]] = None
    bucket_spec: Optional[Tuple[int, Tuple[str, ...]]] = None  # (numBuckets, cols)
    # hive-style partitioned sources (e.g. partitioned Delta): per file, the
    # partition column values that are NOT stored in the data file and must
    # be injected as constants at scan time: (path, ((col, str_value),...))
    file_partition_values: Tuple[Tuple[str, Tuple[Tuple[str, Optional[str]], ...]], ...] = ()
    # query-time row-group pruning (zone maps, executor._range_pruned_scan):
    # aligned with ``files``; per file either None (read every row group) or
    # the ascending row-group indices to read. None for the whole field
    # means no narrowing anywhere. Set ONLY by the range-pruning pass on a
    # Filter's direct scan — the selection is query-shaped state and must
    # never leak into fingerprint-keyed caches of whole-file data (the
    # serve cache reads full files regardless, so its entries stay a
    # superset; see executor._scan_cache_entry).
    file_row_groups: Optional[Tuple[Optional[Tuple[int, ...]], ...]] = None

    @property
    def schema(self) -> Dict[str, pa.DataType]:
        return dict(self.schema_fields)

    @property
    def column_names(self) -> List[str]:
        return [n for n, _ in self.schema_fields]


class Scan(LogicalPlan):
    def __init__(self, relation: Relation):
        self.relation = relation

    @property
    def output(self) -> List[str]:
        return self.relation.column_names

    def schema(self) -> Dict[str, pa.DataType]:
        return self.relation.schema

    def with_children(self, children):
        assert not children
        return self

    def _node_string(self):
        r = self.relation
        if r.index_info:
            name, ver, abbr = r.index_info
            return (
                f"Scan Hyperspace(Type: {abbr}, Name: {name}, "
                f"LogVersion: {ver}) [{', '.join(self.output)}]"
            )
        roots = ",".join(r.root_paths)
        return f"Scan {r.fmt} {roots} [{', '.join(self.output)}]"


class Filter(LogicalPlan):
    def __init__(self, condition: E.Expr, child: LogicalPlan):
        self.condition = condition
        self.child = child

    @property
    def children(self):
        return [self.child]

    @property
    def output(self):
        return self.child.output

    def schema(self):
        return self.child.schema()

    def with_children(self, children):
        (c,) = children
        return Filter(self.condition, c)

    def _node_string(self):
        return f"Filter {self.condition!r}"


class Project(LogicalPlan):
    def __init__(self, columns: Sequence[str], child: LogicalPlan):
        missing = [c for c in columns if c not in child.output]
        if missing:
            raise HyperspaceException(
                f"Cannot project {missing}; child outputs {child.output}"
            )
        self.columns = list(columns)
        self.child = child

    @property
    def children(self):
        return [self.child]

    @property
    def output(self):
        return list(self.columns)

    def schema(self):
        s = self.child.schema()
        return {c: s[c] for c in self.columns}

    def with_children(self, children):
        (c,) = children
        return Project(self.columns, c)

    def _node_string(self):
        return f"Project [{', '.join(self.columns)}]"


class Union(LogicalPlan):
    """Same-schema union (no dedup). Exists for Hybrid Scan: index data +
    appended source files read side by side — the logical role of the
    reference's ``BucketUnion`` (``plans/logical/BucketUnion.scala:31-68``);
    bucket alignment is an execution-time concern here because sharding is
    explicit in our design (SURVEY §2.11)."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan):
        if list(left.output) != list(right.output):
            raise HyperspaceException(
                f"Union children must align: {left.output} vs {right.output}"
            )
        self.left = left
        self.right = right

    @property
    def children(self):
        return [self.left, self.right]

    @property
    def output(self):
        return self.left.output

    def schema(self):
        return self.left.schema()

    def with_children(self, children):
        left, right = children
        return Union(left, right)

    def _node_string(self):
        return "Union"


class Join(LogicalPlan):
    """Inner equi-join (the only join type JoinIndexRule handles;
    ``JoinIndexRule.scala:155-162`` requires inner + equi-CNF)."""

    def __init__(
        self,
        left: LogicalPlan,
        right: LogicalPlan,
        condition: E.Expr,
        how: str = "inner",
    ):
        if how != "inner":
            raise HyperspaceException(f"Unsupported join type: {how}")
        self.left = left
        self.right = right
        self.condition = condition
        self.how = how
        dup = set(left.output) & set(right.output)
        if dup:
            raise HyperspaceException(
                f"Ambiguous join output columns: {sorted(dup)}; "
                "project/rename before joining"
            )

    @property
    def children(self):
        return [self.left, self.right]

    @property
    def output(self):
        return self.left.output + self.right.output

    def schema(self):
        s = dict(self.left.schema())
        s.update(self.right.schema())
        return s

    def with_children(self, children):
        left, right = children
        return Join(left, right, self.condition, self.how)

    def _node_string(self):
        return f"Join {self.how} on {self.condition!r}"


_AGG_FUNCS = ("sum", "count", "min", "max", "avg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate output: ``func(column) AS alias``. ``column`` is None
    for ``count(*)``."""

    func: str
    column: Optional[str]
    name: str

    def __post_init__(self):
        if self.func not in _AGG_FUNCS:
            raise HyperspaceException(
                f"Unknown aggregate {self.func!r}; supported: {_AGG_FUNCS}"
            )
        if self.column is None and self.func != "count":
            raise HyperspaceException(f"{self.func}(*) is not defined")

    def __repr__(self):
        arg = "*" if self.column is None else self.column
        return f"{self.func}({arg}) AS {self.name}"

    def alias(self, name: str) -> "AggSpec":
        return dataclasses.replace(self, name=name)


def _is_string_type(t: pa.DataType) -> bool:
    if pa.types.is_dictionary(t):
        t = t.value_type
    return pa.types.is_string(t) or pa.types.is_large_string(t)


def _agg_output_type(spec: AggSpec, child_schema) -> pa.DataType:
    """Output type, validating the input type at PLAN time (execution must
    never be the first place an unsupported agg/type pairing surfaces)."""
    if spec.func == "count":
        return pa.int64()
    t = child_schema[spec.column]
    numeric = pa.types.is_floating(t) or pa.types.is_integer(t)
    if spec.func == "avg":
        # booleans are NOT summable/averageable (Spark rejects
        # sum/avg(boolean) at analysis time); min/max(bool) stays legal
        if not numeric:
            raise HyperspaceException(
                f"avg() over non-numeric column {spec.column!r} ({t})"
            )
        return pa.float64()
    if spec.func == "sum":
        if not numeric:
            raise HyperspaceException(
                f"sum() over non-numeric column {spec.column!r} ({t})"
            )
        return pa.float64() if pa.types.is_floating(t) else pa.int64()
    numeric = numeric or pa.types.is_boolean(t)
    # min/max preserve the input type; orderable = numeric/temporal/string
    if not (
        numeric
        or pa.types.is_temporal(t)
        or _is_string_type(t)
    ):
        raise HyperspaceException(
            f"{spec.func}() over unorderable column {spec.column!r} ({t})"
        )
    return t


class Aggregate(LogicalPlan):
    """Hash aggregate: ``group_by`` key columns + aggregate outputs.
    Output order = group columns then aggregate aliases."""

    def __init__(
        self,
        group_by: Sequence[str],
        aggs: Sequence[AggSpec],
        child: LogicalPlan,
    ):
        if not aggs:
            raise HyperspaceException("Aggregate needs at least one aggregate")
        missing = [c for c in group_by if c not in child.output]
        missing += [
            a.column
            for a in aggs
            if a.column is not None and a.column not in child.output
        ]
        if missing:
            raise HyperspaceException(
                f"Cannot aggregate {missing}; child outputs {child.output}"
            )
        names = list(group_by) + [a.name for a in aggs]
        if len(set(names)) != len(names):
            raise HyperspaceException(f"Duplicate aggregate output names: {names}")
        self.group_by = list(group_by)
        self.aggs = list(aggs)
        self.child = child

    @property
    def children(self):
        return [self.child]

    @property
    def output(self):
        return list(self.group_by) + [a.name for a in self.aggs]

    @property
    def input_columns(self) -> set:
        """Child columns this aggregate consumes (keys + agg arguments)."""
        return set(self.group_by) | {
            a.column for a in self.aggs if a.column is not None
        }

    def schema(self):
        s = self.child.schema()
        out = {c: s[c] for c in self.group_by}
        for a in self.aggs:
            out[a.name] = _agg_output_type(a, s)
        return out

    def with_children(self, children):
        (c,) = children
        return Aggregate(self.group_by, self.aggs, c)

    def _node_string(self):
        keys = ", ".join(self.group_by) or "()"
        return f"Aggregate [{keys}] [{', '.join(map(repr, self.aggs))}]"


class Sort(LogicalPlan):
    """Total order by ``keys`` = ((column, ascending), ...). Nulls last."""

    def __init__(self, keys: Sequence[Tuple[str, bool]], child: LogicalPlan):
        if not keys:
            raise HyperspaceException("Sort needs at least one key")
        missing = [c for c, _ in keys if c not in child.output]
        if missing:
            raise HyperspaceException(
                f"Cannot sort by {missing}; child outputs {child.output}"
            )
        self.keys = [(c, bool(asc)) for c, asc in keys]
        self.child = child

    @property
    def children(self):
        return [self.child]

    @property
    def output(self):
        return self.child.output

    def schema(self):
        return self.child.schema()

    def with_children(self, children):
        (c,) = children
        return Sort(self.keys, c)

    def _node_string(self):
        ks = ", ".join(f"{c} {'ASC' if a else 'DESC'}" for c, a in self.keys)
        return f"Sort [{ks}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        if n < 0:
            raise HyperspaceException(f"Limit must be >= 0, got {n}")
        self.n = int(n)
        self.child = child

    @property
    def children(self):
        return [self.child]

    @property
    def output(self):
        return self.child.output

    def schema(self):
        return self.child.schema()

    def with_children(self, children):
        (c,) = children
        return Limit(self.n, c)

    def _node_string(self):
        return f"Limit {self.n}"


def prune_join_columns(plan: LogicalPlan, needed: Optional[set] = None) -> LogicalPlan:
    """Insert explicit Projects above Join children so each side carries
    only the columns used above it.

    The reference's rules run after Catalyst's column pruning, so
    ``JoinIndexRule`` sees minimal child outputs; this pass provides the
    same invariant for our IR. Only Join children are wrapped — existing
    Filter/Project chains are preserved so the Filter-rule plan shapes
    stay matchable.
    """
    if needed is None:
        needed = set(plan.output)
    if isinstance(plan, Project):
        return Project(plan.columns, prune_join_columns(plan.child, set(plan.columns)))
    if isinstance(plan, Filter):
        child_needed = needed | E.references(plan.condition)
        return Filter(plan.condition, prune_join_columns(plan.child, child_needed))
    if isinstance(plan, Aggregate):
        child_needed = plan.input_columns
        pruned = prune_join_columns(plan.child, child_needed)
        # insert the Project Catalyst's ColumnPruning would (above the
        # child chain) so index rules see minimal required columns
        cols = [c for c in pruned.output if c in child_needed]
        if cols and cols != pruned.output:
            pruned = Project(cols, pruned)
        return Aggregate(plan.group_by, plan.aggs, pruned)
    if isinstance(plan, Sort):
        child_needed = needed | {c for c, _ in plan.keys}
        return Sort(plan.keys, prune_join_columns(plan.child, child_needed))
    if isinstance(plan, Limit):
        return Limit(plan.n, prune_join_columns(plan.child, needed))
    if isinstance(plan, Join):
        refs = E.references(plan.condition)
        out = []
        for child in (plan.left, plan.right):
            child_needed = (needed | refs) & set(child.output)
            pruned = prune_join_columns(child, child_needed)
            cols = [c for c in pruned.output if c in child_needed]
            if cols != pruned.output:
                pruned = Project(cols, pruned)
            out.append(pruned)
        return Join(out[0], out[1], plan.condition, plan.how)
    if isinstance(plan, Union):
        return plan  # already minimal (built by the rewrite itself)
    return plan


