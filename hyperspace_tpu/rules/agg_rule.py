"""AggregateIndexRule: rewrite a bare Aggregate∘Scan onto a covering index.

The filter rules only fire under a Filter node, so a full-table point
aggregate (``df.group_by(k).agg(count())``, ``df.agg(min(c))``) never
reaches an index scan — and therefore can never be answered from the
aggregate plane's persisted partials (docs/agg-serve.md). This rule
closes that gap: an ``Aggregate`` whose child is a plain source ``Scan``
rewrites onto the smallest ACTIVE covering-family index that covers all
of its input columns, after which the metadata lowering
(``pipeline_compiler.try_metadata_aggregate``) can answer every row
group from the sidecar with zero reads.

Correctness gate: the rewrite changes ROW ORDER (index data is
bucketed/sorted), so only order-insensitive aggregates are eligible —
COUNT, MIN, MAX, and integer SUM/AVG (wrapping addition is associative);
float SUM/AVG would reassociate and is left on the source scan. Hybrid
candidates (appended/deleted compensation) are excluded: the compensated
shapes are Filter-specific machinery this rule has no business building.
"""

from __future__ import annotations

from typing import List

import pyarrow as pa

from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Aggregate, LogicalPlan, Project, Scan
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.base import CandidateMap, HyperspaceRule
from hyperspace_tpu.rules.rule_utils import transform_plan_to_use_index


class AggregateIndexRule(HyperspaceRule):
    name = "AggregateIndexRule"

    index_kinds = ("CoveringIndex", "ZOrderCoveringIndex")
    # below FilterIndexRule/JoinIndexRule (50): a filter- or join-served
    # rewrite always wins when both shapes match
    base_score = 15

    def apply(self, session, plan, candidates: CandidateMap):
        if not isinstance(plan, Aggregate):
            return plan, 0
        if not session.conf.index_agg_enabled:
            return plan, 0
        projects = []
        node = plan.child
        while isinstance(node, Project):
            projects.append(node)
            node = node.child
        scan = node
        if not isinstance(scan, Scan) or scan.relation.index_info is not None:
            return plan, 0
        schema = scan.relation.schema
        for spec in plan.aggs:
            if spec.func in ("sum", "avg") and spec.column is not None:
                t = schema.get(spec.column)
                if t is None or pa.types.is_floating(t):
                    # float sums reassociate across the index's row order
                    return plan, 0
        required = {c.lower() for c in plan.input_columns}
        for p in projects:
            required |= {c.lower() for c in p.columns}
        eligible: List[IndexLogEntry] = []
        for e in candidates.get(scan, []):
            index = e.derived_dataset
            if index.kind not in self.index_kinds:
                continue
            if e.get_tag(scan, tags.HYBRIDSCAN_REQUIRED):
                continue  # appended/deleted compensation: not this rule
            covered = {c.lower() for c in index.referenced_columns()}
            if required <= covered:
                eligible.append(e)
        if not eligible:
            return plan, 0
        best = min(eligible, key=lambda e: (e.content.size_in_bytes, e.name))
        child: LogicalPlan = transform_plan_to_use_index(session, best, scan)
        for p in reversed(projects):
            child = Project(list(p.columns), child)
        return (
            Aggregate(list(plan.group_by), list(plan.aggs), child),
            self.base_score,
        )
