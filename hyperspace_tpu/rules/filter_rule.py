"""FilterIndexRule: rewrite Scan→Filter[→Project] to a covering-index scan.

Reference: ``covering/FilterIndexRule.scala:129-174`` with its filters —
``FilterPlanNodeFilter`` (:33-55, plan shape), ``FilterColumnFilter``
(:62-103, first indexed column must appear in the predicate AND the index
must cover every referenced column), ``FilterRankFilter`` /
``FilterIndexRanker`` (covering/FilterIndexRanker.scala:43-63: Hybrid Scan
→ max common bytes, else min index size). Score = 50·coverage (:151-173).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan
from hyperspace_tpu.plananalysis import filter_reasons as FR
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.base import CandidateMap, HyperspaceRule, tag_filter_reason
from hyperspace_tpu.rules.rule_utils import transform_plan_to_use_index


def _match(plan: LogicalPlan):
    """-> (project|None, filter, scan) when the plan has the target shape."""
    project = None
    node = plan
    if isinstance(node, Project):
        project = node
        node = node.child
    if not isinstance(node, Filter):
        return None
    if not isinstance(node.child, Scan):
        return None
    return project, node, node.child


class FilterIndexRule(HyperspaceRule):
    name = "FilterIndexRule"

    # which index kinds this rule consumes (IndexTypeFilter)
    index_kind = "CoveringIndex"
    # first indexed column must appear in the predicate (z-order relaxes it)
    require_first_indexed_col = True
    base_score = 50

    def apply(self, session, plan, candidates: CandidateMap):
        m = _match(plan)
        if m is None:
            return plan, 0
        project, filt, scan = m
        entries = [
            e
            for e in candidates.get(scan, [])
            if e.derived_dataset.kind == self.index_kind
        ]
        if not entries:
            return plan, 0
        eligible = self._filter_columns(project, filt, scan, entries)
        if not eligible:
            return plan, 0
        best = self._rank(scan, eligible)
        new_scan = transform_plan_to_use_index(
            session,
            best,
            scan,
            use_bucket_spec=session.conf.filter_rule_use_bucket_spec,
        )
        new_plan: LogicalPlan = Filter(filt.condition, new_scan)
        if project is not None:
            new_plan = Project(project.columns, new_plan)
        else:
            # preserve the original output column order
            new_plan = Project(plan.output, new_plan)
        return new_plan, self._score(scan, best)

    # -- FilterColumnFilter (:62-103) ---------------------------------------
    def _filter_columns(
        self,
        project: Optional[Project],
        filt: Filter,
        scan: Scan,
        entries: List[IndexLogEntry],
    ) -> List[IndexLogEntry]:
        cond_cols = {c.lower() for c in E.references(filt.condition)}
        output_cols = {
            c.lower()
            for c in (project.columns if project is not None else scan.output)
        }
        required = cond_cols | output_cols
        out = []
        for e in entries:
            index = e.derived_dataset
            indexed = [c.lower() for c in index.indexed_columns]
            covered = {c.lower() for c in index.referenced_columns()}
            if self.require_first_indexed_col:
                ok_pred = indexed[0] in cond_cols
                reason = FR.no_first_indexed_col_cond(
                    indexed[0], ",".join(sorted(cond_cols))
                )
            else:
                ok_pred = bool(set(indexed) & cond_cols)
                reason = FR.no_indexed_col_cond(
                    ",".join(indexed), ",".join(sorted(cond_cols))
                )
            if not ok_pred:
                tag_filter_reason(e, scan, reason)
                continue
            if not required <= covered:
                tag_filter_reason(
                    e,
                    scan,
                    FR.missing_required_col(
                        ",".join(sorted(required)), ",".join(sorted(covered))
                    ),
                )
                continue
            out.append(e)
        return out

    # -- FilterRankFilter / FilterIndexRanker -------------------------------
    def _rank(self, scan: Scan, entries: List[IndexLogEntry]) -> IndexLogEntry:
        def hybrid_common(e):
            return e.get_tag(scan, tags.COMMON_SOURCE_SIZE_IN_BYTES)

        if all(hybrid_common(e) is not None for e in entries):
            best = max(
                entries, key=lambda e: (hybrid_common(e), e.name)
            )
        else:
            best = min(
                entries,
                key=lambda e: (e.content.size_in_bytes, e.name),
            )
        for e in entries:
            if e is not best:
                tag_filter_reason(e, scan, FR.another_index_applied(best.name))
        return best

    # -- score (:151-173) ---------------------------------------------------
    def _score(self, scan: Scan, entry: IndexLogEntry) -> int:
        common = entry.get_tag(scan, tags.COMMON_SOURCE_SIZE_IN_BYTES)
        if common is not None and entry.source_files_size_in_bytes:
            total = entry.source_files_size_in_bytes
            return max(1, int(self.base_score * min(1.0, common / total)))
        return self.base_score
