"""Rule + filter scaffolding.

Reference: ``rules/HyperspaceRule.scala:28-91`` (template: query-plan
filters → ranker → applyIndex + score) and ``rules/IndexFilter.scala:26-110``
(``withFilterReasonTag`` instrumentation feeding ``whyNot``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.plananalysis.filter_reasons import FilterReason
from hyperspace_tpu.rules import tags

# candidate map: Scan node -> applicable index log entries
CandidateMap = Dict[Scan, List[IndexLogEntry]]


def tag_filter_reason(
    entry: IndexLogEntry, plan_key, reason: FilterReason
) -> None:
    """Record why `entry` was rejected for `plan_key` — only when analysis
    is enabled (IndexFilter.withFilterReasonTag, rules/IndexFilter.scala:26-110)."""
    if not entry.get_tag(None, tags.INDEX_PLAN_ANALYSIS_ENABLED):
        return
    reasons = entry.get_tag(plan_key, tags.FILTER_REASONS) or []
    reasons.append(reason)
    entry.set_tag(plan_key, tags.FILTER_REASONS, reasons)


class HyperspaceRule:
    """A rewrite rule: (plan, candidates) -> (new plan, score).

    Score 0 means inapplicable and new plan == plan
    (HyperspaceRule.apply:62-79; NoOpRule keeps recursion going,
    rules/NoOpRule.scala:26-41).
    """

    name = "HyperspaceRule"

    def apply(
        self, session, plan: LogicalPlan, candidates: CandidateMap
    ) -> Tuple[LogicalPlan, int]:
        return plan, 0


class NoOpRule(HyperspaceRule):
    name = "NoOpRule"
