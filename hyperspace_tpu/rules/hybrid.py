"""Hybrid Scan: serve from a slightly-stale index + compensation.

Reference: ``covering/CoveringIndexRuleUtils.scala:146-288`` —

* appended source files are scanned raw and unioned with the index scan
  (the reference's ``BucketUnion`` merge, `:256-287`; bucket alignment of
  the appended delta happens at execution time in our design since
  sharding is explicit);
* rows from deleted source files are excluded via the lineage column:
  ``Filter(Not(In(_data_file_id, deletedIds)))`` (`:244-253`) — pushed
  into the scan here (``Relation.excluded_file_ids``, applied by
  ``execution/executor._exec_scan``).

Serve side (docs/serve-pipeline.md): on a co-bucketed join the executor
prepares the appended-files delta (read + re-bucket) CONCURRENTLY with
the index-side bucket reads and caches the per-bucket parts keyed by the
delta file fingerprint (``executor._prepare_delta``), so repeated hybrid
queries on a stable appended state pay only the per-bucket merge. The
appended relation is tagged ``hybridDelta`` in its options so tooling
and tests can identify the delta scan without shape-guessing.
"""

from __future__ import annotations

import dataclasses
from typing import List

from hyperspace_tpu.constants import DATA_FILE_NAME_ID
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Project, Scan, Union
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.rule_utils import index_scan_relation


def transform_plan_to_use_hybrid_scan(
    session, entry: IndexLogEntry, scan: Scan, use_bucket_spec: bool = False
):
    appended: List[str] = entry.get_tag(scan, tags.HYBRIDSCAN_APPENDED) or []
    deleted_ids: List[int] = entry.get_tag(scan, tags.HYBRIDSCAN_DELETED) or []
    index_rel = index_scan_relation(
        session,
        entry,
        # layout survives the union: appended rows are re-bucketed at
        # execution time (executor._exec_bucketed's Union branch)
        use_bucket_spec=use_bucket_spec,
        excluded_file_ids=tuple(deleted_ids) if deleted_ids else None,
    )
    index_scan = Scan(index_rel)
    data_cols = [n for n, _ in index_rel.schema_fields if n != DATA_FILE_NAME_ID]
    if not appended:
        return Project(data_cols, index_scan)
    appended_rel = dataclasses.replace(
        scan.relation,
        files=tuple(appended),
        index_info=None,
        options=scan.relation.options + (("hybridDelta", "1"),),
    )
    return Union(
        Project(data_cols, index_scan),
        Project(data_cols, Scan(appended_rel)),
    )
