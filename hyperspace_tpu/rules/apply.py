"""ApplyHyperspace — the optimizer entry point.

Reference: ``rules/ApplyHyperspace.scala:32-76``: gated by config and a
thread-local maintenance disable (`:43`; index-maintenance scans must not
be rewritten to read the index being maintained); fetches ACTIVE log
entries, collects candidates, runs the score-based optimizer; **any
exception falls back to the original plan** (`:60-64`).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Optional

from hyperspace_tpu.constants import States
from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.rules.candidate import collect_candidates
from hyperspace_tpu.rules.score import ScoreBasedIndexPlanOptimizer
from hyperspace_tpu.telemetry import HyperspaceIndexUsageEvent

logger = logging.getLogger(__name__)

_local = threading.local()


@contextlib.contextmanager
def hyperspace_rule_disabled():
    """Thread-local guard (ApplyHyperspace.withHyperspaceRuleDisabled:68-75)."""
    prev = getattr(_local, "disabled", False)
    _local.disabled = True
    try:
        yield
    finally:
        _local.disabled = prev


def apply_hyperspace(
    session, plan: LogicalPlan, entries=None
) -> LogicalPlan:
    """Rewrite ``plan`` against the ACTIVE index entries.

    ``entries`` pins the candidate set: the concurrent serve frontend
    (``serve/frontend.py``) captures the latestStable entries ONCE at
    query admission and passes them here, so a refresh/optimize landing
    mid-query can never mix index versions inside one rewrite. None =
    read the current entries (the single-query embedding path, where
    one ``execute()`` is one snapshot anyway)."""
    if getattr(_local, "disabled", False):
        return plan
    try:
        if entries is None:
            entries = session.index_manager.get_indexes([States.ACTIVE])
        if not entries:
            return plan
        from hyperspace_tpu.plan.nodes import prune_join_columns

        plan = prune_join_columns(plan)
        candidates = collect_candidates(session, plan, entries)
        if not candidates:
            return plan
        new_plan = ScoreBasedIndexPlanOptimizer(session).apply(plan, candidates)
        if new_plan is not plan:
            used = sorted(
                {
                    leaf.relation.index_info[0]
                    for leaf in new_plan.collect_leaves()
                    if leaf.relation.index_info
                }
            )
            if used:
                session.event_logging.log_event(
                    HyperspaceIndexUsageEvent(
                        index_names=used, plan=new_plan.pretty()
                    )
                )
        return new_plan
    # catch-all is the contract (reference ApplyHyperspace :60-64): a
    # rewrite failure must degrade to the original plan, never the query
    except Exception:  # hslint: disable=HS402
        logger.exception("Hyperspace plan rewrite failed; using original plan")
        return plan
