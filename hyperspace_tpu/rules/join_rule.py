"""JoinIndexRule — rewrite an equi-join so BOTH sides read co-bucketed
covering indexes, eliminating the join shuffle.

Reference: ``covering/JoinIndexRule.scala`` (720 LoC; the headline rule):

* eligibility — inner sort-merge-joinable shape (`:122-125`), *linear*
  children (each side is a Scan/Filter/Project chain, `:150-151`),
  conjunctive equi-conditions (`:164-170`), one-to-one left/right
  attribute mapping (``JoinAttributeFilter.ensureAttributeRequirements
  :262-301``);
* candidates — per side, indexes whose **indexed columns equal the join
  columns exactly** and which cover every referenced column
  (``JoinColumnFilter.getUsableIndexes:434-463``);
* ranking — prefer pairs with equal bucket counts (shuffle-free zip),
  then common-bytes/hybrid (``JoinIndexRanker.rank:52-89``);
* score — 70·coverage per side (`:689-719`).

Execution-side payoff: both index relations carry ``bucket_spec``; the
executor zips equal buckets pairwise (``execution/executor._exec_join``) —
the TPU-shaped equivalent of Spark SMJ over co-bucketed scans with no
Exchange (``JoinIndexRule.scala:619-634``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan import expressions as E
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan
from hyperspace_tpu.plananalysis import filter_reasons as FR
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.base import CandidateMap, HyperspaceRule, tag_filter_reason
from hyperspace_tpu.rules.rule_utils import transform_plan_to_use_index


class _Side:
    """A linear join child: Project*/Filter* chain over one Scan."""

    def __init__(self, root: LogicalPlan):
        self.root = root
        self.scan: Optional[Scan] = None
        self.filter_refs: set = set()
        node = root
        while True:
            if isinstance(node, Scan):
                self.scan = node
                break
            if isinstance(node, (Project, Filter)):
                if isinstance(node, Filter):
                    self.filter_refs |= E.references(node.condition)
                node = node.child
                continue
            break  # non-linear (join/union below) -> ineligible

    @property
    def ok(self) -> bool:
        return self.scan is not None

    def required_columns(self) -> set:
        return {c.lower() for c in self.root.output} | {
            c.lower() for c in self.filter_refs
        }

    def rebuilt_with(self, new_scan: LogicalPlan) -> LogicalPlan:
        old_scan = self.scan

        def swap(node):
            return new_scan if node is old_scan else node

        return self.root.transform_up(swap)


class JoinIndexRule(HyperspaceRule):
    name = "JoinIndexRule"
    base_score_per_side = 70

    def apply(self, session, plan, candidates: CandidateMap):
        if not isinstance(plan, Join):
            return plan, 0
        pairs = E.equi_join_pairs(plan.condition)
        if not pairs:
            return plan, 0
        left, right = _Side(plan.left), _Side(plan.right)
        if not (left.ok and right.ok):
            return plan, 0
        mapping = self._attribute_mapping(plan, pairs, left, right)
        if mapping is None:
            return plan, 0
        lcols, rcols = mapping
        l_best = self._usable(session, left, lcols, candidates)
        r_best = self._usable(session, right, rcols, candidates)
        if not l_best or not r_best:
            return plan, 0
        l_entry, r_entry = self._rank_pair(left.scan, right.scan, l_best, r_best)
        new_left = left.rebuilt_with(
            transform_plan_to_use_index(
                session, l_entry, left.scan, use_bucket_spec=True
            )
        )
        new_right = right.rebuilt_with(
            transform_plan_to_use_index(
                session, r_entry, right.scan, use_bucket_spec=True
            )
        )
        # Restore each side's original schema: the index scan may add columns
        # (e.g. the lineage column) that must not surface in the Join output
        # (CoveringIndexRuleUtils filters updatedOutput to the relation's
        # original attributes).
        if list(new_left.output) != list(plan.left.output):
            new_left = Project(plan.left.output, new_left)
        if list(new_right.output) != list(plan.right.output):
            new_right = Project(plan.right.output, new_right)
        score = self._score(left.scan, l_entry) + self._score(right.scan, r_entry)
        return Join(new_left, new_right, plan.condition, plan.how), score

    # -- attribute one-to-one mapping (:262-301) ---------------------------
    def _attribute_mapping(self, plan: Join, pairs, left: _Side, right: _Side):
        l_out = {c.lower() for c in plan.left.output}
        r_out = {c.lower() for c in plan.right.output}
        l2r: Dict[str, str] = {}
        r2l: Dict[str, str] = {}
        lcols: List[str] = []
        rcols: List[str] = []
        for a, b in pairs:
            al, bl = a.lower(), b.lower()
            if al in l_out and bl in r_out:
                lc, rc = al, bl
            elif bl in l_out and al in r_out:
                lc, rc = bl, al
            else:
                return None
            # one-to-one: a left column maps to exactly one right column
            if l2r.setdefault(lc, rc) != rc or r2l.setdefault(rc, lc) != lc:
                return None
            if lc not in lcols:
                lcols.append(lc)
                rcols.append(rc)
        return lcols, rcols

    # -- usable indexes per side (:434-463) ---------------------------------
    def _usable(
        self,
        session,
        side: _Side,
        join_cols: List[str],
        candidates: CandidateMap,
    ) -> List[IndexLogEntry]:
        entries = [
            e
            for e in candidates.get(side.scan, [])
            if e.derived_dataset.kind == "CoveringIndex"
        ]
        required = side.required_columns()
        out = []
        for e in entries:
            index = e.derived_dataset
            indexed = [c.lower() for c in index.indexed_columns]
            covered = {c.lower() for c in index.referenced_columns()}
            if set(indexed) != set(join_cols):
                tag_filter_reason(
                    e,
                    side.scan,
                    FR.not_eligible_join(
                        f"indexed columns {indexed} != join columns {join_cols}"
                    ),
                )
                continue
            if not required <= covered:
                tag_filter_reason(
                    e,
                    side.scan,
                    FR.missing_required_col(
                        ",".join(sorted(required)), ",".join(sorted(covered))
                    ),
                )
                continue
            out.append(e)
        return out

    # -- pair ranking (JoinIndexRanker.rank:52-89) --------------------------
    def _rank_pair(self, l_scan, r_scan, l_entries, r_entries):
        def common(scan, e):
            v = e.get_tag(scan, tags.COMMON_SOURCE_SIZE_IN_BYTES)
            return v if v is not None else e.source_files_size_in_bytes

        best = None
        best_key = None
        for le in l_entries:
            for re in r_entries:
                lb = getattr(le.derived_dataset, "num_buckets", 0)
                rb = getattr(re.derived_dataset, "num_buckets", 0)
                key = (
                    0 if lb == rb else 1,  # equal bucket counts first
                    -(common(l_scan, le) + common(r_scan, re)),
                    le.name,
                    re.name,
                )
                if best_key is None or key < best_key:
                    best, best_key = (le, re), key
        return best

    def _score(self, scan, entry: IndexLogEntry) -> int:
        common = entry.get_tag(scan, tags.COMMON_SOURCE_SIZE_IN_BYTES)
        if common is not None and entry.source_files_size_in_bytes:
            ratio = min(1.0, common / entry.source_files_size_in_bytes)
            return max(1, int(self.base_score_per_side * ratio))
        return self.base_score_per_side
