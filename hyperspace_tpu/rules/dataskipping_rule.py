"""ApplyDataSkippingIndex — prune source files via the sketch table.

Reference: ``dataskipping/rules/ApplyDataSkippingIndex.scala:33-105`` +
``FilterConditionFilter`` (translate the predicate, tag it) +
``DataSkippingIndexRanker``. Score = 1, so any covering-index rewrite wins
(`:76-83`). The rewritten plan scans the SAME source relation with a
reduced file list (the reference's ``DataSkippingFileIndex`` evaluates the
translated predicate against the sketch and collects surviving paths
driver-side, ``DataSkippingFileIndex.scala:49-56``; we evaluate at rewrite
time — the sketch table is one row per file).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from hyperspace_tpu.constants import DATA_FILE_NAME_ID
from hyperspace_tpu.io import parquet as pio
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan
from hyperspace_tpu.plananalysis import filter_reasons as FR
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.base import CandidateMap, HyperspaceRule, tag_filter_reason
from hyperspace_tpu.rules.filter_rule import _match


import functools


@functools.lru_cache(maxsize=32)
def _load_sketch_table(files: tuple):
    """Sketch tables are immutable per log-entry content (new versions get
    new file paths), so cache the parquet read across queries — the rule
    runs inside every optimizer pass."""
    return pio.read_table(list(files), None)


class ApplyDataSkippingIndex(HyperspaceRule):
    name = "ApplyDataSkippingIndex"
    base_score = 1

    def apply(self, session, plan, candidates: CandidateMap):
        m = _match(plan)
        if m is None:
            return plan, 0
        project, filt, scan = m
        entries = [
            e
            for e in candidates.get(scan, [])
            if e.derived_dataset.kind == "DataSkippingIndex"
        ]
        best: Optional[IndexLogEntry] = None
        best_files: Optional[List[str]] = None
        for e in sorted(entries, key=lambda e: e.name):
            files = self._pruned_files(session, e, scan, filt)
            if files is None:
                continue
            if best_files is None or len(files) < len(best_files):
                best, best_files = e, files
        if best is None:
            return plan, 0
        appended = best.get_tag(scan, tags.HYBRIDSCAN_APPENDED) or []
        # A file modified in place appears BOTH in the (stale) sketch keep
        # list and in the appended tag — scan it once, unpruned, via the
        # appended list only.
        appended_set = set(appended)
        pruned = [p for p in best_files if p not in appended_set]
        new_rel = dataclasses.replace(
            scan.relation,
            files=tuple(pruned) + tuple(appended),
            index_info=(best.name, best.id, best.derived_dataset.kind_abbr),
        )
        new_plan: LogicalPlan = Filter(filt.condition, Scan(new_rel))
        new_plan = Project(
            project.columns if project is not None else plan.output, new_plan
        )
        return new_plan, self.base_score

    def _pruned_files(self, session, entry, scan, filt) -> Optional[List[str]]:
        index = entry.derived_dataset
        if not entry.content.files:
            return None
        sketch_table = _load_sketch_table(tuple(entry.content.files))
        mask = index.translate_filter(filt.condition, sketch_table)
        if mask is None:
            tag_filter_reason(
                entry,
                scan,
                FR.ineligible_predicate(
                    f"no sketch matches predicate {filt.condition!r}"
                ),
            )
            return None
        ids = np.asarray(sketch_table.column(DATA_FILE_NAME_ID))
        keep_ids = set(ids[mask].tolist())
        id_to_path = {
            info.id: path
            for path, info in entry.relation.content.file_infos
        }
        current = set(scan.relation.files)
        out = [
            p
            for fid, p in sorted(id_to_path.items())
            if fid in keep_ids and p in current
        ]
        return out
