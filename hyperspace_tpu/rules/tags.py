"""Typed tag keys on (log entry, plan node) pairs.

Reference: ``index/IndexLogEntryTags.scala:1-85``. Tags carry per-plan
candidate-evaluation results (Hybrid Scan requirements, common bytes,
whyNot reasons) from the candidate filters to the ranking/rewrite stages
without mutating shared state.
"""

COMMON_SOURCE_SIZE_IN_BYTES = "commonSourceSizeInBytes"
HYBRIDSCAN_REQUIRED = "hybridScanRequired"
HYBRIDSCAN_APPENDED = "hybridScanAppendedFiles"
HYBRIDSCAN_DELETED = "hybridScanDeletedFileIds"
FILTER_REASONS = "filterReasons"
INDEX_PLAN_ANALYSIS_ENABLED = "indexPlanAnalysisEnabled"
DATASKIPPING_INDEX_PREDICATE = "dataskippingIndexPredicate"
