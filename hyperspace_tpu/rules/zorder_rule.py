"""ZOrderFilterIndexRule.

Reference: ``zordercovering/ZOrderFilterIndexRule.scala:36-153`` — the
FilterIndexRule variant for z-order covering indexes: ANY indexed column
(not only the first) may appear in the predicate, and no bucketSpec is
attached (z-order files are range-laid-out, not hash-bucketed).
"""

from __future__ import annotations

from hyperspace_tpu.rules.filter_rule import FilterIndexRule


class ZOrderFilterIndexRule(FilterIndexRule):
    # The class attributes fully specialize the parent pipeline; z-order
    # relations never get a bucketSpec because ZOrderCoveringIndex has no
    # num_buckets (index_scan_relation checks hasattr).
    name = "ZOrderFilterIndexRule"
    index_kind = "ZOrderCoveringIndex"
    require_first_indexed_col = False
    base_score = 50
