"""Candidate index collection: which ACTIVE indexes could serve each Scan.

Reference: ``rules/CandidateIndexCollector.scala:28-60`` — per source leaf
relation apply ``ColumnSchemaFilter`` (index's referenced cols ⊆ relation
cols, rules/ColumnSchemaFilter.scala:28-44) then ``FileSignatureFilter``
(exact signature equality, or Hybrid Scan candidacy with appended/deleted
byte-ratio thresholds, rules/FileSignatureFilter.scala:33-192).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.metadata.entry import FileInfo, IndexLogEntry
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.plananalysis import filter_reasons as FR
from hyperspace_tpu.rules import tags
from hyperspace_tpu.rules.base import CandidateMap, tag_filter_reason
from hyperspace_tpu.utils import resolver


def _current_file_infos(session, scan: Scan) -> Dict[str, FileInfo]:
    """path -> FileInfo for the scan's snapshot, via the source provider SPI
    so snapshot-based sources (Delta/Iceberg) report their own file view."""
    rel = session.source_manager.get_relation(scan.relation)
    return {
        path: FileInfo(os.path.basename(path), size, mtime, -1)
        for path, size, mtime in rel.all_file_infos()
    }


def column_schema_filter(
    scan: Scan, entries: List[IndexLogEntry]
) -> List[IndexLogEntry]:
    """Index's referenced columns must all resolve against the relation
    (ColumnSchemaFilter.scala:28-44)."""
    out = []
    cols = scan.relation.column_names
    for e in entries:
        refs = e.derived_dataset.referenced_columns()
        if resolver.resolve(refs, cols) is not None:
            out.append(e)
        else:
            tag_filter_reason(
                e, scan, FR.col_schema_mismatch(",".join(refs), ",".join(cols))
            )
    return out


def file_signature_filter(
    session, scan: Scan, entries: List[IndexLogEntry]
) -> List[IndexLogEntry]:
    """Exact-signature mode, or Hybrid Scan candidacy
    (FileSignatureFilter.scala:49-191). Time-travel sources first swap each
    entry for the historical index version closest to the queried source
    version (``closestIndex``, DeltaLakeRelation.scala:179-251)."""
    hybrid = session.conf.hybrid_scan_enabled
    provider_rel = session.source_manager.get_relation(scan.relation)
    entries = [provider_rel.closest_index(e) or e for e in entries]
    out = []
    for e in entries:
        if hybrid:
            ok = _hybrid_scan_candidate(session, scan, e)
        else:
            ok = _signature_valid(session, scan, e)
            if ok and e.has_source_update:
                # Quick-refreshed entry: fingerprint matches the new source
                # but the DATA covers only the original snapshot — accept
                # and compensate at rewrite time from the recorded Update
                # delta (the reference's exact-mode quick-refresh path,
                # CoveringIndexRuleUtils.scala:74-79,164-170).
                ok = _tag_update_compensation(scan, e)
            if not ok:
                tag_filter_reason(e, scan, FR.source_data_changed())
        if ok:
            out.append(e)
    return out


def _signature_valid(session, scan: Scan, entry: IndexLogEntry) -> bool:
    """Stored file-based signature == recomputed one
    (FileSignatureFilter.signatureValid:70-88)."""
    from hyperspace_tpu.signatures import FileBasedSignatureProvider

    provider = FileBasedSignatureProvider(session.source_manager)
    current = provider.sign(scan)
    for sig in entry.fingerprint.signatures:
        if sig.provider == FileBasedSignatureProvider.name:
            return sig.value == current
    return False


def _tag_update_compensation(scan: Scan, entry: IndexLogEntry) -> bool:
    """Set the Hybrid-Scan compensation tags from a quick refresh's recorded
    Update delta (no file diffing needed — the delta is in the metadata).
    Returns False (reject) for recorded deletes on a lineage-less index —
    there is no way to exclude the dead rows."""
    upd = entry.relation.update
    appended = (
        [p for p, _ in upd.appended_files.file_infos] if upd.appended_files else []
    )
    deleted_ids = (
        [i.id for _, i in upd.deleted_files.file_infos if i.id != -1]
        if upd.deleted_files
        else []
    )
    has_deletes = upd.deleted_files is not None and bool(
        upd.deleted_files.files
    )
    if has_deletes and not entry.derived_dataset.can_handle_deleted_files:
        tag_filter_reason(entry, scan, FR.no_delete_support())
        return False
    entry.set_tag(
        scan, tags.COMMON_SOURCE_SIZE_IN_BYTES, entry.relation.content.size_in_bytes
    )
    entry.set_tag(scan, tags.HYBRIDSCAN_REQUIRED, True)
    entry.set_tag(scan, tags.HYBRIDSCAN_APPENDED, appended)
    entry.set_tag(scan, tags.HYBRIDSCAN_DELETED, deleted_ids)
    return True


def _hybrid_scan_candidate(session, scan: Scan, entry: IndexLogEntry) -> bool:
    """File-level diff against the indexed snapshot; tags the common-bytes
    and hybrid-required info used by ranking and the rewrite
    (FileSignatureFilter.getHybridScanCandidate:108-191)."""
    current = _current_file_infos(session, scan)
    # Diff against what the index DATA covers (the build-time snapshot,
    # relation.content) — NOT the update-adjusted metadata view: a quick
    # refresh moves the metadata forward while the data stays put, and the
    # compensation must cover exactly that gap.
    indexed = dict(entry.relation.content.file_infos)

    common_paths = []
    appended = []
    for path, info in current.items():
        known = indexed.get(path)
        if known is not None and known.size == info.size and (
            known.modified_time == info.modified_time
        ):
            common_paths.append(path)
        else:
            appended.append((path, info))
    deleted = [
        (p, i) for p, i in indexed.items() if p not in current
        or current[p].size != i.size
        or current[p].modified_time != i.modified_time
    ]

    common_bytes = sum(indexed[p].size for p in common_paths)
    appended_bytes = sum(i.size for _, i in appended)
    deleted_bytes = sum(i.size for _, i in deleted)
    total_current = common_bytes + appended_bytes
    index_source_bytes = common_bytes + deleted_bytes

    if common_bytes == 0:
        tag_filter_reason(entry, scan, FR.source_data_changed())
        return False
    appended_ratio = appended_bytes / total_current if total_current else 0.0
    deleted_ratio = deleted_bytes / index_source_bytes if index_source_bytes else 0.0
    max_appended = session.conf.hybrid_scan_max_appended_ratio
    max_deleted = session.conf.hybrid_scan_max_deleted_ratio
    if appended_ratio > max_appended:
        tag_filter_reason(
            entry, scan, FR.too_much_appended(appended_ratio, max_appended)
        )
        return False
    if deleted:
        if not entry.derived_dataset.can_handle_deleted_files:
            tag_filter_reason(entry, scan, FR.no_delete_support())
            return False
        if deleted_ratio > max_deleted:
            tag_filter_reason(
                entry, scan, FR.too_much_deleted(deleted_ratio, max_deleted)
            )
            return False

    entry.set_tag(scan, tags.COMMON_SOURCE_SIZE_IN_BYTES, common_bytes)
    entry.set_tag(
        scan, tags.HYBRIDSCAN_REQUIRED, bool(appended or deleted)
    )
    entry.set_tag(scan, tags.HYBRIDSCAN_APPENDED, [p for p, _ in appended])
    # deleted file ids come from the indexed snapshot's lineage ids
    deleted_ids = [i.id for _, i in deleted if i.id != -1]
    entry.set_tag(scan, tags.HYBRIDSCAN_DELETED, deleted_ids)
    return True


def collect_candidates(
    session, plan: LogicalPlan, entries: List[IndexLogEntry]
) -> CandidateMap:
    """CandidateIndexCollector.apply:49-59."""
    out: CandidateMap = {}
    for scan in plan.collect_leaves():
        step1 = column_schema_filter(scan, entries)
        step2 = file_signature_filter(session, scan, step1)
        if step2:
            out[scan] = step2
    return out
