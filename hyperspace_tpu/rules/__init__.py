"""Query optimizer extension (L5): index-aware plan rewriting.

Reference: ``index/rules/`` + per-index-kind rules. The pipeline
(``ApplyHyperspace.apply``, rules/ApplyHyperspace.scala:45-66):

1. fetch all ACTIVE index log entries (TTL-cached),
2. per source Scan, collect *candidates* (schema filter + signature /
   Hybrid-Scan filter — ``CandidateIndexCollector``),
3. run the score-based optimizer over the whole plan
   (``ScoreBasedIndexPlanOptimizer``) trying FilterIndexRule,
   JoinIndexRule, z-order and data-skipping rules, keeping the max-score
   rewrite; any exception falls back to the original plan.
"""

from hyperspace_tpu.rules.apply import apply_hyperspace, hyperspace_rule_disabled

__all__ = ["apply_hyperspace", "hyperspace_rule_disabled"]
