"""Score-based plan optimizer.

Reference: ``rules/ScoreBasedIndexPlanOptimizer.scala:31-81`` — a
recursive, memoized search: at every node, either some rule rewrites the
subtree (its score), or the children are optimized independently (sum of
child scores); keep the max. The rule set mirrors `:32-33`:
{FilterIndexRule, JoinIndexRule, ApplyDataSkippingIndex,
ZOrderFilterIndexRule, NoOpRule}.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.rules.base import CandidateMap, HyperspaceRule, NoOpRule


def _all_rules() -> List[HyperspaceRule]:
    from hyperspace_tpu.rules.filter_rule import FilterIndexRule

    rules: List[HyperspaceRule] = [FilterIndexRule()]
    try:
        from hyperspace_tpu.rules.join_rule import JoinIndexRule

        rules.append(JoinIndexRule())
    except ImportError:
        pass
    try:
        from hyperspace_tpu.rules.zorder_rule import ZOrderFilterIndexRule

        rules.append(ZOrderFilterIndexRule())
    except ImportError:
        pass
    try:
        from hyperspace_tpu.rules.dataskipping_rule import ApplyDataSkippingIndex

        rules.append(ApplyDataSkippingIndex())
    except ImportError:
        pass
    try:
        from hyperspace_tpu.rules.agg_rule import AggregateIndexRule

        rules.append(AggregateIndexRule())
    except ImportError:
        pass
    rules.append(NoOpRule())
    return rules


class ScoreBasedIndexPlanOptimizer:
    def __init__(self, session):
        self.session = session
        self.rules = _all_rules()

    def apply(self, plan: LogicalPlan, candidates: CandidateMap) -> LogicalPlan:
        best, _score = self.apply_with_score(plan, candidates)
        return best

    def apply_with_score(
        self, plan: LogicalPlan, candidates: CandidateMap
    ) -> Tuple[LogicalPlan, int]:
        """The search result WITH its winning score — the what-if
        advisor's comparison primitive (``advisor/whatif.py``): score a
        plan against the active candidate set, then again with a
        hypothetical entry injected; the score delta is the candidate's
        predicted usefulness on that plan, by the exact machinery serve
        rewrites run through (never a parallel cost model)."""
        self._memo: Dict[int, Tuple[LogicalPlan, int]] = {}
        return self._rec_apply(plan, candidates)

    def _rec_apply(
        self, plan: LogicalPlan, candidates: CandidateMap
    ) -> Tuple[LogicalPlan, int]:
        key = id(plan)
        if key in self._memo:
            return self._memo[key]
        # Option A: optimize children independently
        best_plan, best_score = plan, 0
        if plan.children:
            new_children = []
            child_score = 0
            for c in plan.children:
                p, s = self._rec_apply(c, candidates)
                new_children.append(p)
                child_score += s
            if child_score > 0:
                best_plan, best_score = plan.with_children(new_children), child_score
        # Option B: a rule rewrites this subtree wholesale
        for rule in self.rules:
            p, s = rule.apply(self.session, plan, candidates)
            if s > best_score:
                best_plan, best_score = p, s
        self._memo[key] = (best_plan, best_score)
        return best_plan, best_score
