"""Plan-transformation helpers shared by the covering-index rules.

Reference: ``covering/CoveringIndexRuleUtils.scala:35-418`` — swap a source
relation for the index's data (index-only scan), or build the Hybrid Scan
compensation plan (appended files merged bucket-aligned, deleted rows
excluded via lineage NOT-IN).
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

import pyarrow as pa

from hyperspace_tpu.constants import DATA_FILE_NAME_ID
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.metadata.entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Relation as PlanRelation
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.rules import tags


def parse_arrow_type(s: str) -> pa.DataType:
    """Inverse of ``str(pa.DataType)`` for the types we persist in
    schemaJson (covering_build.create_covering_index)."""
    try:
        return pa.type_for_alias(s)
    except ValueError:
        pass
    if s.startswith("timestamp["):
        inner = s[len("timestamp[") : -1]
        if "," in inner:
            unit, tz = inner.split(",", 1)
            tz = tz.split("=", 1)[1].strip() if "=" in tz else tz.strip()
            return pa.timestamp(unit.strip(), tz)
        return pa.timestamp(inner.strip())
    if s.startswith("time32["):
        return pa.time32(s[len("time32[") : -1])
    if s.startswith("time64["):
        return pa.time64(s[len("time64[") : -1])
    if s.startswith("dictionary"):
        return pa.string()
    raise HyperspaceException(f"Cannot parse arrow type {s!r}")


def index_schema_fields(entry: IndexLogEntry) -> Tuple[Tuple[str, pa.DataType], ...]:
    pairs = json.loads(entry.derived_dataset.schema_json)
    return tuple((name, parse_arrow_type(t)) for name, t in pairs)


def index_scan_relation(
    session,
    entry: IndexLogEntry,
    use_bucket_spec: bool = False,
    excluded_file_ids: Optional[Tuple[int, ...]] = None,
) -> PlanRelation:
    """The relation that reads the index data instead of the source
    (transformPlanToUseIndexOnlyScan:98-130; display string mirrors
    ``IndexHadoopFsRelation`` ``Hyperspace(Type: CI, Name: …, LogVersion: …)``)."""
    index = entry.derived_dataset
    bucket_spec = None
    if use_bucket_spec and hasattr(index, "num_buckets"):
        bucket_spec = (index.num_buckets, tuple(index.indexed_columns))
    return PlanRelation(
        root_paths=tuple(sorted({_version_root(f) for f in entry.content.files})),
        files=tuple(entry.content.files),
        fmt="parquet",
        schema_fields=index_schema_fields(entry),
        index_info=(entry.name, entry.id, index.kind_abbr),
        excluded_file_ids=excluded_file_ids,
        bucket_spec=bucket_spec,
    )


def _version_root(path: str) -> str:
    return path.rsplit("/", 1)[0]


def transform_plan_to_use_index(
    session, entry: IndexLogEntry, scan: Scan, use_bucket_spec: bool = False
):
    """Replace `scan` with the index scan; Hybrid Scan compensation when the
    candidate filter tagged appended/deleted files
    (transformPlanToUseIndex:55-83 → index-only :98-130 / hybrid :146-288)."""
    hybrid_required = entry.get_tag(scan, tags.HYBRIDSCAN_REQUIRED)
    if not hybrid_required:
        return Scan(index_scan_relation(session, entry, use_bucket_spec))
    from hyperspace_tpu.rules.hybrid import transform_plan_to_use_hybrid_scan

    return transform_plan_to_use_hybrid_scan(session, entry, scan, use_bucket_spec)
