"""Typed config system.

Reference: ``util/HyperspaceConf.scala:27-238`` — typed accessors over flat
string-keyed Spark SQL confs. Here the session owns a plain dict; this
module provides the same typed accessor surface plus defaults from
:mod:`hyperspace_tpu.constants`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from hyperspace_tpu import constants as C


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


class Config:
    """Flat key→value config with typed accessors and change tracking.

    ``version`` increments on every mutation; caches keyed on config state
    (reference ``util/CacheWithTransform.scala``) compare it to decide
    invalidation.
    """

    def __init__(self, initial: Optional[dict] = None):
        self._values: dict = dict(initial or {})
        self.version = 0

    # -- raw access ---------------------------------------------------------
    def set(self, key: str, value: Any) -> None:
        self._values[key] = value
        self.version += 1

    def unset(self, key: str) -> None:
        if key in self._values:
            del self._values[key]
            self.version += 1

    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def get_bool(self, key: str, default: bool = False) -> bool:
        return _to_bool(self._values.get(key, default))

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self._values.get(key, default))

    def get_float(self, key: str, default: float = 0.0) -> float:
        return float(self._values.get(key, default))

    def get_str(self, key: str, default: str = "") -> str:
        return str(self._values.get(key, default))

    def prefixed(self, prefix: str) -> dict:
        """All ``{key: value}`` pairs whose key starts with ``prefix`` —
        the fault-injection registry (``testing/faults.py``) scans
        ``hyperspace.faults.*`` through this without reaching into the
        private value dict. Iterates a snapshot: a concurrent ``set()``
        of a new key (serve workers share one conf) must not blow up
        the iteration."""
        return {
            k: v
            for k, v in list(self._values.items())
            if k.startswith(prefix)
        }

    # -- typed accessors (HyperspaceConf.scala) -----------------------------
    @property
    def apply_enabled(self) -> bool:
        return self.get_bool(
            C.HYPERSPACE_APPLY_ENABLED, C.HYPERSPACE_APPLY_ENABLED_DEFAULT
        )

    @property
    def num_buckets(self) -> int:
        return self.get_int(C.INDEX_NUM_BUCKETS, C.INDEX_NUM_BUCKETS_DEFAULT)

    @property
    def profile_trace_dir(self) -> str:
        return self.get_str(C.PROFILE_TRACE_DIR, C.PROFILE_TRACE_DIR_DEFAULT)

    @property
    def explain_display_mode(self) -> str:
        return self.get_str(
            C.EXPLAIN_DISPLAY_MODE, C.EXPLAIN_DISPLAY_MODE_DEFAULT
        )

    @property
    def build_memory_budget(self) -> int:
        """Max bytes materialized per build wave (0 = unbounded)."""
        return self.get_int(
            C.INDEX_BUILD_MEMORY_BUDGET, C.INDEX_BUILD_MEMORY_BUDGET_DEFAULT
        )

    @property
    def build_partition_first(self) -> bool:
        """Partition-then-sort build pipeline (bit-identical to the
        global lexsort it replaces; False = legacy path)."""
        return self.get_bool(
            C.INDEX_BUILD_PARTITION_FIRST,
            C.INDEX_BUILD_PARTITION_FIRST_DEFAULT,
        )

    @property
    def build_num_shards(self) -> int:
        """Device shards for the build plane (0 = the whole session
        mesh); a positive value caps the build mesh to the first N
        devices."""
        return self.get_int(C.BUILD_NUM_SHARDS, C.BUILD_NUM_SHARDS_DEFAULT)

    @property
    def build_exchange_strategy(self) -> str:
        """Exchange strategy of the build's bucket shuffle
        (``parallel/shuffle.py``): ``auto`` | ``flat`` | ``compact`` |
        ``host`` | ``twostage`` — all bit-identical; ``auto`` resolves
        per topology (see ``shuffle.resolve_strategy``)."""
        return self.get_str(
            C.BUILD_EXCHANGE_STRATEGY, C.BUILD_EXCHANGE_STRATEGY_DEFAULT
        )

    @property
    def build_exchange_twostage_hosts(self) -> int:
        """Simulated host count for the twostage exchange on a
        single-process mesh (0 = derive from the process count)."""
        return self.get_int(
            C.BUILD_EXCHANGE_TWOSTAGE_HOSTS,
            C.BUILD_EXCHANGE_TWOSTAGE_HOSTS_DEFAULT,
        )

    @property
    def build_sharded_tail(self) -> bool:
        """Device-local build/serve tail on a >1-device mesh: per-shard
        sort + write and per-shard join prepare/merge, union at the
        edge (bit-identical to the single-tail path; False = old path)."""
        return self.get_bool(
            C.BUILD_SHARDED_TAIL_ENABLED,
            C.BUILD_SHARDED_TAIL_ENABLED_DEFAULT,
        )

    @property
    def lineage_enabled(self) -> bool:
        return self.get_bool(
            C.INDEX_LINEAGE_ENABLED, C.INDEX_LINEAGE_ENABLED_DEFAULT
        )

    @property
    def hybrid_scan_enabled(self) -> bool:
        return self.get_bool(
            C.INDEX_HYBRID_SCAN_ENABLED, C.INDEX_HYBRID_SCAN_ENABLED_DEFAULT
        )

    @property
    def hybrid_scan_max_appended_ratio(self) -> float:
        return self.get_float(
            C.INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO,
            C.INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO_DEFAULT,
        )

    @property
    def hybrid_scan_max_deleted_ratio(self) -> float:
        return self.get_float(
            C.INDEX_HYBRID_SCAN_MAX_DELETED_RATIO,
            C.INDEX_HYBRID_SCAN_MAX_DELETED_RATIO_DEFAULT,
        )

    @property
    def filter_rule_use_bucket_spec(self) -> bool:
        return self.get_bool(
            C.INDEX_FILTER_RULE_USE_BUCKET_SPEC,
            C.INDEX_FILTER_RULE_USE_BUCKET_SPEC_DEFAULT,
        )

    @property
    def optimize_file_size_threshold(self) -> int:
        return self.get_int(
            C.OPTIMIZE_FILE_SIZE_THRESHOLD, C.OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT
        )

    @property
    def cache_expiry_seconds(self) -> int:
        return self.get_int(
            C.INDEX_CACHE_EXPIRY_SECONDS, C.INDEX_CACHE_EXPIRY_SECONDS_DEFAULT
        )

    @property
    def source_provider_builders(self) -> list:
        raw = self.get_str(
            C.INDEX_SOURCES_PROVIDERS, C.INDEX_SOURCES_PROVIDERS_DEFAULT
        )
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def device_filter_min_rows(self) -> int:
        return self.get_int(
            C.EXECUTION_DEVICE_FILTER_MIN_ROWS,
            C.EXECUTION_DEVICE_FILTER_MIN_ROWS_DEFAULT,
        )

    @property
    def device_join_min_rows(self) -> int:
        return self.get_int(
            C.EXECUTION_DEVICE_JOIN_MIN_ROWS,
            C.EXECUTION_DEVICE_JOIN_MIN_ROWS_DEFAULT,
        )

    @property
    def support_nested_fields(self) -> bool:
        return self.get_bool(
            C.INDEX_SUPPORT_NESTED_FIELDS,
            C.INDEX_SUPPORT_NESTED_FIELDS_DEFAULT,
        )

    @property
    def index_agg_enabled(self) -> bool:
        """Aggregate index plane (docs/agg-serve.md): sidecar capture of
        partial-aggregate state at build time, the serve-side metadata
        lowering, and the AggregateIndexRule rewrite."""
        return self.get_bool(C.INDEX_AGG_ENABLED, C.INDEX_AGG_ENABLED_DEFAULT)

    @property
    def index_agg_max_groups(self) -> int:
        """Per-row-group distinct-value cap for grouped-partial capture."""
        return max(
            0, self.get_int(C.INDEX_AGG_MAX_GROUPS, C.INDEX_AGG_MAX_GROUPS_DEFAULT)
        )

    @property
    def index_agg_sample_rows(self) -> int:
        """Stratified-sample rows captured per row group (0 = none)."""
        return max(
            0,
            self.get_int(C.INDEX_AGG_SAMPLE_ROWS, C.INDEX_AGG_SAMPLE_ROWS_DEFAULT),
        )

    @property
    def serve_approx_enabled(self) -> bool:
        """Explicit opt-in for sample-based approximate aggregates
        (``DataFrame.collect_approx``); never substituted for exact."""
        return self.get_bool(
            C.SERVE_APPROX_ENABLED, C.SERVE_APPROX_ENABLED_DEFAULT
        )

    @property
    def serve_approx_max_rel_error(self) -> float:
        """Widest acceptable 95%-CI half-width relative to the estimate."""
        return max(
            0.0,
            self.get_float(
                C.SERVE_APPROX_MAX_REL_ERROR, C.SERVE_APPROX_MAX_REL_ERROR_DEFAULT
            ),
        )

    @property
    def serve_cache_enabled(self) -> bool:
        return self.get_bool(
            C.SERVE_CACHE_ENABLED, C.SERVE_CACHE_ENABLED_DEFAULT
        )

    @property
    def serve_cache_max_bytes(self) -> int:
        return self.get_int(
            C.SERVE_CACHE_MAX_BYTES, C.SERVE_CACHE_MAX_BYTES_DEFAULT
        )

    @property
    def serve_stream_enabled(self) -> bool:
        """Streaming per-bucket join serve (docs/out-of-core.md):
        prepared sides flow wave-by-wave under the stream byte budget
        instead of materializing whole; bit-identical to the
        materializing path."""
        return self.get_bool(
            C.SERVE_STREAM_ENABLED, C.SERVE_STREAM_ENABLED_DEFAULT
        )

    @property
    def serve_stream_max_bytes(self) -> int:
        """Wave budget: estimated decoded bytes of prepared buckets in
        flight at once on the streaming join path."""
        return max(
            1,
            self.get_int(
                C.SERVE_STREAM_MAX_BYTES, C.SERVE_STREAM_MAX_BYTES_DEFAULT
            ),
        )

    @property
    def serve_spill_max_bytes(self) -> int:
        """ServeCache on-disk spill tier byte cap (0 = spill off)."""
        return max(
            0,
            self.get_int(
                C.SERVE_SPILL_MAX_BYTES, C.SERVE_SPILL_MAX_BYTES_DEFAULT
            ),
        )

    @property
    def serve_spill_orphan_ttl_ms(self) -> int:
        """Lease age after which orphaned spill files are reaped."""
        return max(
            1,
            self.get_int(
                C.SERVE_SPILL_ORPHAN_TTL_MS,
                C.SERVE_SPILL_ORPHAN_TTL_MS_DEFAULT,
            ),
        )

    @property
    def io_mmap_enabled(self) -> bool:
        """Memory-mapped Arrow/parquet reads (io/parquet.py)."""
        return self.get_bool(C.IO_MMAP_ENABLED, C.IO_MMAP_ENABLED_DEFAULT)

    @property
    def serve_max_concurrency(self) -> int:
        """Serve-frontend worker threads (0 = auto-size)."""
        n = self.get_int(
            C.SERVE_MAX_CONCURRENCY, C.SERVE_MAX_CONCURRENCY_DEFAULT
        )
        if n > 0:
            return n
        import os

        return min(32, 4 * (os.cpu_count() or 1))

    @property
    def serve_max_queue_depth(self) -> int:
        return self.get_int(
            C.SERVE_MAX_QUEUE_DEPTH, C.SERVE_MAX_QUEUE_DEPTH_DEFAULT
        )

    @property
    def serve_retry_max_attempts(self) -> int:
        return max(
            1,
            self.get_int(
                C.SERVE_RETRY_MAX_ATTEMPTS, C.SERVE_RETRY_MAX_ATTEMPTS_DEFAULT
            ),
        )

    @property
    def serve_retry_backoff_ms(self) -> int:
        return max(
            0,
            self.get_int(
                C.SERVE_RETRY_BACKOFF_MS, C.SERVE_RETRY_BACKOFF_MS_DEFAULT
            ),
        )

    @property
    def recovery_enabled(self) -> bool:
        """Crash-safe lifecycle recovery (metadata/recovery.py): writer
        leases, stranded-entry rollback, stale-pointer healing, and the
        OCC retry loop in Action.run."""
        return self.get_bool(C.RECOVERY_ENABLED, C.RECOVERY_ENABLED_DEFAULT)

    @property
    def recovery_lease_ms(self) -> int:
        return max(
            1, self.get_int(C.RECOVERY_LEASE_MS, C.RECOVERY_LEASE_MS_DEFAULT)
        )

    @property
    def recovery_orphan_grace_ms(self) -> int:
        return max(
            0,
            self.get_int(
                C.RECOVERY_ORPHAN_GRACE_MS, C.RECOVERY_ORPHAN_GRACE_MS_DEFAULT
            ),
        )

    @property
    def recovery_retry_max_attempts(self) -> int:
        return max(
            1,
            self.get_int(
                C.RECOVERY_RETRY_MAX_ATTEMPTS,
                C.RECOVERY_RETRY_MAX_ATTEMPTS_DEFAULT,
            ),
        )

    @property
    def recovery_retry_backoff_ms(self) -> int:
        return max(
            0,
            self.get_int(
                C.RECOVERY_RETRY_BACKOFF_MS, C.RECOVERY_RETRY_BACKOFF_MS_DEFAULT
            ),
        )

    # -- observability plane (hyperspace_tpu/obs/) ---------------------------
    @property
    def obs_enabled(self) -> bool:
        """Structured tracing + durable query log (docs/observability.md);
        off = the zero-cost no-op path, bit-identical serve behavior."""
        return self.get_bool(C.OBS_ENABLED, C.OBS_ENABLED_DEFAULT)

    @property
    def obs_querylog_enabled(self) -> bool:
        return self.get_bool(
            C.OBS_QUERYLOG_ENABLED, C.OBS_QUERYLOG_ENABLED_DEFAULT
        )

    @property
    def obs_querylog_max_bytes(self) -> int:
        return max(
            1,
            self.get_int(
                C.OBS_QUERYLOG_MAX_BYTES, C.OBS_QUERYLOG_MAX_BYTES_DEFAULT
            ),
        )

    @property
    def obs_querylog_max_files(self) -> int:
        return max(
            1,
            self.get_int(
                C.OBS_QUERYLOG_MAX_FILES, C.OBS_QUERYLOG_MAX_FILES_DEFAULT
            ),
        )

    @property
    def obs_trace_max_spans(self) -> int:
        return max(
            1,
            self.get_int(C.OBS_TRACE_MAX_SPANS, C.OBS_TRACE_MAX_SPANS_DEFAULT),
        )

    @property
    def obs_trace_retain(self) -> int:
        return max(
            1, self.get_int(C.OBS_TRACE_RETAIN, C.OBS_TRACE_RETAIN_DEFAULT)
        )

    @property
    def obs_eventlog_path(self) -> str:
        return self.get_str(C.OBS_EVENTLOG_PATH, C.OBS_EVENTLOG_PATH_DEFAULT)

    @property
    def obs_querylog_record_plans(self) -> bool:
        """Opt-in replayable plan specs in querylog records — specs
        carry literals, unlike the scrubbed predicate shape."""
        return self.get_bool(
            C.OBS_QUERYLOG_RECORD_PLANS, C.OBS_QUERYLOG_RECORD_PLANS_DEFAULT
        )

    # -- workload advisor (hyperspace_tpu/advisor/) --------------------------
    @property
    def advisor_profile_max_shapes(self) -> int:
        return max(
            1,
            self.get_int(
                C.ADVISOR_PROFILE_MAX_SHAPES,
                C.ADVISOR_PROFILE_MAX_SHAPES_DEFAULT,
            ),
        )

    @property
    def advisor_max_candidates(self) -> int:
        return max(
            1,
            self.get_int(
                C.ADVISOR_MAX_CANDIDATES, C.ADVISOR_MAX_CANDIDATES_DEFAULT
            ),
        )

    @property
    def advisor_apply_enabled(self) -> bool:
        return self.get_bool(
            C.ADVISOR_APPLY_ENABLED, C.ADVISOR_APPLY_ENABLED_DEFAULT
        )

    @property
    def advisor_apply_max_bytes(self) -> int:
        return max(
            1,
            self.get_int(
                C.ADVISOR_APPLY_MAX_BYTES, C.ADVISOR_APPLY_MAX_BYTES_DEFAULT
            ),
        )

    @property
    def advisor_apply_max_seconds(self) -> float:
        return max(
            0.0,
            self.get_float(
                C.ADVISOR_APPLY_MAX_SECONDS, C.ADVISOR_APPLY_MAX_SECONDS_DEFAULT
            ),
        )

    # -- replicated serve fleet (serve/fleet.py, serve/bus.py) ---------------
    @property
    def fleet_enabled(self) -> bool:
        """Fleet mode: durable cross-process pins, index-version fanout
        bus, cross-process single-flight (docs/fleet-serve.md)."""
        return self.get_bool(C.FLEET_ENABLED, C.FLEET_ENABLED_DEFAULT)

    @property
    def fleet_pin_lease_ms(self) -> int:
        return max(
            1, self.get_int(C.FLEET_PIN_LEASE_MS, C.FLEET_PIN_LEASE_MS_DEFAULT)
        )

    @property
    def fleet_bus_poll_ms(self) -> int:
        return max(
            1, self.get_int(C.FLEET_BUS_POLL_MS, C.FLEET_BUS_POLL_MS_DEFAULT)
        )

    @property
    def fleet_bus_retain_ms(self) -> int:
        return max(
            0,
            self.get_int(C.FLEET_BUS_RETAIN_MS, C.FLEET_BUS_RETAIN_MS_DEFAULT),
        )

    @property
    def fleet_singleflight_enabled(self) -> bool:
        return self.get_bool(
            C.FLEET_SINGLEFLIGHT_ENABLED, C.FLEET_SINGLEFLIGHT_ENABLED_DEFAULT
        )

    @property
    def fleet_singleflight_wait_ms(self) -> int:
        return max(
            0,
            self.get_int(
                C.FLEET_SINGLEFLIGHT_WAIT_MS,
                C.FLEET_SINGLEFLIGHT_WAIT_MS_DEFAULT,
            ),
        )

    @property
    def fleet_singleflight_claim_ms(self) -> int:
        return max(
            1,
            self.get_int(
                C.FLEET_SINGLEFLIGHT_CLAIM_MS,
                C.FLEET_SINGLEFLIGHT_CLAIM_MS_DEFAULT,
            ),
        )

    @property
    def fleet_spool_max_bytes(self) -> int:
        return max(
            0,
            self.get_int(
                C.FLEET_SPOOL_MAX_BYTES, C.FLEET_SPOOL_MAX_BYTES_DEFAULT
            ),
        )

    # -- fleet fast data plane (serve/fastbus.py, serve/router.py) -----------
    @property
    def fleet_fast_enabled(self) -> bool:
        """Fast data plane over the durable fleet planes: per-host push
        bus + owner routing (docs/fleet-serve.md, "Fast data plane")."""
        return self.get_bool(C.FLEET_FAST_ENABLED, C.FLEET_FAST_ENABLED_DEFAULT)

    @property
    def fleet_fast_routing_enabled(self) -> bool:
        return self.get_bool(
            C.FLEET_FAST_ROUTING_ENABLED, C.FLEET_FAST_ROUTING_ENABLED_DEFAULT
        )

    @property
    def fleet_fast_request_timeout_ms(self) -> int:
        return max(
            1,
            self.get_int(
                C.FLEET_FAST_REQUEST_TIMEOUT_MS,
                C.FLEET_FAST_REQUEST_TIMEOUT_MS_DEFAULT,
            ),
        )

    @property
    def fleet_fast_member_lease_ms(self) -> int:
        return max(
            1,
            self.get_int(
                C.FLEET_FAST_MEMBER_LEASE_MS,
                C.FLEET_FAST_MEMBER_LEASE_MS_DEFAULT,
            ),
        )

    @property
    def fleet_fast_result_cache_bytes(self) -> int:
        return max(
            0,
            self.get_int(
                C.FLEET_FAST_RESULT_CACHE_BYTES,
                C.FLEET_FAST_RESULT_CACHE_BYTES_DEFAULT,
            ),
        )

    @property
    def fleet_fast_gossip_ms(self) -> int:
        return max(
            1,
            self.get_int(
                C.FLEET_FAST_GOSSIP_MS, C.FLEET_FAST_GOSSIP_MS_DEFAULT
            ),
        )

    @property
    def fleet_fast_slo_fleet_wide(self) -> bool:
        return self.get_bool(
            C.FLEET_FAST_SLO_FLEET_WIDE, C.FLEET_FAST_SLO_FLEET_WIDE_DEFAULT
        )

    @property
    def fleet_slo_classes(self) -> dict:
        """``{class name: (max_concurrency, max_queue_depth)}`` from the
        ``hyperspace.fleet.class.<name>.{maxConcurrency,maxQueueDepth}``
        prefix family (0 = unlimited for either bound)."""
        out: dict = {}
        prefix = C.FLEET_CLASS_KEY_PREFIX
        for key, value in self.prefixed(prefix).items():
            name, _, attr = key[len(prefix):].rpartition(".")
            if not name:
                continue
            caps = out.setdefault(name, [0, 0])
            try:
                if attr == "maxConcurrency":
                    caps[0] = max(0, int(value))
                elif attr == "maxQueueDepth":
                    caps[1] = max(0, int(value))
            except (TypeError, ValueError):
                continue
        return {name: (c[0], c[1]) for name, c in out.items()}

    @property
    def serve_pipeline_enabled(self) -> bool:
        return self.get_bool(
            C.SERVE_PIPELINE_ENABLED, C.SERVE_PIPELINE_ENABLED_DEFAULT
        )

    @property
    def serve_rangeprune_enabled(self) -> bool:
        return self.get_bool(
            C.SERVE_RANGEPRUNE_ENABLED, C.SERVE_RANGEPRUNE_ENABLED_DEFAULT
        )

    @property
    def serve_fusedpipeline_enabled(self) -> bool:
        """Fused serve-pipeline compiler: Filter→Project→Aggregate over a
        pruned index scan runs as one native pass per row-group chunk
        (bit-identical to the interpreted chain; False = old path)."""
        return self.get_bool(
            C.SERVE_FUSEDPIPELINE_ENABLED,
            C.SERVE_FUSEDPIPELINE_ENABLED_DEFAULT,
        )

    @property
    def default_supported_formats(self) -> set:
        raw = self.get_str(
            C.DEFAULT_SUPPORTED_FORMATS, C.DEFAULT_SUPPORTED_FORMATS_DEFAULT
        )
        return {s.strip().lower() for s in raw.split(",") if s.strip()}

    @property
    def zorder_target_source_bytes_per_partition(self) -> int:
        return self.get_int(
            C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION,
            C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION_DEFAULT,
        )

    @property
    def zorder_quantile_enabled(self) -> bool:
        return self.get_bool(
            C.ZORDER_QUANTILE_ENABLED, C.ZORDER_QUANTILE_ENABLED_DEFAULT
        )

    @property
    def zorder_quantile_relative_error(self) -> float:
        return self.get_float(
            C.ZORDER_QUANTILE_RELATIVE_ERROR,
            C.ZORDER_QUANTILE_RELATIVE_ERROR_DEFAULT,
        )

    @property
    def dataskipping_target_index_data_file_size(self) -> int:
        return self.get_int(
            C.DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE,
            C.DATASKIPPING_TARGET_INDEX_DATA_FILE_SIZE_DEFAULT,
        )

    @property
    def dataskipping_auto_partition_sketch(self) -> bool:
        return self.get_bool(
            C.DATASKIPPING_AUTO_PARTITION_SKETCH,
            C.DATASKIPPING_AUTO_PARTITION_SKETCH_DEFAULT,
        )


class CacheWithTransform:
    """Caches ``transform(conf)`` until the config is mutated.

    Reference: ``util/CacheWithTransform.scala:45`` — the source-provider
    list is rebuilt only when the backing conf value changes.
    """

    def __init__(self, conf: Config, transform: Callable[[Config], Any]):
        self._conf = conf
        self._transform = transform
        self._cached = None
        self._cached_version = -1

    def load(self) -> Any:
        if self._cached_version != self._conf.version:
            self._cached = self._transform(self._conf)
            self._cached_version = self._conf.version
        return self._cached
